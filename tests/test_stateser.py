"""Snapshot codec safety + round-trip (zeebe_tpu/log/stateser.py).

Snapshots cross the unauthenticated snapshot-replication wire
(``cluster_broker._fetch_snapshots_from_leader``), so decoding must be a
pure data operation: no pickle, nothing executable, malformed input
rejected with SnapshotFormatError. Reference stance: the broker replicates
opaque RocksDB/state files and never deserializes objects from peers
(``broker-core/.../clustering/base/snapshots/SnapshotReplicationService.java``).
"""

import pickle

import numpy as np
import pytest

from zeebe_tpu.engine.interpreter import PartitionEngine, WorkflowRepository
from zeebe_tpu.log import stateser
from zeebe_tpu.log.snapshot import (
    SnapshotController,
    SnapshotMetadata,
    SnapshotStorage,
)
from zeebe_tpu.gateway import ZeebeClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.protocol import msgpack
from zeebe_tpu.runtime import Broker, ControlledClock


@pytest.fixture
def traffic_broker(tmp_path):
    b = Broker(
        num_partitions=1,
        data_dir=str(tmp_path / "data"),
        clock=ControlledClock(start_ms=1_000_000),
    )
    client = ZeebeClient(b)
    model = (
        Bpmn.create_process("order")
        .start_event("start")
        .service_task("task", type="work")
        .end_event("end")
        .done()
    )
    client.deploy_model(model)
    client.create_instance("order", {"a": 1, "s": "x"})
    client.create_instance("order", {"a": 2})
    b.run_until_idle()
    yield b
    b.close()


class TestAwaitingJobsRoundTrip:
    def test_snapshot_encode_decode_restore_round_trip(self):
        """A leader restored from a snapshot must keep the _awaiting_jobs
        drought backlog: dropping it strands every job that became
        activatable while all matching subscriptions were out of credits
        (backlog_activations never revisits them)."""
        from zeebe_tpu.engine.interpreter import (
            JobState,
            JobSubscription,
        )
        from zeebe_tpu.protocol.intents import JobIntent
        from zeebe_tpu.protocol.records import JobRecord

        engine = PartitionEngine(repository=WorkflowRepository())
        engine.jobs[77] = JobState(
            state=int(JobIntent.CREATED),
            record=JobRecord(type="work", retries=3),
            deadline=-1,
        )
        engine.jobs[78] = JobState(
            state=int(JobIntent.CREATED),
            record=JobRecord(type="work", retries=3),
            deadline=-1,
        )
        # drought: both jobs queued awaiting credits, insertion-ordered
        engine._awaiting_jobs = {"work": {77: None, 78: None}}

        payload = stateser.encode_host_state(engine.snapshot_state())
        restored = PartitionEngine(repository=WorkflowRepository())
        restored.restore_state(stateser.decode_host_state(payload))
        assert restored._awaiting_jobs == {"work": {77: None, 78: None}}
        assert list(restored._awaiting_jobs["work"]) == [77, 78]

        # behavioral: a credit arriving after restore drains the backlog
        # (register directly so the subscribe-time job-table scan does not
        # shadow the awaiting-jobs path under test)
        restored.job_subscriptions.append(
            JobSubscription(
                subscriber_key=5, job_type="work", worker="w",
                timeout=1000, credits=1,
            )
        )
        out = restored.backlog_activations()
        assert [r.key for r in out] == [77]

    def test_old_snapshot_without_awaiting_jobs_restores(self):
        """Pre-round-6 snapshots carry no awaiting_jobs field; decode must
        default it instead of failing the restore."""
        engine = PartitionEngine(repository=WorkflowRepository())
        doc = msgpack.unpack(
            stateser.encode_host_state(engine.snapshot_state())
        )
        del doc["awaiting_jobs"]
        state = stateser.decode_host_state(msgpack.pack(doc))
        assert state["awaiting_jobs"] == {}


class TestHostStateRoundTrip:
    def test_round_trip_preserves_replay_equivalence(self, traffic_broker):
        engine = traffic_broker.partitions[0].engine
        state = engine.snapshot_state()
        payload = stateser.encode_state(state)
        assert isinstance(payload, bytes)
        restored = stateser.decode_state(payload)

        fresh = PartitionEngine(
            partition_id=0, num_partitions=1, repository=WorkflowRepository(),
            clock=engine.clock,
        )
        fresh.restore_state(restored)
        # the restored engine serves the same state families
        assert set(fresh.element_instances.instances) == set(
            engine.element_instances.instances
        )
        assert set(fresh.jobs) == set(engine.jobs)
        for k, js in engine.jobs.items():
            assert fresh.jobs[k].state == js.state
            assert fresh.jobs[k].record.to_document() == js.record.to_document()
        assert fresh.last_processed_position == engine.last_processed_position
        # key generators resume where they left off
        assert fresh.wf_keys.peek == engine.wf_keys.peek
        assert fresh.job_keys.peek == engine.job_keys.peek
        # workflows re-transformed from source are executable
        wf = fresh.repository.latest("order")
        assert wf is not None and wf.key == engine.repository.latest("order").key
        assert wf.element_by_id("task").job_type == "work"

    def test_scope_tree_round_trip(self, traffic_broker):
        engine = traffic_broker.partitions[0].engine
        state = engine.snapshot_state()
        restored = stateser.decode_state(stateser.encode_state(state))
        for key, inst in engine.element_instances.instances.items():
            r = restored["element_instances"].get(key)
            assert r is not None
            assert r.state == inst.state
            assert r.active_tokens == inst.active_tokens
            if inst.parent is None:
                assert r.parent is None
            else:
                assert r.parent.key == inst.parent.key
            assert [c.key for c in r.children] == [c.key for c in inst.children]


class TestUntrustedPayloadRejection:
    def test_pickle_payload_rejected_not_executed(self, tmp_path):
        # a malicious peer plants a pickle that would execute on load
        class Boom:
            def __reduce__(self):
                return (pytest.fail, ("pickle payload was executed!",))

        evil = pickle.dumps(Boom())
        storage = SnapshotStorage(str(tmp_path))
        storage.write(SnapshotMetadata(5, 5, 1), evil)
        controller = SnapshotController(storage)
        state, meta = controller.recover(log_last_position=100)
        assert state is None and meta is None

    def test_garbage_bytes_rejected(self):
        with pytest.raises(stateser.SnapshotFormatError):
            stateser.decode_state(b"\x00\x01\x02 garbage")

    def test_wrong_format_tag_rejected(self):
        payload = msgpack.pack({"fmt": "something-else", "data": 1})
        with pytest.raises(stateser.SnapshotFormatError):
            stateser.decode_state(payload)

    def test_truncated_valid_payload_rejected(self, traffic_broker):
        engine = traffic_broker.partitions[0].engine
        payload = stateser.encode_state(engine.snapshot_state())
        with pytest.raises(stateser.SnapshotFormatError):
            stateser.decode_state(payload[: len(payload) // 2])

    def test_malformed_host_fields_rejected(self):
        doc = {"fmt": stateser.FORMAT_HOST_V1, "wf_keys": "nope"}
        with pytest.raises(stateser.SnapshotFormatError):
            stateser.decode_state(msgpack.pack(doc))

    def test_ndarray_dtype_allowlist(self):
        with pytest.raises(stateser.SnapshotFormatError):
            stateser.unpack_ndarray({"__nd": "object", "sh": [1], "b": b"x"})

    def test_ndarray_size_mismatch_rejected(self):
        with pytest.raises(stateser.SnapshotFormatError):
            stateser.unpack_ndarray({"__nd": "int32", "sh": [100], "b": b"\0" * 8})


class TestDeviceEnvelope:
    def test_device_state_round_trip(self):
        arrays = {
            "ei_i32": np.arange(12, dtype=np.int32).reshape(3, 4),
            "flags": np.array([True, False, True]),
            "nums": np.linspace(0, 1, 5),
        }
        state = {
            "fmt": stateser.FORMAT_DEVICE_V1,
            "arrays": arrays,
            "meta": {"num_vars": 8, "capacity": 3},
            "host": None,
        }
        restored = stateser.decode_state(stateser.encode_state(state))
        assert restored["meta"] == {"num_vars": 8, "capacity": 3}
        for name, a in arrays.items():
            np.testing.assert_array_equal(restored["arrays"][name], a)
            assert restored["arrays"][name].dtype == a.dtype

    def test_device_state_with_embedded_host(self, traffic_broker):
        engine = traffic_broker.partitions[0].engine
        state = {
            "fmt": stateser.FORMAT_DEVICE_V1,
            "arrays": {"x": np.ones((2, 2), np.float32)},
            "meta": {},
            "host": engine.snapshot_state(),
        }
        restored = stateser.decode_state(stateser.encode_state(state))
        assert set(restored["host"]["jobs"]) == set(engine.jobs)
