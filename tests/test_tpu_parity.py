"""Event-replay parity: TPU device engine vs host oracle engine.

The correctness contract from BASELINE.json: the device kernel must produce
the same committed record stream as the reference-semantics oracle for the
same commands (SURVEY.md §5 — "the event log IS the trace"). Every scenario
drives both engines through the broker runtime with identical inputs and
compares the full log signature: position, record type, value type, intent,
key, source position, rejection, activity, payload, scope, headers.

Scenarios mirror BASELINE.json's benchmark configs: service-task sequence,
exclusive-gateway split with json-el conditions, parallel fork/join, timer
catch events, plus incident/rejection paths.
"""

import pytest

from zeebe_tpu.engine.interpreter import WorkflowRepository
from zeebe_tpu.gateway import ClientException, JobWorker, ZeebeClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI
from zeebe_tpu.runtime import Broker, ControlledClock
from zeebe_tpu.tpu import TpuPartitionEngine

SIG_TYPES = {
    int(ValueType.WORKFLOW_INSTANCE),
    int(ValueType.JOB),
    int(ValueType.INCIDENT),
    int(ValueType.TIMER),
    int(ValueType.MESSAGE),
    int(ValueType.MESSAGE_SUBSCRIPTION),
    int(ValueType.WORKFLOW_INSTANCE_SUBSCRIPTION),
}


def record_signature(records):
    out = []
    for r in records:
        if int(r.metadata.value_type) not in SIG_TYPES:
            continue
        out.append(
            (
                r.position,
                int(r.metadata.record_type),
                int(r.metadata.value_type),
                int(r.metadata.intent),
                r.key,
                r.source_record_position,
                int(r.metadata.rejection_type),
                r.metadata.rejection_reason,
                getattr(r.value, "activity_id", None) or None,
                dict(getattr(r.value, "payload", {}) or {}),
                getattr(r.value, "scope_instance_key", None),
                getattr(r.value, "workflow_instance_key", None),
                getattr(r.value, "retries", None),
                getattr(r.value, "worker", None),
                getattr(r.value, "error_type", None),
                getattr(r.value, "error_message", None),
                getattr(
                    getattr(r.value, "headers", None), "activity_instance_key", None
                ),
            )
        )
    return out


class DualRig:
    """Runs the same scenario against oracle and TPU brokers."""

    def __init__(self):
        self.brokers = []
        for tpu in (False, True):
            clock = ControlledClock(start_ms=1_000_000)
            if tpu:
                repo = WorkflowRepository()
                broker = Broker(
                    num_partitions=1,
                    clock=clock,
                    engine_factory=lambda pid: TpuPartitionEngine(
                        pid, 1, repository=repo, clock=clock
                    ),
                )
            else:
                broker = Broker(num_partitions=1, clock=clock)
            broker._test_clock = clock
            self.brokers.append(broker)

    def run(self, scenario):
        outcomes = []
        for broker in self.brokers:
            client = ZeebeClient(broker)
            outcomes.append(scenario(broker, client, broker._test_clock))
            broker.run_until_idle()
        return outcomes

    def assert_parity(self):
        oracle = record_signature(self.brokers[0].records(0))
        tpu = record_signature(self.brokers[1].records(0))
        for i, (a, b) in enumerate(zip(oracle, tpu)):
            assert a == b, f"record {i} mismatch:\n  oracle: {a}\n  tpu:    {b}"
        assert len(oracle) == len(tpu), (
            f"record count mismatch: oracle={len(oracle)} tpu={len(tpu)}\n"
            f"oracle tail: {oracle[-4:]}\ntpu tail: {tpu[-4:]}"
        )

    def close(self):
        for broker in self.brokers:
            broker.close()


@pytest.fixture
def rig():
    r = DualRig()
    yield r
    r.close()


def order_process():
    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


def gateway_process():
    b = Bpmn.create_process("decision").start_event("start").exclusive_gateway("split")
    b.branch("$.orderValue >= 100").service_task(
        "high", type="priority-service"
    ).end_event("end-high")
    b.branch(default=True).service_task("low", type="normal-service").end_event(
        "end-low"
    )
    return b.done()


def fork_join_process():
    b = Bpmn.create_process("fork-join").start_event("start").parallel_gateway("fork")
    branch1 = b.branch().service_task("task-a", type="svc-a")
    branch2 = b.branch().service_task("task-b", type="svc-b")
    branch1.parallel_gateway("join")
    branch2.connect_to("join")
    b.move_to("join").end_event("end")
    return b.done()


class TestServiceTaskParity:
    def test_happy_path(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(order_process())
            JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
            client.create_instance(
                "order-process", payload={"orderId": 31243, "orderValue": 99}
            )

        rig.run(scenario)
        rig.assert_parity()

    def test_multiple_instances(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(order_process())
            JobWorker(
                broker, "payment-service", lambda ctx: {"paid": True}, credits=64
            )
            for i in range(10):
                client.create_instance("order-process", payload={"orderId": i})

        rig.run(scenario)
        rig.assert_parity()

    def test_job_fail_and_retry(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(order_process())
            attempts = []

            def handler(ctx):
                attempts.append(1)
                if len(attempts) == 1:
                    ctx.fail(retries=ctx.job.retries - 1)
                    return None
                return {"paid": True}

            JobWorker(broker, "payment-service", handler)
            client.create_instance("order-process", payload={"orderId": 1})

        rig.run(scenario)
        rig.assert_parity()

    def test_job_no_retries_incident(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(order_process())

            def handler(ctx):
                ctx.fail(retries=0)

            JobWorker(broker, "payment-service", handler)
            client.create_instance("order-process", payload={"orderId": 1})

        rig.run(scenario)
        rig.assert_parity()

    def test_job_timeout_reactivation(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(order_process())
            seen = []

            def handler(ctx):
                seen.append(ctx.key)
                if len(seen) == 1:
                    ctx.finished = True  # crashed worker: never completes
                    return None
                return {"paid": True}

            JobWorker(broker, "payment-service", handler, timeout_ms=5_000)
            client.create_instance("order-process", payload={"orderId": 1})
            broker.run_until_idle()
            clock.advance(10_000)
            broker.tick()

        rig.run(scenario)
        rig.assert_parity()

    def test_complete_unknown_job_rejected(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(order_process())
            try:
                client.complete_job(999999)
            except ClientException:
                pass

        rig.run(scenario)
        rig.assert_parity()

    def test_create_unknown_workflow_rejected(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(order_process())
            try:
                client.create_instance("no-such-process")
            except ClientException:
                pass

        rig.run(scenario)
        rig.assert_parity()


class TestExclusiveGatewayParity:
    def test_condition_routes_high(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(gateway_process())
            JobWorker(broker, "priority-service", lambda ctx: None)
            JobWorker(broker, "normal-service", lambda ctx: None)
            client.create_instance("decision", payload={"orderValue": 250})

        rig.run(scenario)
        rig.assert_parity()

    def test_condition_routes_default(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(gateway_process())
            JobWorker(broker, "priority-service", lambda ctx: None)
            JobWorker(broker, "normal-service", lambda ctx: None)
            client.create_instance("decision", payload={"orderValue": 42})

        rig.run(scenario)
        rig.assert_parity()

    def test_condition_error_incident(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(gateway_process())
            client.create_instance("decision", payload={"unrelated": 1})

        rig.run(scenario)
        rig.assert_parity()

    def test_string_and_mixed_conditions(self, rig):
        def scenario(broker, client, clock):
            b = (
                Bpmn.create_process("strings")
                .start_event("start")
                .exclusive_gateway("split")
            )
            b.branch('$.kind == "express" && $.weight < 10').service_task(
                "a", type="svc-a"
            ).end_event("end-a")
            b.branch(default=True).service_task("b", type="svc-b").end_event("end-b")
            client.deploy_model(b.done())
            JobWorker(broker, "svc-a", lambda ctx: None)
            JobWorker(broker, "svc-b", lambda ctx: None)
            client.create_instance("strings", payload={"kind": "express", "weight": 5})
            client.create_instance("strings", payload={"kind": "bulk", "weight": 5})

        rig.run(scenario)
        rig.assert_parity()


class TestParallelGatewayParity:
    def test_fork_join(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(fork_join_process())
            JobWorker(broker, "svc-a", lambda ctx: {"a": 1})
            JobWorker(broker, "svc-b", lambda ctx: {"b": 2})
            client.create_instance("fork-join", payload={"seed": 7})

        rig.run(scenario)
        rig.assert_parity()

    def test_fork_join_many(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(fork_join_process())
            JobWorker(broker, "svc-a", lambda ctx: {"a": 1}, credits=64)
            JobWorker(broker, "svc-b", lambda ctx: {"b": 2}, credits=64)
            for i in range(5):
                client.create_instance("fork-join", payload={"seed": i})

        rig.run(scenario)
        rig.assert_parity()


class TestTimerParity:
    def test_timer_catch_event(self, rig):
        def scenario(broker, client, clock):
            model = (
                Bpmn.create_process("timed")
                .start_event("start")
                .timer_catch_event("wait", duration_ms=60_000)
                .end_event("end")
                .done()
            )
            client.deploy_model(model)
            client.create_instance("timed", payload={"x": 1})
            broker.run_until_idle()
            clock.advance(120_000)
            broker.tick()

        rig.run(scenario)
        rig.assert_parity()


class TestMappingParity:
    def test_io_mappings(self, rig):
        def scenario(broker, client, clock):
            model = (
                Bpmn.create_process("mapped")
                .start_event("start")
                .service_task(
                    "work",
                    type="svc",
                    inputs=[("$.total", "$.amount")],
                    outputs=[("$.result", "$.outcome")],
                )
                .end_event("end")
                .done()
            )
            client.deploy_model(model)
            JobWorker(broker, "svc", lambda ctx: {"result": 41})
            client.create_instance("mapped", payload={"total": 99, "noise": 1})

        rig.run(scenario)
        rig.assert_parity()

    def test_input_mapping_error_incident(self, rig):
        def scenario(broker, client, clock):
            model = (
                Bpmn.create_process("mapped-err")
                .start_event("start")
                .service_task("work", type="svc", inputs=[("$.missing", "$.amount")])
                .end_event("end")
                .done()
            )
            client.deploy_model(model)
            client.create_instance("mapped-err", payload={"total": 99})

        rig.run(scenario)
        rig.assert_parity()


class TestInstanceCounts:
    def test_completion_events_present(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(order_process())
            JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
            client.create_instance("order-process", payload={"v": 1})

        rig.run(scenario)
        for broker in rig.brokers:
            completed = [
                r
                for r in broker.records(0)
                if int(r.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE)
                and int(r.metadata.record_type) == int(RecordType.EVENT)
                and int(r.metadata.intent) == int(WI.ELEMENT_COMPLETED)
                and r.value.activity_id == "order-process"
            ]
            assert len(completed) == 1


class TestPayloadContract:
    """TPU partitions reject (not crash on, not round) payload numbers that
    are not exactly representable in float32 — the device stores payload
    numerics as f32 (state.pack_payload); the reference likewise validates
    msgpack documents at the client API boundary
    (``ClientApiMessageHandler.java:90-165``)."""

    def _tpu_broker(self):
        from tests.conftest import make_tpu_broker

        return make_tpu_broker()

    def test_inexact_float_create_is_rejected(self):
        from zeebe_tpu.protocol.enums import RejectionType

        broker = self._tpu_broker()
        try:
            client = ZeebeClient(broker)
            client.deploy_model(order_process())
            with pytest.raises(ClientException) as err:
                client.create_instance("order-process", {"x": 0.1})
            assert "float32" in str(err.value)
            broker.run_until_idle()
            rejections = [
                r for r in broker.records(0)
                if int(r.metadata.record_type) == int(RecordType.COMMAND_REJECTION)
                and int(r.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE)
            ]
            assert len(rejections) == 1
            assert rejections[0].metadata.rejection_type == RejectionType.BAD_VALUE
        finally:
            broker.close()

    def test_exact_float_passes(self):
        broker = self._tpu_broker()
        try:
            client = ZeebeClient(broker)
            client.deploy_model(order_process())
            client.create_instance("order-process", {"x": 0.25, "n": 1 << 20})
            broker.run_until_idle()
            assert not any(
                int(r.metadata.record_type) == int(RecordType.COMMAND_REJECTION)
                for r in broker.records(0)
            )
        finally:
            broker.close()


class TestHostOnlyFallback:
    """Device-incompatible workflows (nested correlation-key paths here —
    message catches with FLAT keys compile to the device since round 4) run
    on the embedded host oracle of a TPU-backed partition — every deployed
    workflow keeps executing (reference bar: the stream processor serves
    the whole deployed set; `graph.check_device_compatible` decides WHERE
    each one runs)."""

    def _tpu_broker(self):
        from tests.conftest import make_tpu_broker

        return make_tpu_broker()

    def test_mixed_deployment_both_complete(self):
        broker = self._tpu_broker()
        try:
            client = ZeebeClient(broker)
            client.deploy_model(order_process())
            msg_model = (
                Bpmn.create_process("wait-for-msg")
                .start_event("s")
                .message_catch_event(
                    # nested path: no device column form → host-only
                    "wait", message_name="go", correlation_key="$.meta.orderId"
                )
                .end_event("e")
                .done()
            )
            client.deploy_model(msg_model)
            engine = broker.partitions[0].engine
            assert engine._host_only_keys, "nested-path workflow should be host-only"
            assert engine.graph is not None, "device workflow should compile"

            # device workflow completes on the kernel
            worker = JobWorker(broker, "payment-service", lambda ctx: {"ok": True})
            client.create_instance("order-process", {"orderId": 1})
            broker.run_until_idle()
            assert len(worker.handled) == 1

            # host-only workflow completes via message correlation
            client.create_instance("wait-for-msg", {"meta": {"orderId": 7}})
            broker.run_until_idle()
            client.publish_message("go", correlation_key="7")
            broker.run_until_idle()
            completed = [
                r for r in broker.records(0)
                if int(r.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE)
                and int(r.metadata.record_type) == int(RecordType.EVENT)
                and int(r.metadata.intent) == int(WI.ELEMENT_COMPLETED)
                and getattr(r.value, "activity_id", "") in ("order-process", "wait-for-msg")
            ]
            assert {r.value.activity_id for r in completed} == {
                "order-process", "wait-for-msg"
            }
        finally:
            broker.close()

    def test_host_only_workflow_with_service_task(self):
        """Jobs of host-only workflows are served through the embedded host
        oracle's subscriptions (the device sub table only covers device
        jobs) — a worker completes them like on a host partition."""
        broker = self._tpu_broker()
        try:
            client = ZeebeClient(broker)
            model = (
                Bpmn.create_process("msg-then-work")
                .start_event("s")
                .message_catch_event(
                    # nested path keeps this workflow host-only
                    "wait", message_name="go2", correlation_key="$.meta.k"
                )
                .service_task("work", type="late-service")
                .end_event("e")
                .done()
            )
            client.deploy_model(model)
            assert broker.partitions[0].engine._host_only_keys
            worker = JobWorker(broker, "late-service", lambda ctx: {"done": 1})
            client.create_instance("msg-then-work", {"meta": {"k": 5}})
            broker.run_until_idle()
            client.publish_message("go2", correlation_key="5")
            broker.run_until_idle()
            assert len(worker.handled) == 1
            events = [
                (int(r.metadata.intent), getattr(r.value, "activity_id", ""))
                for r in broker.records(0)
                if int(r.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE)
                and int(r.metadata.record_type) == int(RecordType.EVENT)
            ]
            assert (int(WI.ELEMENT_COMPLETED), "msg-then-work") in events
        finally:
            broker.close()

    def test_mixed_deployment_survives_snapshot_restore(self, tmp_path):
        """Snapshot + restart of a mixed (device + host-only) deployment
        preserves the host-only split and workflow slot numbering — the
        regression where restore compiled EVERYTHING into the device graph
        wedged host-only instances at their catch events."""
        from tests.conftest import make_tpu_broker

        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")

        def make_broker():
            return make_tpu_broker(data_dir=data, clock=clock)

        broker = make_broker()
        client = ZeebeClient(broker)
        client.deploy_model(order_process())
        msg_model = (
            Bpmn.create_process("wait-for-msg")
            .start_event("s")
            .message_catch_event(
                # nested path keeps this workflow host-only
                "wait", message_name="go3", correlation_key="$.meta.k")
            .end_event("e")
            .done()
        )
        client.deploy_model(msg_model)
        host_only_before = set(broker.partitions[0].engine._host_only_keys)
        compiled_before = broker.partitions[0].engine._compiled_count
        client.create_instance("wait-for-msg", {"meta": {"k": 9}})
        broker.run_until_idle()
        broker.snapshot()
        broker.close()

        broker = make_broker()
        engine = broker.partitions[0].engine
        assert set(engine._host_only_keys) == host_only_before
        assert engine._compiled_count == compiled_before
        client = ZeebeClient(broker)
        client.publish_message("go3", correlation_key="9")
        broker.run_until_idle()
        completed = [
            r for r in broker.records(0)
            if int(r.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE)
            and int(r.metadata.record_type) == int(RecordType.EVENT)
            and int(r.metadata.intent) == int(WI.ELEMENT_COMPLETED)
            and getattr(r.value, "activity_id", "") == "wait-for-msg"
        ]
        assert completed, "host-only instance must complete after restore"
        # a device workflow still runs on the kernel after restore
        worker = JobWorker(broker, "payment-service", lambda ctx: {"ok": 1})
        client.create_instance("order-process", {"orderId": 3})
        broker.run_until_idle()
        assert len(worker.handled) == 1
        broker.close()

    def test_cancel_host_only_instance(self):
        """CANCEL carries no workflow key — routing must recognize the
        host-side instance by key (regression: it went to the device
        kernel and vanished without a response)."""
        broker = self._tpu_broker()
        try:
            client = ZeebeClient(broker)
            client.deploy_model(order_process())  # device workflow too
            msg_model = (
                Bpmn.create_process("cancellable")
                .start_event("s")
                .message_catch_event(
                    # nested path keeps this workflow host-only
                    "w", message_name="m9", correlation_key="$.meta.k")
                .end_event("e")
                .done()
            )
            client.deploy_model(msg_model)
            inst = client.create_instance("cancellable", {"meta": {"k": 1}})
            broker.run_until_idle()
            client.cancel_instance(inst.workflow_instance_key)
            broker.run_until_idle()
            canceled = [
                r for r in broker.records(0)
                if int(r.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE)
                and int(r.metadata.intent) == int(WI.ELEMENT_TERMINATED)
            ]
            assert canceled
        finally:
            broker.close()


def receive_task_process():
    return (
        Bpmn.create_process("msgflow")
        .start_event("start")
        .receive_task("wait", message_name="paid", correlation_key="$.oid")
        .end_event("done")
        .done()
    )


def catch_event_process():
    return (
        Bpmn.create_process("catchflow")
        .start_event("start")
        .message_catch_event("gate", message_name="go", correlation_key="$.key")
        .service_task("after", type="post-service")
        .end_event("end")
        .done()
    )


class TestMessageCorrelationParity:
    """Round 4: message catch/receive compile to the device — subscription
    open, publish correlate, stored-message TTL, close — and the full log
    must stay bit-identical to the oracle (reference
    SubscriptionCommandSender.java:96-108,
    WorkflowInstanceStreamProcessor.java:455-509)."""

    def test_open_then_publish(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.create_instance("msgflow", {"oid": "o-7"})
            broker.run_until_idle()
            client.publish_message("paid", "o-7", {"paid": True})

        rig.run(scenario)
        rig.assert_parity()

    def test_publish_before_open_with_ttl(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.publish_message(
                "paid", "o-1", {"amount": 5}, time_to_live_ms=60_000
            )
            broker.run_until_idle()
            client.create_instance("msgflow", {"oid": "o-1"})

        rig.run(scenario)
        rig.assert_parity()

    def test_publish_without_ttl_does_not_store(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.publish_message("paid", "o-2", {"x": 1})  # no subscriber
            broker.run_until_idle()
            client.create_instance("msgflow", {"oid": "o-2"})
            broker.run_until_idle()
            # instance still waiting: publish again, now correlates
            client.publish_message("paid", "o-2", {"x": 2})

        rig.run(scenario)
        rig.assert_parity()

    def test_ttl_expiry_deletes_stored_message(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.publish_message("paid", "late", {"v": 1}, time_to_live_ms=5_000)
            broker.run_until_idle()
            clock.advance(6_000)
            broker.tick()
            broker.run_until_idle()
            # a subscriber arriving after expiry waits (no stored message)
            client.create_instance("msgflow", {"oid": "late"})

        rig.run(scenario)
        rig.assert_parity()

    def test_message_catch_event_with_downstream_task(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(catch_event_process())
            JobWorker(broker, "post-service", lambda ctx: {"done": 1})
            client.create_instance("catchflow", {"key": "k-1"})
            broker.run_until_idle()
            client.publish_message("go", "k-1", {"approved": True})

        rig.run(scenario)
        rig.assert_parity()

    def test_numeric_correlation_key(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.create_instance("msgflow", {"oid": 42})
            broker.run_until_idle()
            client.publish_message("paid", "42", {"ok": True})

        rig.run(scenario)
        rig.assert_parity()

    def test_duplicate_message_id_rejected(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.publish_message(
                "paid", "dup", {"n": 1}, time_to_live_ms=60_000, message_id="m-1"
            )
            broker.run_until_idle()
            try:
                client.publish_message(
                    "paid", "dup", {"n": 2}, time_to_live_ms=60_000, message_id="m-1"
                )
            except ClientException:
                pass

        rig.run(scenario)
        rig.assert_parity()

    def test_cancel_closes_subscription(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            inst = client.create_instance("msgflow", {"oid": "c-1"})
            broker.run_until_idle()
            client.cancel_instance(inst.workflow_instance_key)
            broker.run_until_idle()
            # late publish: subscription is closed, message stores (TTL)
            client.publish_message("paid", "c-1", {"late": 1}, time_to_live_ms=9_000)

        rig.run(scenario)
        rig.assert_parity()

    def test_two_instances_distinct_keys(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.create_instance("msgflow", {"oid": "a"})
            client.create_instance("msgflow", {"oid": "b"})
            broker.run_until_idle()
            client.publish_message("paid", "b", {"who": "b"})
            broker.run_until_idle()
            client.publish_message("paid", "a", {"who": "a"})

        rig.run(scenario)
        rig.assert_parity()

    def test_correlation_key_missing_raises_incident(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.create_instance("msgflow", {"other": 1})  # no oid var

        rig.run(scenario)
        rig.assert_parity()

    def test_float_correlation_key_raises_incident(self, rig):
        # oracle accepts (str, int) only; floats incident on both engines
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.create_instance("msgflow", {"oid": 1.5})

        rig.run(scenario)
        rig.assert_parity()

    def test_bool_correlation_key_subscribes(self, rig):
        # bool IS an int to the oracle — both engines subscribe with "True"
        def scenario(broker, client, clock):
            client.deploy_model(receive_task_process())
            client.create_instance("msgflow", {"oid": True})
            broker.run_until_idle()
            client.publish_message("paid", "True", {"ok": 1})

        rig.run(scenario)
        rig.assert_parity()


class TestMessageStoreLimits:
    """The device message store keys ONE live slot per (name, correlation)
    composite. Workloads exceeding that (two instances waiting on the same
    key, two buffered messages with the same key) REJECT the extra record
    with an explicit reason — a documented capability divergence from the
    oracle that degrades per-record instead of crashing the partition."""

    def _tpu_broker(self):
        from tests.conftest import make_tpu_broker

        return make_tpu_broker()

    def test_second_subscription_same_key_rejected_partition_survives(self):
        broker = self._tpu_broker()
        try:
            client = ZeebeClient(broker)
            client.deploy_model(receive_task_process())
            client.create_instance("msgflow", {"oid": "same"})
            client.create_instance("msgflow", {"oid": "same"})
            broker.run_until_idle()
            rejections = [
                r for r in broker.records(0)
                if int(r.metadata.record_type) == int(RecordType.COMMAND_REJECTION)
                and "already open" in (r.metadata.rejection_reason or "")
            ]
            assert rejections, "second OPEN must reject with a reason"
            # the partition keeps serving: first instance still correlates
            client.publish_message("paid", "same", {"ok": 1})
            broker.run_until_idle()
            completed = [
                r for r in broker.records(0)
                if int(r.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE)
                and int(r.metadata.intent) == int(WI.ELEMENT_COMPLETED)
                and getattr(r.value, "activity_id", "") == "msgflow"
            ]
            assert len(completed) == 1
        finally:
            broker.close()

    def test_second_stored_message_same_key_rejected(self):
        broker = self._tpu_broker()
        try:
            client = ZeebeClient(broker)
            client.deploy_model(receive_task_process())
            client.publish_message("paid", "k", {"n": 1}, time_to_live_ms=60_000)
            try:
                client.publish_message(
                    "paid", "k", {"n": 2}, time_to_live_ms=60_000
                )
                raise AssertionError("second TTL store should reject")
            except ClientException as e:
                assert "already stored" in str(e)
            # the stored first message still correlates a late subscriber
            client.create_instance("msgflow", {"oid": "k"})
            broker.run_until_idle()
            completed = [
                r for r in broker.records(0)
                if int(r.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE)
                and int(r.metadata.intent) == int(WI.ELEMENT_COMPLETED)
                and getattr(r.value, "activity_id", "") == "msgflow"
            ]
            assert len(completed) == 1
        finally:
            broker.close()


def boundary_timer_process(interrupting=True):
    return (
        Bpmn.create_process("bdflow")
        .start_event("start")
        .service_task("slow", type="slow-service")
        .boundary_event("deadline", duration_ms=30_000, interrupting=interrupting)
        .service_task("escalate", type="esc-service")
        .end_event("late-end")
        .move_to("slow")
        .end_event("end")
        .done()
    )


def boundary_message_process(interrupting=True):
    return (
        Bpmn.create_process("bdmsg")
        .start_event("start")
        .service_task("work", type="work-service")
        .boundary_event(
            "stop", message_name="halt", correlation_key="$.wid",
            interrupting=interrupting,
        )
        .end_event("halted")
        .move_to("work")
        .end_event("end")
        .done()
    )


def mi_cardinality_process(cardinality=3):
    builder = Bpmn.create_process("miflow")
    sub = builder.start_event("start").sub_process(
        "each", multi_instance={"cardinality": cardinality}
    )
    sub.start_event("s").service_task("work", type="mi-service").end_event("e")
    return sub.embedded_done().end_event("done").done()


class TestBoundaryEventParity:
    """Round 4: timer and message boundary events on tasks compile to the
    device — arming, disarming, interrupting termination (job cancel +
    continuation at the boundary), non-interrupting token fan-out — with
    logs bit-identical to the oracle (reference model BoundaryEvent.java;
    the reference engine never executes it)."""

    def test_interrupting_timer_fires(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(boundary_timer_process())
            done = []
            JobWorker(broker, "esc-service", lambda ctx: done.append(1) or {})
            # no slow-service worker: the job stays out; the timer wins
            client.create_instance("bdflow", {"orderId": 1})
            broker.run_until_idle()
            clock.advance(31_000)
            broker.tick()
            broker.run_until_idle()

        rig.run(scenario)
        rig.assert_parity()

    def test_interrupting_timer_beaten_by_completion(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(boundary_timer_process())
            JobWorker(broker, "slow-service", lambda ctx: {"done": True})
            client.create_instance("bdflow", {"orderId": 2})
            broker.run_until_idle()
            clock.advance(31_000)
            broker.tick()
            broker.run_until_idle()

        rig.run(scenario)
        rig.assert_parity()

    def test_non_interrupting_timer_fires_host_continues(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(boundary_timer_process(interrupting=False))
            done = []
            JobWorker(broker, "esc-service", lambda ctx: done.append(1) or {})
            client.create_instance("bdflow", {"orderId": 3})
            broker.run_until_idle()
            clock.advance(31_000)
            broker.tick()
            broker.run_until_idle()
            # the host task is still live after the boundary fired —
            # completing it now finishes the instance
            JobWorker(broker, "slow-service", lambda ctx: {"late": True})
            broker.run_until_idle()

        rig.run(scenario)
        rig.assert_parity()

    def test_interrupting_message_boundary(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(boundary_message_process())
            client.create_instance("bdmsg", {"wid": "w-1"})
            broker.run_until_idle()
            client.publish_message("halt", "w-1", {"reason": "stop"})
            broker.run_until_idle()

        rig.run(scenario)
        rig.assert_parity()

    def test_message_boundary_disarms_on_completion(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(boundary_message_process())
            JobWorker(broker, "work-service", lambda ctx: {"ok": 1})
            client.create_instance("bdmsg", {"wid": "w-2"})
            broker.run_until_idle()
            # late publish: the subscription is closed, message buffers
            client.publish_message("halt", "w-2", {"late": 1}, time_to_live_ms=5_000)
            broker.run_until_idle()

        rig.run(scenario)
        rig.assert_parity()

    def test_receive_task_with_timer_boundary_config4(self, rig):
        """The BASELINE config-4 shape: message catch + interrupting timer
        deadline — half the instances correlate, half expire."""
        def scenario(broker, client, clock):
            model = (
                Bpmn.create_process("c4")
                .start_event("start")
                .receive_task("wait-pay", message_name="paid",
                              correlation_key="$.oid")
                .boundary_event("deadline", duration_ms=30_000)
                .end_event("expired")
                .move_to("wait-pay")
                .end_event("done")
                .done()
            )
            client.deploy_model(model)
            for i in range(6):
                client.create_instance("c4", {"oid": f"o-{i}"})
            broker.run_until_idle()
            for i in range(0, 6, 2):
                client.publish_message("paid", f"o-{i}", {"paid": True})
            broker.run_until_idle()
            clock.advance(31_000)
            broker.tick()
            broker.run_until_idle()

        rig.run(scenario)
        rig.assert_parity()


class TestMultiInstanceParity:
    """Round 4: cardinality-based multi-instance sub-processes fan out on
    the device (collection-driven MI keeps the host path — collections
    have no columnar form)."""

    def test_cardinality_fanout_completes(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(mi_cardinality_process(3))
            seen = []
            JobWorker(
                broker, "mi-service",
                lambda ctx: seen.append(ctx.job.payload.get("loopCounter")) or {},
                credits=16,
            )
            client.create_instance("miflow", {"batch": 7})

        rig.run(scenario)
        rig.assert_parity()

    def test_two_instances_interleaved(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(mi_cardinality_process(2))
            JobWorker(broker, "mi-service", lambda ctx: {}, credits=16)
            client.create_instance("miflow", {"a": 1})
            client.create_instance("miflow", {"a": 2})

        rig.run(scenario)
        rig.assert_parity()

    def test_collection_mi_stays_host_side(self):
        from tests.conftest import make_tpu_broker

        broker = make_tpu_broker()
        try:
            client = ZeebeClient(broker)
            builder = Bpmn.create_process("coll")
            sub = builder.start_event("s").sub_process(
                "each", multi_instance={"input_collection": "$.items",
                                        "input_element": "item"}
            )
            sub.start_event("ss").service_task("w", type="c-svc").end_event("se")
            client.deploy_model(sub.embedded_done().end_event("e").done())
            assert broker.partitions[0].engine._host_only_keys
            seen = []
            JobWorker(
                broker, "c-svc",
                lambda ctx: seen.append(ctx.job.payload["item"]) or {},
            )
            client.create_instance("coll", {"items": ["x", "y"]})
            broker.run_until_idle()
            assert sorted(seen) == ["x", "y"]
        finally:
            broker.close()


def dual_boundary_process():
    """Receive task with BOTH an interrupting message boundary and a timer
    boundary — the terminate-catch path must re-scan timers exactly like
    the oracle (two CANCEL commands for the armed timer: disarm + the
    terminate-catch scan)."""
    return (
        Bpmn.create_process("dual")
        .start_event("start")
        .receive_task("wait", message_name="main", correlation_key="$.cid")
        .boundary_event(
            "abort", message_name="abort", correlation_key="$.cid",
            interrupting=True,
        )
        .end_event("aborted")
        .move_to("wait")
        .boundary_event("late", duration_ms=60_000)
        .end_event("timed-out")
        .move_to("wait")
        .end_event("done")
        .done()
    )


class TestDualBoundaryParity:
    def test_message_boundary_fires_while_timer_armed(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(dual_boundary_process())
            client.create_instance("dual", {"cid": "c-1"})
            broker.run_until_idle()
            client.publish_message("abort", "c-1", {"why": "stop"})
            broker.run_until_idle()

        rig.run(scenario)
        rig.assert_parity()

    def test_timer_fires_while_message_boundary_armed(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(dual_boundary_process())
            client.create_instance("dual", {"cid": "c-2"})
            broker.run_until_idle()
            clock.advance(61_000)
            broker.tick()
            broker.run_until_idle()

        rig.run(scenario)
        rig.assert_parity()

    def test_main_message_wins_disarms_both(self, rig):
        def scenario(broker, client, clock):
            client.deploy_model(dual_boundary_process())
            client.create_instance("dual", {"cid": "c-3"})
            broker.run_until_idle()
            client.publish_message("main", "c-3", {"ok": 1})
            broker.run_until_idle()
            clock.advance(61_000)
            broker.tick()
            broker.run_until_idle()

        rig.run(scenario)
        rig.assert_parity()
