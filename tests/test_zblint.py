"""zblint suite tests: every rule proves it fires on its motivating bug
class (positive), stays quiet on the sanctioned idiom (negative), and
honors inline suppression; plus baseline ratchet semantics, the live-tree
pin, and the seeded-historical-bug gate proof from the issue's acceptance
list.
"""

import json
import os
import textwrap

import pytest

from tools.zblint import BASELINE_PATH, RULES, lint, lint_source
from tools.zblint.engine import (
    FileCtx,
    Finding,
    apply_baseline,
    load_baseline,
    write_baseline,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return {f.rule for f in findings}


def src(text):
    return textwrap.dedent(text).lstrip("\n")


# -- unobserved-actor-future -------------------------------------------------

class TestUnobservedActorFuture:
    RULE = "unobserved-actor-future"

    def test_discarded_submit_actor_fires(self):
        findings = lint_source(src("""
            def boot(scheduler, actor):
                scheduler.submit_actor(actor)
        """), rules=[self.RULE])
        assert [f.rule for f in findings] == [self.RULE]
        assert findings[0].line == 2

    def test_discarded_raft_append_fires(self):
        # the historical bug: acked-means-committed made a discarded
        # append future the only trace of dropped records
        findings = lint_source(src("""
            class PartitionServer:
                def tick(self, commands):
                    self.raft.append(commands)
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_assigned_future_is_quiet(self):
        findings = lint_source(src("""
            def boot(scheduler, actor):
                fut = scheduler.submit_actor(actor)
                return fut
        """), rules=[self.RULE])
        assert findings == []

    def test_observed_future_is_quiet(self):
        findings = lint_source(src("""
            def boot(scheduler, actor, cb):
                scheduler.submit_actor(actor).on_complete(cb)
        """), rules=[self.RULE])
        assert findings == []

    def test_inferred_return_type_fires(self):
        findings = lint_source(src("""
            from zeebe_tpu.runtime.actors import ActorFuture

            def enqueue_probe() -> ActorFuture:
                pass

            def caller():
                enqueue_probe()
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}
        assert findings[0].line == 7

    def test_list_append_is_quiet(self):
        findings = lint_source(src("""
            def collect(items, x):
                items.append(x)
        """), rules=[self.RULE])
        assert findings == []

    def test_suppression(self):
        findings = lint_source(src("""
            def boot(scheduler, actor):
                scheduler.submit_actor(actor)  # zblint: disable=unobserved-actor-future (boot)
        """), rules=[self.RULE])
        assert findings == []


# -- actor-thread-blocking ---------------------------------------------------

class TestActorThreadBlocking:
    RULE = "actor-thread-blocking"

    def test_sleep_reachable_from_lifecycle_hook_fires(self):
        findings = lint_source(src("""
            import time

            class A:
                def on_actor_started(self):
                    self._pump()

                def _pump(self):
                    time.sleep(1)
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_fsync_reachable_from_dispatched_method_fires(self):
        findings = lint_source(src("""
            import os

            class A:
                def kick(self):
                    self.actor.run(self._work)

                def _work(self):
                    os.fsync(3)
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_thread_target_body_is_quiet(self):
        # a nested function handed to threading.Thread is NOT actor context
        findings = lint_source(src("""
            import threading
            import time

            class A:
                def on_actor_started(self):
                    def drain():
                        time.sleep(1)
                    threading.Thread(target=drain, daemon=True).start()
        """), rules=[self.RULE])
        assert findings == []

    def test_str_join_is_quiet(self):
        findings = lint_source(src("""
            class A:
                def on_actor_started(self):
                    return ",".join(["a", "b"])
        """), rules=[self.RULE])
        assert findings == []

    def test_suppression(self):
        findings = lint_source(src("""
            import os

            class A:
                def on_actor_started(self):
                    # zblint: disable=actor-thread-blocking (durability)
                    os.fsync(3)
        """), rules=[self.RULE])
        assert findings == []


# -- metrics-hot-loop --------------------------------------------------------

class TestMetricsHotLoop:
    RULE = "metrics-hot-loop"

    def test_count_event_in_loop_fires(self):
        findings = lint_source(src("""
            from zeebe_tpu.runtime.metrics import count_event

            def drain(records):
                for r in records:
                    count_event("records_seen")
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_registry_lookup_in_loop_fires(self):
        findings = lint_source(src("""
            def publish(registry, load):
                for idx, n in load.items():
                    registry.gauge("device_load", device=str(idx)).set(n)
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_cached_handle_miss_guard_is_quiet(self):
        findings = lint_source(src("""
            def publish(registry, load, cache):
                for idx, n in load.items():
                    handle = cache.get(idx)
                    if handle is None:
                        handle = registry.gauge("device_load", device=str(idx))
                        cache[idx] = handle
                    handle.set(n)
        """), rules=[self.RULE])
        assert findings == []

    def test_except_handler_path_is_quiet(self):
        findings = lint_source(src("""
            from zeebe_tpu.runtime.metrics import count_event

            def drain(records, apply):
                for r in records:
                    try:
                        apply(r)
                    except ValueError:
                        count_event("apply_failures")
        """), rules=[self.RULE])
        assert findings == []

    def test_outside_loop_is_quiet(self):
        findings = lint_source(src("""
            from zeebe_tpu.runtime.metrics import count_event

            def drain(records):
                count_event("drains", delta=len(records))
        """), rules=[self.RULE])
        assert findings == []


# -- metrics-doc-drift -------------------------------------------------------

class TestMetricsDocDrift:
    RULE = "metrics-doc-drift"

    @staticmethod
    def _tree(tmp_path, code, doc):
        pkg = tmp_path / "zeebe_tpu"
        pkg.mkdir()
        (pkg / "m.py").write_text(code)
        docs = tmp_path / "docs"
        docs.mkdir()
        (docs / "metrics.md").write_text(doc)
        return str(tmp_path)

    def test_both_directions_fire(self, tmp_path):
        root = self._tree(
            tmp_path,
            'from x import count_event\ncount_event("undocumented_series")\n',
            "| `zb_ghost_series` | counter | gone |\n",
        )
        findings, _, _ = lint(root, rules=[self.RULE], roots=("zeebe_tpu",))
        messages = " ".join(f.message for f in findings)
        assert "zb_undocumented_series" in messages
        assert "zb_ghost_series" in messages

    def test_documented_metric_is_quiet(self, tmp_path):
        root = self._tree(
            tmp_path,
            'from x import count_event\ncount_event("good_series")\n',
            "`zb_good_series` counts good things\n",
        )
        findings, _, _ = lint(root, rules=[self.RULE], roots=("zeebe_tpu",))
        assert findings == []

    def test_ifexp_names_register_both_branches(self, tmp_path):
        # the STATE.md false positive: conditional metric names
        root = self._tree(
            tmp_path,
            'from x import count_event\n'
            'count_event("delta_takes" if True else "full_takes")\n',
            "`zb_delta_takes` / `zb_full_takes` by kind\n",
        )
        findings, _, _ = lint(root, rules=[self.RULE], roots=("zeebe_tpu",))
        assert findings == []

    def test_histogram_suffixes_match_base_series(self, tmp_path):
        root = self._tree(
            tmp_path,
            'from x import count_event\ncount_event("latency_ms")\n',
            "`zb_latency_ms_bucket` and `zb_latency_ms_sum` rows\n",
        )
        findings, _, _ = lint(root, rules=[self.RULE], roots=("zeebe_tpu",))
        assert findings == []


# -- dirty-family-audit ------------------------------------------------------

class TestDirtyFamilyAudit:
    RULE = "dirty-family-audit"

    def test_unmarked_table_mutation_fires(self):
        # `jobs` is a HOST_FAMILIES table; TestEngine participates in
        # dirty tracking, but mutate() is reachable from no marking method
        findings = lint_source(src("""
            class TestEngine:
                def process(self, record):
                    self.snapshot_mark_dirty(("jobs",))

                def sweep(self, key):
                    self.jobs.pop(key, None)
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}
        assert "self.jobs" in findings[0].message

    def test_mutation_reachable_from_marker_is_quiet(self):
        findings = lint_source(src("""
            class TestEngine:
                def process(self, record):
                    self.snapshot_mark_dirty(("jobs",))
                    self._apply(record)

                def _apply(self, record):
                    self.jobs.pop(record.key, None)
        """), rules=[self.RULE])
        assert findings == []

    def test_dispatch_table_edge_is_quiet(self):
        # the interpreter idiom: process() marks, then dispatches through
        # a class-level handler table
        findings = lint_source(src("""
            class TestEngine:
                def process(self, record):
                    self.snapshot_mark_dirty(("jobs",))
                    self._HANDLERS[record.kind](self, record)

                def _handle_job(self, record):
                    self.jobs.pop(record.key, None)

                _HANDLERS = {"job": _handle_job}
        """), rules=[self.RULE])
        assert findings == []

    def test_non_tracking_class_is_quiet(self):
        findings = lint_source(src("""
            class Cache:
                def sweep(self, key):
                    self.jobs.pop(key, None)
        """), rules=[self.RULE])
        assert findings == []

    def test_init_is_exempt(self):
        findings = lint_source(src("""
            class TestEngine:
                def __init__(self):
                    self.jobs = {}

                def process(self, record):
                    self.snapshot_mark_dirty(("jobs",))
        """), rules=[self.RULE])
        assert findings == []


# -- swallowed-exception -----------------------------------------------------

class TestSwallowedException:
    RULE = "swallowed-exception"

    def test_silent_broad_except_fires(self):
        findings = lint_source(src("""
            def f(x):
                try:
                    return x()
                except Exception:
                    pass
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_bare_except_fires(self):
        findings = lint_source(src("""
            def f(x):
                try:
                    return x()
                except:
                    pass
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_logging_handler_is_quiet(self):
        findings = lint_source(src("""
            import logging

            def f(x):
                try:
                    return x()
                except Exception:
                    logging.getLogger(__name__).warning("boom")
        """), rules=[self.RULE])
        assert findings == []

    def test_narrow_except_is_quiet(self):
        findings = lint_source(src("""
            def f(d, k):
                try:
                    return d[k]
                except KeyError:
                    return None
        """), rules=[self.RULE])
        assert findings == []

    def test_stashed_exception_is_quiet(self):
        # deferred re-raise past a loop observes the exception
        findings = lint_source(src("""
            def f(x):
                error = None
                try:
                    x()
                except Exception as e:
                    error = e
                return error
        """), rules=[self.RULE])
        assert findings == []

    def test_suppression(self):
        findings = lint_source(src("""
            def f(x):
                try:
                    return x()
                except Exception:  # zblint: disable=swallowed-exception (why)
                    pass
        """), rules=[self.RULE])
        assert findings == []


# -- undefined-name (ex-nameslint) -------------------------------------------

class TestUndefinedName:
    RULE = "undefined-name"

    def test_undefined_global_fires(self):
        # the round-4 class: referenced on a rarely-run path, defined nowhere
        findings = lint_source(src("""
            def tick():
                return _due_probe_jit()
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}
        assert "_due_probe_jit" in findings[0].message

    def test_defined_global_is_quiet(self):
        findings = lint_source(src("""
            def _due_probe_jit():
                return 1

            def tick():
                return _due_probe_jit()
        """), rules=[self.RULE])
        assert findings == []

    def test_nameslint_shim_still_works(self):
        import tools.nameslint as shim

        assert shim.main([]) == 0


# -- jit-registry ------------------------------------------------------------

class TestJitRegistry:
    RULE = "jit-registry"

    def test_bare_jax_jit_call_fires(self):
        findings = lint_source(src("""
            import jax

            step = jax.jit(lambda s: s)
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}
        assert "register_jit" in findings[0].message

    def test_jit_decorator_fires(self):
        findings = lint_source(src("""
            import jax

            @jax.jit
            def step(s):
                return s
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_partial_jit_decorator_fires(self):
        findings = lint_source(src("""
            from functools import partial
            import jax

            @partial(jax.jit, static_argnames=("n",))
            def step(s, n):
                return s
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_from_import_alias_fires(self):
        findings = lint_source(src("""
            from jax import jit

            step = jit(lambda s: s)
        """), rules=[self.RULE])
        assert rules_of(findings) == {self.RULE}

    def test_register_jit_is_quiet(self):
        findings = lint_source(src("""
            from zeebe_tpu.tpu import jit_registry

            step = jit_registry.register_jit("m.step", lambda s: s)
        """), rules=[self.RULE])
        assert findings == []

    def test_registry_module_is_exempt(self):
        findings = lint_source(src("""
            import jax

            jitted = jax.jit(lambda s: s)
        """), path="zeebe_tpu/tpu/jit_registry.py", rules=[self.RULE])
        assert findings == []

    def test_outside_package_is_quiet(self):
        findings = lint_source(src("""
            import jax

            probe = jax.jit(lambda s: s)
        """), path="benchmarks/probe.py", rules=[self.RULE])
        assert findings == []

    def test_inline_disable(self):
        findings = lint_source(src("""
            import jax

            probe = jax.jit(lambda s: s)  # zblint: disable=jit-registry
        """), rules=[self.RULE])
        assert findings == []

    def test_one_finding_per_site(self):
        # the Call and its Attribute func must not double-report
        findings = lint_source(src("""
            import jax

            a = jax.jit(lambda s: s)
            b = jax.jit(lambda s: s)
        """), rules=[self.RULE])
        assert len(findings) == 2

    def test_jax_numpy_jit_free_code_is_quiet(self):
        findings = lint_source(src("""
            import jax.numpy as jnp

            def step(s):
                return jnp.sum(s)
        """), rules=[self.RULE])
        assert findings == []


# -- suppression mechanics ---------------------------------------------------

class TestSuppression:
    def test_comment_line_above(self):
        findings = lint_source(src("""
            def boot(scheduler, actor):
                # zblint: disable=unobserved-actor-future (boot)
                scheduler.submit_actor(actor)
        """), rules=["unobserved-actor-future"])
        assert findings == []

    def test_disable_all(self):
        findings = lint_source(src("""
            def boot(scheduler, actor):
                scheduler.submit_actor(actor)  # zblint: disable=all
        """), rules=["unobserved-actor-future", "undefined-name"])
        assert findings == []

    def test_unrelated_rule_does_not_suppress(self):
        findings = lint_source(src("""
            def boot(scheduler, actor):
                scheduler.submit_actor(actor)  # zblint: disable=metrics-hot-loop
        """), rules=["unobserved-actor-future"])
        assert len(findings) == 1


# -- baseline ratchet --------------------------------------------------------

class TestBaseline:
    def test_round_trip_and_counts(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        f1 = Finding("swallowed-exception", "a.py", 10, "msg")
        f2 = Finding("swallowed-exception", "a.py", 20, "msg")
        write_baseline(path, [f1, f2])
        baseline = load_baseline(path)
        assert baseline == {"a.py::swallowed-exception::msg": 2}

    def test_grandfathers_up_to_count_then_surfaces(self):
        baseline = {"a.py::r::m": 1}
        f1, f2 = Finding("r", "a.py", 1, "m"), Finding("r", "a.py", 2, "m")
        surfaced, baselined = apply_baseline([f1, f2], baseline)
        assert baselined == 1
        assert surfaced == [f2]

    def test_keys_survive_line_churn(self):
        # baseline keys carry no line numbers by design
        baseline = {"a.py::r::m": 1}
        moved = Finding("r", "a.py", 999, "m")
        surfaced, baselined = apply_baseline([moved], baseline)
        assert surfaced == [] and baselined == 1

    def test_checked_in_baseline_is_valid(self):
        path = os.path.join(REPO_ROOT, BASELINE_PATH)
        with open(path) as f:
            doc = json.load(f)
        assert doc["version"] == 1
        assert doc["entries"], "empty baseline should just be deleted"
        for key in doc["entries"]:
            rule = key.split("::")[1]
            assert rule in RULES, f"baseline entry for unknown rule {rule}"


# -- the gate itself ---------------------------------------------------------

class TestGate:
    def test_live_tree_is_clean(self):
        """The pin the whole PR stands on: repo lints clean after baseline."""
        baseline = load_baseline(os.path.join(REPO_ROOT, BASELINE_PATH))
        surfaced, _baselined, files = lint(REPO_ROOT, baseline=baseline)
        assert files > 100
        assert surfaced == [], "\n".join(f.render() for f in surfaced)

    def test_seeded_historical_bug_fails_the_gate(self, tmp_path):
        """Acceptance proof: re-introducing the unobserved raft.append bug
        in a scratch tree makes the gate fail."""
        pkg = tmp_path / "zeebe_tpu"
        pkg.mkdir()
        (pkg / "broker.py").write_text(src("""
            class PartitionServer:
                def tick(self, commands):
                    if commands:
                        self.raft.append(commands)
        """))
        surfaced, _, _ = lint(str(tmp_path), roots=("zeebe_tpu",))
        assert "unobserved-actor-future" in rules_of(surfaced)

    def test_parse_error_surfaces(self, tmp_path):
        pkg = tmp_path / "zeebe_tpu"
        pkg.mkdir()
        (pkg / "broken.py").write_text("def f(:\n")
        surfaced, _, _ = lint(str(tmp_path), roots=("zeebe_tpu",))
        assert rules_of(surfaced) == {"parse-error"}

    def test_json_cli_shape(self, tmp_path, capsys):
        from tools.zblint.__main__ import main as zblint_main

        pkg = tmp_path / "zeebe_tpu"
        pkg.mkdir()
        (pkg / "m.py").write_text("def boot(s, a):\n    s.submit_actor(a)\n")
        rc = zblint_main([
            "--json", "--no-baseline", "--root", str(tmp_path), "zeebe_tpu",
        ])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert doc["findings"][0]["rule"] == "unobserved-actor-future"
        assert set(doc["findings"][0]) == {"rule", "path", "line", "message"}
