"""Transform tests: BPMN model → executable workflow with step bindings.

Reference parity: the transform handlers' bindLifecycle tables
(broker-core/.../workflow/model/transformation/handler/*.java).
"""

from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.models.bpmn.model import ElementType
from zeebe_tpu.models.transform import BpmnStep, transform_model
from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI


def transform_one(model):
    workflows = transform_model(model)
    assert len(workflows) == 1
    return workflows[0]


def order_process_workflow():
    return transform_one(
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


class TestStepBindings:
    def test_process_bindings(self):
        wf = order_process_workflow()
        root = wf.root
        assert root.element_type == ElementType.PROCESS
        assert root.get_step(WI.ELEMENT_READY) == BpmnStep.APPLY_INPUT_MAPPING
        assert root.get_step(WI.ELEMENT_ACTIVATED) == BpmnStep.TRIGGER_START_EVENT
        assert root.get_step(WI.ELEMENT_COMPLETING) == BpmnStep.COMPLETE_PROCESS
        assert (
            root.get_step(WI.ELEMENT_TERMINATING)
            == BpmnStep.TERMINATE_CONTAINED_INSTANCES
        )

    def test_service_task_bindings(self):
        wf = order_process_workflow()
        task = wf.element_by_id("collect-money")
        assert task.get_step(WI.ELEMENT_READY) == BpmnStep.APPLY_INPUT_MAPPING
        assert task.get_step(WI.ELEMENT_ACTIVATED) == BpmnStep.CREATE_JOB
        assert task.get_step(WI.ELEMENT_COMPLETING) == BpmnStep.APPLY_OUTPUT_MAPPING
        assert task.get_step(WI.ELEMENT_COMPLETED) == BpmnStep.TAKE_SEQUENCE_FLOW
        assert task.get_step(WI.ELEMENT_TERMINATING) == BpmnStep.TERMINATE_JOB_TASK
        assert task.get_step(WI.ELEMENT_TERMINATED) == BpmnStep.PROPAGATE_TERMINATION
        assert task.job_type == "payment-service"

    def test_start_end_event_bindings(self):
        wf = order_process_workflow()
        start = wf.element_by_id("start")
        end = wf.element_by_id("end")
        assert start.get_step(WI.START_EVENT_OCCURRED) == BpmnStep.TAKE_SEQUENCE_FLOW
        assert end.get_step(WI.END_EVENT_OCCURRED) == BpmnStep.CONSUME_TOKEN
        assert wf.root.start_event is start

    def test_sequence_flow_bindings(self):
        wf = order_process_workflow()
        start = wf.element_by_id("start")
        to_task = start.outgoing[0]
        assert to_task.get_step(WI.SEQUENCE_FLOW_TAKEN) == BpmnStep.START_STATEFUL_ELEMENT
        task = wf.element_by_id("collect-money")
        to_end = task.outgoing[0]
        assert to_end.get_step(WI.SEQUENCE_FLOW_TAKEN) == BpmnStep.TRIGGER_END_EVENT

    def test_exclusive_gateway_with_conditions(self):
        b = Bpmn.create_process("p").start_event().exclusive_gateway("split")
        b.branch("$.x > 1").end_event("e1")
        b.branch(default=True).end_event("e2")
        wf = transform_one(b.done())
        gw = wf.element_by_id("split")
        assert gw.get_step(WI.GATEWAY_ACTIVATED) == BpmnStep.EXCLUSIVE_SPLIT
        assert gw.default_flow is not None
        assert len(gw.outgoing_with_condition) == 1
        # flow into a gateway binds ACTIVATE_GATEWAY
        into_gw = gw.incoming[0]
        assert into_gw.get_step(WI.SEQUENCE_FLOW_TAKEN) == BpmnStep.ACTIVATE_GATEWAY

    def test_exclusive_gateway_without_conditions_takes_flow(self):
        b = Bpmn.create_process("p").start_event().exclusive_gateway("gw")
        b.branch().end_event("e")
        wf = transform_one(b.done())
        gw = wf.element_by_id("gw")
        assert gw.get_step(WI.GATEWAY_ACTIVATED) == BpmnStep.TAKE_SEQUENCE_FLOW

    def test_parallel_gateway_fork_join(self):
        b = Bpmn.create_process("p").start_event().parallel_gateway("fork")
        branch1 = b.branch().service_task("a", type="t")
        branch2 = b.branch().service_task("c", type="t")
        branch1.parallel_gateway("join")
        branch2.connect_to("join")
        b.move_to("join").end_event("end")
        wf = transform_one(b.done())
        fork = wf.element_by_id("fork")
        join = wf.element_by_id("join")
        assert fork.get_step(WI.GATEWAY_ACTIVATED) == BpmnStep.PARALLEL_SPLIT
        # flows into the join bind PARALLEL_MERGE
        for flow in join.incoming:
            assert flow.get_step(WI.SEQUENCE_FLOW_TAKEN) == BpmnStep.PARALLEL_MERGE
        # join itself activates normally once merged
        assert join.get_step(WI.GATEWAY_ACTIVATED) == BpmnStep.TAKE_SEQUENCE_FLOW

    def test_subprocess_bindings(self):
        b = Bpmn.create_process("p").start_event("s")
        sub = b.sub_process("sub")
        sub.start_event("ss").end_event("se")
        sub.embedded_done().end_event("e")
        wf = transform_one(b.done())
        sub_el = wf.element_by_id("sub")
        assert sub_el.get_step(WI.ELEMENT_ACTIVATED) == BpmnStep.TRIGGER_START_EVENT
        assert sub_el.get_step(WI.ELEMENT_READY) == BpmnStep.APPLY_INPUT_MAPPING
        assert sub_el.start_event is wf.element_by_id("ss")
        assert wf.element_by_id("ss").scope_id == "sub"

    def test_message_catch_bindings(self):
        wf = transform_one(
            Bpmn.create_process("p")
            .start_event()
            .message_catch_event("wait", message_name="m", correlation_key="$.k")
            .end_event()
            .done()
        )
        catch = wf.element_by_id("wait")
        assert (
            catch.get_step(WI.ELEMENT_ACTIVATED)
            == BpmnStep.SUBSCRIBE_TO_INTERMEDIATE_MESSAGE
        )
        assert catch.message_name == "m"
        assert catch.correlation_key_path == "$.k"

    def test_timer_catch_bindings(self):
        wf = transform_one(
            Bpmn.create_process("p")
            .start_event()
            .timer_catch_event("wait", duration_ms=1000)
            .end_event()
            .done()
        )
        catch = wf.element_by_id("wait")
        assert catch.get_step(WI.ELEMENT_ACTIVATED) == BpmnStep.CREATE_TIMER

    def test_element_indices_dense(self):
        wf = order_process_workflow()
        assert [e.index for e in wf.elements] == list(range(len(wf.elements)))
        assert wf.root.index == 0
