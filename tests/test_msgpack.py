"""msgpack codec tests (reference: msgpack-core spec tests)."""

import pytest

from zeebe_tpu.protocol import msgpack


ROUND_TRIP_CASES = [
    None,
    True,
    False,
    0,
    1,
    127,
    128,
    255,
    256,
    65535,
    65536,
    2**31 - 1,
    2**32,
    2**63 - 1,
    -1,
    -32,
    -33,
    -128,
    -129,
    -32768,
    -32769,
    -(2**31),
    -(2**63),
    1.5,
    -2.75,
    "",
    "hello",
    "x" * 31,
    "x" * 32,
    "x" * 300,
    "ünïcödé ⚙",
    b"",
    b"\x00\x01\x02",
    b"y" * 300,
    [],
    [1, 2, 3],
    list(range(20)),
    {},
    {"a": 1},
    {"k" + str(i): i for i in range(20)},
    {"nested": {"a": [1, {"b": None}], "c": "d"}},
]


@pytest.mark.parametrize("value", ROUND_TRIP_CASES, ids=lambda v: repr(v)[:40])
def test_round_trip(value):
    assert msgpack.unpack(msgpack.pack(value)) == value


def test_empty_document_constant():
    assert msgpack.unpack(msgpack.EMPTY_DOCUMENT) == {}


def test_canonical_sorts_keys():
    a = msgpack.canonical({"b": 1, "a": 2})
    b = msgpack.canonical({"a": 2, "b": 1})
    assert a == b


def test_canonical_distinguishes_values():
    assert msgpack.canonical({"a": 1}) != msgpack.canonical({"a": 2})


def test_unpack_rejects_trailing_bytes():
    with pytest.raises(ValueError):
        msgpack.unpack(msgpack.pack(1) + b"\x01")


def test_unpack_from_offset():
    data = msgpack.pack("ab") + msgpack.pack([1])
    v1, o = msgpack.unpack_from(data, 0)
    v2, o2 = msgpack.unpack_from(data, o)
    assert v1 == "ab" and v2 == [1] and o2 == len(data)
