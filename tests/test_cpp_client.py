"""Second-language client: the C++ native-protocol client (clients/cpp)
drives the order process end to end against a live broker over real
sockets — the reference's polyglot-client parity (its Java client speaks
the broker-native wire protocol; its Go client covers gRPC, whose schema
here is gateway-protocol/gateway.proto)."""

import os
import subprocess
import time

import pytest

from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.models.bpmn.xml import write_model
from zeebe_tpu.runtime.cluster_broker import ClusterBroker
from zeebe_tpu.runtime.config import BrokerCfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLIENT_DIR = os.path.join(REPO, "clients", "cpp")
CLIENT_BIN = os.path.join(CLIENT_DIR, "zbclient")


def wait_until(predicate, timeout=20.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture(scope="module")
def client_bin():
    proc = subprocess.run(
        ["make", "-C", CLIENT_DIR], capture_output=True, text=True
    )
    if proc.returncode != 0:
        pytest.skip(f"C++ toolchain unavailable: {proc.stderr[-300:]}")
    return CLIENT_BIN


@pytest.fixture
def broker(tmp_path):
    cfg = BrokerCfg()
    cfg.network.client_port = 0
    cfg.network.management_port = 0
    cfg.network.subscription_port = 0
    cfg.metrics.port = 0
    cfg.cluster.node_id = "cpp-broker"
    cfg.raft.heartbeat_interval_ms = 30
    cfg.raft.election_timeout_ms = 200
    cfg.gossip.probe_interval_ms = 50
    cfg.metrics.enabled = False
    b = ClusterBroker(cfg, str(tmp_path / "b0"))
    b.open_partition(0).join(10)
    b.bootstrap_partition(0, {})
    assert wait_until(lambda: b.partitions[0].is_leader, 20)
    yield b
    b.close()


class TestCppClient:
    def test_topology(self, client_bin, broker):
        out = subprocess.run(
            [client_bin, broker.client_address.host,
             str(broker.client_address.port), "topology"],
            capture_output=True, text=True, timeout=30,
        )
        assert out.returncode == 0, out.stderr
        assert "partition 0 leader" in out.stdout

    def test_order_process_end_to_end(self, client_bin, broker, tmp_path):
        model = (
            Bpmn.create_process("order-process")
            .start_event("start")
            .service_task("collect-money", type="payment-service")
            .end_event("end")
            .done()
        )
        bpmn = tmp_path / "order.bpmn"
        bpmn.write_bytes(write_model(model))
        out = subprocess.run(
            [client_bin, broker.client_address.host,
             str(broker.client_address.port), "run-order-process", str(bpmn)],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "ORDER-PROCESS-OK" in out.stdout
        # the broker's log confirms the full lifecycle ran
        from zeebe_tpu.protocol.enums import RecordType, ValueType
        from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI

        def completed():
            return any(
                int(r.metadata.value_type) == int(ValueType.WORKFLOW_INSTANCE)
                and int(r.metadata.record_type) == int(RecordType.EVENT)
                and int(r.metadata.intent) == int(WI.ELEMENT_COMPLETED)
                and getattr(r.value, "activity_id", "") == "order-process"
                for r in broker.partitions[0].log.reader(0)
            )

        assert wait_until(completed, 15)


GRPC_WORKER_BIN = os.path.join(CLIENT_DIR, "zbgrpcworker")


class TestGrpcCppWorker:
    """The gRPC-speaking external worker (clients/cpp/zbgrpcworker.cc):
    hand-rolled HTTP/2 + protobuf wire format against the PUBLISHED
    gateway.proto — deploys, creates instances, consumes the ActivateJobs
    stream, completes every job, touching ONLY the gRPC gateway
    (reference: clients/go/client.go:16-38)."""

    def test_worker_runs_order_process_via_gateway_only(
        self, client_bin, broker, tmp_path
    ):
        from zeebe_tpu.gateway.cluster_client import ClusterClient
        from zeebe_tpu.gateway.grpc_gateway import GrpcGateway

        client = ClusterClient([broker.client_address])
        gw = GrpcGateway(client)
        try:
            bpmn = tmp_path / "order.bpmn"
            bpmn.write_bytes(write_model(
                Bpmn.create_process("order-process")
                .start_event("start")
                .service_task("collect-money", type="payment-service")
                .end_event("end")
                .done()
            ))
            proc = subprocess.run(
                [GRPC_WORKER_BIN, "127.0.0.1", str(gw.port),
                 "run-order-process", str(bpmn), "3"],
                capture_output=True, text=True, timeout=60,
            )
            assert proc.returncode == 0, (proc.stdout, proc.stderr)
            assert "OK run-order-process grpc completed=3" in proc.stdout
            # all three instances completed on the broker
            engine = broker.partitions[0].engine
            assert wait_until(lambda: not engine.element_instances.instances, 10)
        finally:
            gw.close()
            client.close()
