"""Record value + frame codec tests (reference: protocol SBE round trips)."""

from zeebe_tpu.protocol import (
    JobIntent,
    RecordType,
    RejectionType,
    ValueType,
    WorkflowInstanceIntent,
)
from zeebe_tpu.protocol.codec import decode_record, encode_record
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import (
    JobHeaders,
    JobRecord,
    Record,
    WorkflowInstanceRecord,
)


def make_record():
    return Record(
        position=42,
        source_record_position=41,
        key=7,
        timestamp=123456789,
        producer_id=3,
        raft_term=2,
        metadata=RecordMetadata(
            record_type=RecordType.EVENT,
            value_type=ValueType.WORKFLOW_INSTANCE,
            intent=int(WorkflowInstanceIntent.ELEMENT_ACTIVATED),
            request_id=99,
            request_stream_id=5,
        ),
        value=WorkflowInstanceRecord(
            bpmn_process_id="order-process",
            version=1,
            workflow_key=11,
            workflow_instance_key=7,
            activity_id="collect-money",
            payload={"orderId": 31243, "orderValue": 99.5},
            scope_instance_key=7,
        ),
    )


def test_frame_round_trip():
    record = make_record()
    frame = encode_record(record)
    assert len(frame) % 8 == 0
    decoded, consumed = decode_record(frame)
    assert consumed == len(frame)
    assert decoded.position == 42
    assert decoded.key == 7
    assert decoded.metadata.record_type == RecordType.EVENT
    assert decoded.metadata.value_type == ValueType.WORKFLOW_INSTANCE
    assert decoded.metadata.intent == WorkflowInstanceIntent.ELEMENT_ACTIVATED
    assert decoded.metadata.request_id == 99
    assert decoded.value.bpmn_process_id == "order-process"
    assert decoded.value.payload == {"orderId": 31243, "orderValue": 99.5}


def test_rejection_frame():
    record = make_record()
    record.metadata.record_type = RecordType.COMMAND_REJECTION
    record.metadata.rejection_type = RejectionType.NOT_APPLICABLE
    record.metadata.rejection_reason = "Workflow instance is not running"
    decoded, _ = decode_record(encode_record(record))
    assert decoded.metadata.rejection_type == RejectionType.NOT_APPLICABLE
    assert decoded.metadata.rejection_reason == "Workflow instance is not running"


def test_job_record_document_keys_match_reference():
    job = JobRecord(
        type="payment-service",
        retries=3,
        payload={"total": 100},
        headers=JobHeaders(
            workflow_instance_key=7,
            bpmn_process_id="order-process",
            activity_id="collect-money",
            activity_instance_key=9,
        ),
        custom_headers={"method": "VISA"},
    )
    doc = job.to_document()
    # keys must match reference JobRecord.java / JobHeaders.java property names
    assert set(doc.keys()) == {
        "deadline",
        "worker",
        "retries",
        "type",
        "headers",
        "customHeaders",
        "payload",
    }
    assert doc["headers"]["workflowInstanceKey"] == 7
    assert doc["headers"]["bpmnProcessId"] == "order-process"
    round_tripped = JobRecord.decode(job.encode())
    assert round_tripped == job


def test_workflow_instance_record_keys_match_reference():
    doc = make_record().value.to_document()
    assert set(doc.keys()) == {
        "bpmnProcessId",
        "version",
        "workflowKey",
        "workflowInstanceKey",
        "activityId",
        "payload",
        "scopeInstanceKey",
    }


def test_multiple_frames_in_buffer():
    r1, r2 = make_record(), make_record()
    r2.position = 43
    buf = encode_record(r1) + encode_record(r2)
    d1, o = decode_record(buf, 0)
    d2, o2 = decode_record(buf, o)
    assert d1.position == 42 and d2.position == 43 and o2 == len(buf)
