"""gRPC gateway tests (reference: ``Gateway.java`` + the Go client's
``healthCheck_test.go`` integration suite: dial the gateway over real gRPC,
check topology, then drive commands end to end)."""

import time

import pytest

from zeebe_tpu.gateway.cluster_client import ClusterClient
from zeebe_tpu.gateway.grpc_gateway import GrpcGateway, GrpcGatewayClient
from zeebe_tpu.gateway.proto import gateway_pb2 as pb
from zeebe_tpu.protocol import msgpack
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.models.bpmn.xml import write_model
from zeebe_tpu.runtime.cluster_broker import ClusterBroker
from zeebe_tpu.runtime.config import BrokerCfg


def wait_until(predicate, timeout=20.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def gateway(tmp_path):
    cfg = BrokerCfg()
    cfg.network.client_port = 0
    cfg.network.management_port = 0
    cfg.network.subscription_port = 0
    cfg.metrics.port = 0
    cfg.cluster.node_id = "gw-broker"
    cfg.raft.heartbeat_interval_ms = 30
    cfg.raft.election_timeout_ms = 200
    cfg.gossip.probe_interval_ms = 50
    cfg.metrics.enabled = False
    broker = ClusterBroker(cfg, str(tmp_path / "b0"))
    broker.open_partition(0).join(10)
    broker.bootstrap_partition(0, {})
    assert wait_until(lambda: broker.partitions[0].is_leader, 20)
    client = ClusterClient([broker.client_address])
    gw = GrpcGateway(client)
    stub = GrpcGatewayClient("127.0.0.1", gw.port)
    yield stub, broker
    stub.close()
    gw.close()
    client.close()
    broker.close()


def order_process_bytes():
    return write_model(
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


class TestGrpcGateway:
    def test_health_check_reports_topology(self, gateway):
        stub, broker = gateway
        health = stub.health_check()
        assert health.brokers, health
        assert health.brokers[0].partition_id == 0
        assert health.brokers[0].port == broker.client_address.port

    def test_deploy_and_run_instance_over_grpc(self, gateway):
        stub, broker = gateway
        deployed = stub.call(
            "DeployWorkflow",
            pb.DeployWorkflowRequest(resource=order_process_bytes()),
        )
        assert deployed.workflows[0].bpmn_process_id == "order-process"

        created = stub.call(
            "CreateWorkflowInstance",
            pb.CreateWorkflowInstanceRequest(
                bpmn_process_id="order-process",
                payload_msgpack=msgpack.pack({"orderId": 7}),
                partition_id=0,
            ),
        )
        instance_key = created.workflow_instance_key
        assert instance_key > 0

        # the job exists on the broker; complete it over gRPC
        engine = broker.partitions[0].engine
        assert wait_until(lambda: len(engine.jobs) == 1, 10)
        job_key = next(iter(engine.jobs))
        # jobs must be activated before completion; drive via the activation
        # path: a zero-handler worker would race, so complete directly after
        # activation through the engine-visible state
        from zeebe_tpu.engine.interpreter import JobSubscription

        backlog = engine.add_job_subscription(
            JobSubscription(subscriber_key=999, job_type="payment-service",
                            worker="grpc-test", timeout=300_000, credits=1)
        )
        if backlog:
            broker.partitions[0].raft.append(backlog)
        assert wait_until(
            lambda: engine.jobs.get(job_key) is not None
            and engine.jobs[job_key].state == 3,  # ACTIVATED
            10,
        )
        stub.call(
            "CompleteJob",
            pb.CompleteJobRequest(
                partition_id=0, job_key=job_key,
                payload_msgpack=msgpack.pack({"paid": True}),
            ),
        )
        assert wait_until(
            lambda: engine.element_instances.get(instance_key) is None, 10
        ), "instance must complete after the job is done"

    def test_rejection_maps_to_grpc_error(self, gateway):
        import grpc

        stub, _broker = gateway
        with pytest.raises(grpc.RpcError) as err:
            stub.call(
                "CreateWorkflowInstance",
                pb.CreateWorkflowInstanceRequest(
                    bpmn_process_id="no-such", partition_id=0
                ),
            )
        assert err.value.code() in (
            grpc.StatusCode.FAILED_PRECONDITION, grpc.StatusCode.INTERNAL,
        )


class TestActivateJobsStream:
    """The polyglot worker surface: jobs stream over gRPC, the worker
    completes them through CompleteJob — no native-protocol connection
    involved (reference: clients/go/client.go:16-38)."""

    def test_worker_completes_job_through_gateway_only(self, gateway):
        stub, broker = gateway
        stub.call(
            "DeployWorkflow",
            pb.DeployWorkflowRequest(resource=order_process_bytes()),
        )
        created = stub.call(
            "CreateWorkflowInstance",
            pb.CreateWorkflowInstanceRequest(
                bpmn_process_id="order-process",
                payload_msgpack=msgpack.pack({"orderId": 11}),
                partition_id=0,
            ),
        )
        instance_key = created.workflow_instance_key

        stream = stub.activate_jobs(
            pb.ActivateJobsRequest(
                type="payment-service", worker="ext-worker", max_jobs=4
            )
        )
        job = next(iter(stream))
        assert job.type == "payment-service"
        assert job.bpmn_process_id == "order-process"
        assert job.activity_id == "collect-money"
        assert job.workflow_instance_key == instance_key
        assert msgpack.unpack(job.payload_msgpack) == {"orderId": 11}

        stub.call(
            "CompleteJob",
            pb.CompleteJobRequest(
                partition_id=job.partition_id, job_key=job.key,
                payload_msgpack=msgpack.pack({"paid": True}),
            ),
        )
        engine = broker.partitions[0].engine
        assert wait_until(
            lambda: engine.element_instances.get(instance_key) is None, 10
        ), "instance must complete via the gRPC-only worker"
        stream.cancel()

    def test_stream_delivers_multiple_jobs(self, gateway):
        stub, broker = gateway
        stub.call(
            "DeployWorkflow",
            pb.DeployWorkflowRequest(resource=order_process_bytes()),
        )
        for i in range(3):
            stub.call(
                "CreateWorkflowInstance",
                pb.CreateWorkflowInstanceRequest(
                    bpmn_process_id="order-process",
                    payload_msgpack=msgpack.pack({"orderId": i}),
                    partition_id=0,
                ),
            )
        stream = stub.activate_jobs(
            pb.ActivateJobsRequest(type="payment-service", max_jobs=8)
        )
        it = iter(stream)
        seen = set()
        for _ in range(3):
            job = next(it)
            seen.add(msgpack.unpack(job.payload_msgpack)["orderId"])
            stub.call(
                "CompleteJob",
                pb.CompleteJobRequest(
                    partition_id=job.partition_id, job_key=job.key,
                    payload_msgpack=msgpack.pack({}),
                ),
            )
        assert seen == {0, 1, 2}
        stream.cancel()
