"""Fused phase-B/C mega-gather + packed emission takes
(zeebe_tpu/tpu/pallas_ops.fused_gather_rows, zeebe_tpu/tpu/batch.take_rows).

CPU pins the semantics: off-TPU every family resolves to the XLA
fallbacks, so the fused gather must equal direct indexing exactly — the
same contract that makes the parity fuzzer meaningful for the TPU path.
The on-chip pallas-vs-XLA leg lives in benchmarks/pallas_ops_check.py
(check_fused_gather).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from zeebe_tpu import tpu as _tpu  # noqa: F401  (enables x64)
from zeebe_tpu.tpu import autotune, batch as rb, pallas_ops as pops


def _tables(rng, T, K):
    i32 = jnp.asarray(rng.integers(-(2**31), 2**31, (T, K)), jnp.int32)
    i64 = jnp.asarray(rng.integers(-(2**62), 2**62, (T, K), dtype=np.int64))
    f32 = jax.lax.bitcast_convert_type(
        jnp.asarray(rng.integers(-(2**31), 2**31, (T, K)), jnp.int32),
        jnp.float32,
    )
    i8 = jnp.asarray(rng.integers(-128, 128, (T, K)), jnp.int8)
    l32 = jnp.asarray(rng.integers(-(2**31), 2**31, (T,)), jnp.int32)
    l64 = jnp.asarray(rng.integers(-(2**62), 2**62, (T,), dtype=np.int64))
    lf32 = jax.lax.bitcast_convert_type(
        jnp.asarray(rng.integers(-(2**31), 2**31, (T,)), jnp.int32),
        jnp.float32,
    )
    return [i32, i64, f32, i8, l32, l64, lf32]


def _bits(a):
    return (jax.lax.bitcast_convert_type(a, jnp.int32)
            if a.dtype == jnp.float32 else a)


class TestFusedGatherFallback:
    def test_matches_direct_indexing_all_dtypes(self):
        """Every table normal form the kernel feeds the pass — 2D
        i32/i64/f32/i8, 1D i32/i64/f32 — with duplicate-heavy index
        vectors (reads commute, duplicates are always legal)."""
        rng = np.random.default_rng(3)
        T, B = 512, 192
        tables = _tables(rng, T, 8)
        ops = [pops.GatherOp(t, jnp.asarray(rng.choice(T, B), jnp.int32))
               for t in range(len(tables))]
        got = pops.fused_gather_rows(tables, ops)
        for o, g in zip(ops, got):
            want = tables[o.table][o.slots]
            assert g.dtype == want.dtype
            assert (np.asarray(_bits(g)) == np.asarray(_bits(want))).all()

    def test_same_table_ops_share_one_gather(self):
        """Grouping: N reads off one 2D table lower to ONE gather (concat
        index vectors + static splits) — the census mechanism."""
        rng = np.random.default_rng(5)
        T, B = 256, 64
        tbl = jnp.asarray(rng.integers(0, 100, (T, 8)), jnp.int32)
        slots = [jnp.asarray(rng.choice(T, B), jnp.int32) for _ in range(3)]

        def f(tbl, s0, s1, s2):
            return pops.fused_gather_rows(
                [tbl], [pops.GatherOp(0, s0), pops.GatherOp(0, s1),
                        pops.GatherOp(0, s2)])

        text = jax.jit(f).lower(tbl, *slots).as_text()
        assert text.count('"stablehlo.gather"(') == 1
        got = f(tbl, *slots)
        for s, g in zip(slots, got):
            assert (np.asarray(g) == np.asarray(tbl[s])).all()

    def test_1d_tables_group_by_dtype(self):
        """Two 1D i32 tables fold into one offset-indexed gather."""
        rng = np.random.default_rng(7)
        T, B = 256, 64
        ta = jnp.asarray(rng.integers(0, 100, (T,)), jnp.int32)
        tb = jnp.asarray(rng.integers(0, 100, (T,)), jnp.int32)
        sa = jnp.asarray(rng.choice(T, B), jnp.int32)
        sb = jnp.asarray(rng.choice(T, B), jnp.int32)

        def f(ta, tb, sa, sb):
            return pops.fused_gather_rows(
                [ta, tb], [pops.GatherOp(0, sa), pops.GatherOp(1, sb)])

        text = jax.jit(f).lower(ta, tb, sa, sb).as_text()
        assert text.count('"stablehlo.gather"(') == 1
        ga, gb = f(ta, tb, sa, sb)
        assert (np.asarray(ga) == np.asarray(ta[sa])).all()
        assert (np.asarray(gb) == np.asarray(tb[sb])).all()

    def test_mixed_batch_sizes(self):
        """Ops with different batch widths (the lookup stages fuse a 3B
        probe with a B probe) still group correctly in the fallback."""
        rng = np.random.default_rng(9)
        T = 128
        tbl = jnp.asarray(rng.integers(0, 100, (T, 4)), jnp.int32)
        s_wide = jnp.asarray(rng.choice(T, 96), jnp.int32)
        s_narrow = jnp.asarray(rng.choice(T, 32), jnp.int32)
        gw, gn = pops.fused_gather_rows(
            [tbl], [pops.GatherOp(0, s_wide), pops.GatherOp(0, s_narrow)])
        assert (np.asarray(gw) == np.asarray(tbl[s_wide])).all()
        assert (np.asarray(gn) == np.asarray(tbl[s_narrow])).all()

    def test_empty_ops(self):
        assert pops.fused_gather_rows([jnp.ones((4, 4), jnp.int32)], []) == []


class TestTakeRows:
    def _random_batch(self, rng, B, V):
        b = rb.empty(B, V)
        upd = {}
        for f in rb._FIELDS:
            a = getattr(b, f)
            if a.dtype == jnp.float32:
                upd[f] = jax.lax.bitcast_convert_type(
                    jnp.asarray(rng.integers(-(2**31), 2**31, a.shape),
                                jnp.int32), jnp.float32)
            elif a.dtype == bool:
                upd[f] = jnp.asarray(rng.integers(0, 2, a.shape), bool)
            else:
                info = np.iinfo(np.dtype(str(a.dtype)))
                upd[f] = jnp.asarray(
                    rng.integers(info.min, int(info.max) + 1, a.shape,
                                 dtype=np.int64).astype(str(a.dtype)))
        return dataclasses.replace(b, **upd)

    def test_bit_identical_to_tree_map(self):
        """take_rows == per-field a[idx] for every field and dtype,
        including f32 NaN payload bit patterns."""
        rng = np.random.default_rng(11)
        B, V = 96, 4
        b = self._random_batch(rng, B, V)
        idx = jnp.asarray(rng.choice(B, B), jnp.int32)
        got = rb.take_rows(b, idx)
        want = jax.tree.map(lambda a: a[idx], b)
        for f in rb._FIELDS:
            g, w = getattr(got, f), getattr(want, f)
            assert g.dtype == w.dtype, f
            assert (np.asarray(_bits(g)) == np.asarray(_bits(w))).all(), f

    def test_take_count(self):
        """The packed form lowers to exactly TWO gathers (i32 + i8
        matrices) — the 24→2 consolidation."""
        b = rb.empty(64, 4)
        idx = jnp.arange(64, dtype=jnp.int32)
        text = jax.jit(rb.take_rows).lower(b, idx).as_text()
        assert text.count('"stablehlo.gather"(') == 2

    def test_compact_prefixes_valid_rows(self):
        rng = np.random.default_rng(13)
        b = self._random_batch(rng, 64, 4)
        out = rb.compact(b)
        v = np.asarray(out.valid)
        n = int(v.sum())
        assert v[:n].all() and not v[n:].any()
        # stable order: valid rows keep their relative order
        src = np.asarray(b.key)[np.asarray(b.valid)]
        assert (np.asarray(out.key)[:n] == src).all()


class TestDispatchFamilies:
    def test_new_families_registered(self):
        assert "gather" in pops.FAMILIES
        assert "emit" in pops.FAMILIES

    def test_off_tpu_stays_xla(self):
        if jax.default_backend() == "tpu":
            pytest.skip("CPU-only behavior")
        with pops.forced("pallas"):
            assert not pops.use_pallas("gather")
            assert not pops.use_pallas("emit")

    def test_autotune_benches_cover_new_families(self):
        benches = autotune._benches()
        assert "gather" in benches and "emit" in benches
        with pops.forced("xla"):
            out = jax.jit(benches["gather"])()
            jax.block_until_ready(out)
