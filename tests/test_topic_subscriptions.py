"""Topic subscription tests: durable server-push of a partition's records.

Reference parity: ``broker-core/.../event/processor/
TopicSubscriptionManagementProcessor`` (SUBSCRIBE/SUBSCRIBED lifecycle),
``TopicSubscriptionPushProcessor:36`` (per-subscriber push with credit flow
control), and ack records persisting consumer progress in the log
(``TopicSubscriberState``). Tests mirror broker-core's
TopicSubscriptionTest: open, receive all records, ack, reopen resumes,
force-start rewinds.
"""

import tempfile

import pytest

from zeebe_tpu.gateway import JobWorker, TopicSubscriber, ZeebeClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.protocol.enums import ValueType
from zeebe_tpu.protocol.intents import WorkflowInstanceIntent
from zeebe_tpu.runtime import Broker, ControlledClock


def order_process():
    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


@pytest.fixture
def broker(tmp_path):
    b = Broker(num_partitions=1, data_dir=str(tmp_path / "data"),
               clock=ControlledClock())
    yield b
    b.close()


class TestTopicSubscription:
    def test_receives_all_records_of_the_partition(self, broker):
        client = ZeebeClient(broker)
        sub = TopicSubscriber(broker, "all-records")
        client.deploy_model(order_process())
        JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        client.create_instance("order-process", {"orderId": 1})
        broker.run_until_idle()

        value_types = {r.metadata.value_type for r in sub.records}
        assert ValueType.DEPLOYMENT in value_types
        assert ValueType.WORKFLOW_INSTANCE in value_types
        assert ValueType.JOB in value_types
        # matches the log (minus subscription-admin records)
        log_records = [
            r for r in broker.records(0)
            if r.metadata.value_type not in (ValueType.SUBSCRIBER, ValueType.SUBSCRIPTION)
        ]
        assert [r.position for r in sub.records] == [r.position for r in log_records]
        sub.close()

    def test_credit_flow_control_pauses_delivery(self, broker):
        client = ZeebeClient(broker)
        received = []
        # no auto-ack: delivery must stall at the credit limit
        handle = broker.open_topic_subscription(
            "limited", lambda pid, r: received.append(r), credits=4
        )
        client.deploy_model(order_process())
        client.create_instance("order-process")
        broker.run_until_idle()
        assert len(received) == 4, "delivery must stop at the credit limit"
        # acking frees credits and delivery resumes
        handle.ack(received[-1].position)
        broker.run_until_idle()
        assert len(received) > 4
        handle.close()

    def test_reopen_resumes_after_last_ack(self, tmp_path):
        clock = ControlledClock()
        data = str(tmp_path / "data")
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process())
        client.create_instance("order-process")
        sub = TopicSubscriber(broker, "resume-me", ack_batch=1)
        broker.run_until_idle()
        seen = len(sub.records)
        assert seen > 0
        last = sub.records[-1].position
        sub.ack_all()
        broker.run_until_idle()
        sub.close()

        # restart the broker: the ack survives in the log; a reopened
        # subscription with the same name resumes AFTER the acked position
        broker.close()
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        sub2 = TopicSubscriber(broker, "resume-me")
        client.create_instance("order-process")
        broker.run_until_idle()
        assert sub2.records, "new records must still arrive"
        assert all(r.position > last for r in sub2.records), (
            "resumed subscription must not re-deliver acked records"
        )
        sub2.close()
        broker.close()

    def test_force_start_rewinds_to_the_beginning(self, broker):
        client = ZeebeClient(broker)
        client.deploy_model(order_process())
        sub = TopicSubscriber(broker, "rewind", ack_batch=1)
        broker.run_until_idle()
        sub.ack_all()
        broker.run_until_idle()
        sub.close()

        sub2 = TopicSubscriber(broker, "rewind", force_start=True)
        broker.run_until_idle()
        assert sub2.records and sub2.records[0].position == 0
        sub2.close()

    def test_start_position_skips_history(self, broker):
        client = ZeebeClient(broker)
        client.deploy_model(order_process())
        broker.run_until_idle()
        cut = broker.partitions[0].log.next_position
        sub = TopicSubscriber(broker, "tail-only", start_position=cut)
        client.create_instance("order-process")
        broker.run_until_idle()
        assert sub.records
        assert all(r.position >= cut for r in sub.records)
        intents = [
            WorkflowInstanceIntent(r.metadata.intent)
            for r in sub.records
            if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
        ]
        assert WorkflowInstanceIntent.CREATED in intents
        sub.close()
