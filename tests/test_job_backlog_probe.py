"""Device job-backlog serving path: typed probe, persisted round-robin
cursor, and the in-process broker's gated device pull
(zeebe_tpu/tpu/engine.py, zeebe_tpu/runtime/broker.py).
"""

import dataclasses

import numpy as np

import jax.numpy as jnp

from zeebe_tpu import tpu as _tpu  # noqa: F401  (enables x64)
from zeebe_tpu.protocol.intents import JobIntent as JI
from zeebe_tpu.runtime import Broker
from zeebe_tpu.tpu.engine import (
    PROBE_DEADLINES,
    PROBE_JOB_BACKLOG,
    TpuPartitionEngine,
    _due_probe_jit,
)


def _engine(n_jobs, sub_specs, job_type="work"):
    """TpuPartitionEngine with ``n_jobs`` CREATED device-table jobs of
    ``job_type`` and subscriptions per (key, type, credits) specs."""
    eng = TpuPartitionEngine(capacity=256, sub_capacity=8)
    s = eng.state
    tid = eng.interns.intern(job_type)
    job_i32 = np.asarray(s.job_i32).copy()
    job_i64 = np.asarray(s.job_i64).copy()
    for i in range(n_jobs):
        job_i32[i] = (int(JI.CREATED), 0, 0, tid, 3, 0)
        job_i64[i] = (100 + 5 * i, -1, -1, -1)
    sub_key = np.asarray(s.sub_key).copy()
    sub_type = np.asarray(s.sub_type).copy()
    sub_worker = np.asarray(s.sub_worker).copy()
    sub_credits = np.asarray(s.sub_credits).copy()
    sub_timeout = np.asarray(s.sub_timeout).copy()
    sub_valid = np.asarray(s.sub_valid).copy()
    for slot, (key, stype, credits) in enumerate(sub_specs):
        sub_key[slot] = key
        sub_type[slot] = eng.interns.intern(stype)
        sub_worker[slot] = eng.interns.intern(f"worker-{key}")
        sub_credits[slot] = credits
        sub_timeout[slot] = 1000
        sub_valid[slot] = True
    eng.state = dataclasses.replace(
        s,
        job_i32=jnp.asarray(job_i32), job_i64=jnp.asarray(job_i64),
        sub_key=jnp.asarray(sub_key), sub_type=jnp.asarray(sub_type),
        sub_worker=jnp.asarray(sub_worker),
        sub_credits=jnp.asarray(sub_credits),
        sub_timeout=jnp.asarray(sub_timeout),
        sub_valid=jnp.asarray(sub_valid),
    )
    return eng


class TestTypedBacklogProbe:
    def test_backlog_bit_set_on_type_match(self):
        eng = _engine(2, [(1, "work", 5)])
        # the probe donates state (aliased pass-through): rebind
        eng.state, mask = _due_probe_jit(eng.state, jnp.asarray(0, jnp.int64))
        mask = int(mask)
        assert mask & PROBE_JOB_BACKLOG
        assert not mask & PROBE_DEADLINES

    def test_orphan_job_with_unmatched_credits_keeps_bit_clear(self):
        """The round-5 failure mode: ONE orphan job of an unserved type +
        any credited subscription kept the bit set, paying a full
        device→host backlog pull every tick for nothing."""
        eng = _engine(1, [(1, "other-type", 5)])
        eng.state, mask = _due_probe_jit(eng.state, jnp.asarray(0, jnp.int64))
        mask = int(mask)
        assert not mask & PROBE_JOB_BACKLOG
        # and the pull it gates would indeed have found nothing
        assert eng.device_backlog_activations() == []

    def test_exhausted_credits_keep_bit_clear(self):
        eng = _engine(2, [(1, "work", 0)])
        eng.state, mask = _due_probe_jit(eng.state, jnp.asarray(0, jnp.int64))
        mask = int(mask)
        assert not mask & PROBE_JOB_BACKLOG


class TestRoundRobinCursor:
    def test_assignments_alternate_within_a_call(self):
        eng = _engine(4, [(1, "work", 10), (2, "work", 10)])
        out = eng.device_backlog_activations()
        streams = [r.metadata.request_stream_id for r in out]
        assert streams == [1, 2, 1, 2]

    def test_cursor_persists_across_calls(self):
        """A fresh ``rr = 0`` every call handed every drain's first job to
        the first credited subscription; the cursor now lives in
        state.sub_rr, so consecutive drains continue the rotation."""
        eng = _engine(1, [(1, "work", 10), (2, "work", 10)])
        first = eng.device_backlog_activations()
        second = eng.device_backlog_activations()
        assert first[0].metadata.request_stream_id == 1
        assert second[0].metadata.request_stream_id == 2
        assert int(np.asarray(eng.state.sub_rr)) == 0  # wrapped around

    def test_cursor_survives_snapshot_restore(self):
        eng = _engine(1, [(1, "work", 10), (2, "work", 10)])
        eng.device_backlog_activations()  # advances the cursor to 1
        assert int(np.asarray(eng.state.sub_rr)) == 1
        snap = eng.snapshot_state()
        restored = TpuPartitionEngine(capacity=256, sub_capacity=8)
        restored.restore_state(snap)
        assert int(np.asarray(restored.state.sub_rr)) == 1


class TestBrokerTickGating:
    def test_device_pull_gated_by_probe_bit(self, tmp_path):
        """Broker.tick must consult the fused probe before paying the
        device→host backlog pull (the cluster broker's existing
        protocol); a clear bit skips the pull entirely."""
        broker = Broker(num_partitions=1, data_dir=str(tmp_path / "d"))
        partition = broker.partitions[0]
        calls = {"pull": 0}

        class GatedEngine:
            def __init__(self, inner, mask):
                self._inner = inner
                self._mask = mask

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def deadlines_due_probe(self):
                return self._mask

            def device_backlog_activations(self):
                calls["pull"] += 1
                return []

        partition.engine = GatedEngine(partition.engine, 0)
        broker.tick()
        assert calls["pull"] == 0
        partition.engine = GatedEngine(
            partition.engine._inner, PROBE_JOB_BACKLOG
        )
        broker.tick()
        assert calls["pull"] == 1
        broker.close()
