"""Boundary events + multi-instance sub-process (host engine).

Reference parity: the reference MODEL defines both
(``bpmn-model/.../instance/BoundaryEvent.java``,
``.../instance/MultiInstanceLoopCharacteristics.java``) but its
tech-preview engine never executes them; this engine does (BASELINE.json
bench configs 4-5 require them). Assertions follow the reference test
style: the event log is the observable behavior.
"""

import pytest

from zeebe_tpu.gateway import JobWorker, ZeebeClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import (
    JobIntent,
    TimerIntent,
    WorkflowInstanceIntent as WI,
)
from zeebe_tpu.runtime import Broker, ControlledClock


@pytest.fixture
def clock():
    return ControlledClock(start_ms=1_000_000)


@pytest.fixture
def broker(tmp_path, clock):
    b = Broker(num_partitions=1, data_dir=str(tmp_path / "data"), clock=clock)
    yield b
    b.close()


@pytest.fixture
def client(broker):
    return ZeebeClient(broker)


def wi_events(broker, partition=0):
    return [
        (WI(r.metadata.intent).name, r.value.activity_id)
        for r in broker.records(partition)
        if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
        and r.metadata.record_type == RecordType.EVENT
    ]


def timer_boundary_model(interrupting=True):
    return (
        Bpmn.create_process("escalate")
        .start_event("start")
        .service_task("work", type="slow-service")
        .boundary_event(
            "deadline", duration_ms=5_000, interrupting=interrupting
        )
        .service_task("escalate-task", type="escalation-service")
        .end_event("escalated")
        .move_to("work")
        .end_event("done")
        .done()
    )


class TestTimerBoundaryEvent:
    def test_interrupting_timer_fires_and_cancels_host(self, broker, client, clock):
        client.deploy_model(timer_boundary_model())
        # no worker for slow-service: the job stays out; the timer fires
        escalated = JobWorker(broker, "escalation-service", lambda ctx: {})
        client.create_instance("escalate", {"orderId": 1})
        broker.run_until_idle()
        events = wi_events(broker)
        assert ("ELEMENT_ACTIVATED", "work") in events
        assert ("BOUNDARY_EVENT_OCCURRED", "deadline") not in events

        clock.advance(6_000)
        broker.tick()
        broker.run_until_idle()
        events = wi_events(broker)
        # the host was terminated by the trigger, then the boundary path ran
        assert ("ELEMENT_TERMINATED", "work") in events
        assert ("BOUNDARY_EVENT_OCCURRED", "deadline") in events
        assert ("ELEMENT_ACTIVATED", "escalate-task") in events
        assert len(escalated.handled) == 1
        broker.run_until_idle()
        assert ("ELEMENT_COMPLETED", "escalate") in wi_events(broker)
        # the abandoned job was canceled with the host
        job_intents = [
            JobIntent(r.metadata.intent).name
            for r in broker.records(0)
            if r.metadata.value_type == ValueType.JOB
        ]
        assert "CANCEL" in job_intents

    def test_timer_canceled_when_host_completes_first(self, broker, client, clock):
        client.deploy_model(timer_boundary_model())
        worker = JobWorker(broker, "slow-service", lambda ctx: {"ok": True})
        client.create_instance("escalate", {})
        broker.run_until_idle()
        events = wi_events(broker)
        assert ("ELEMENT_COMPLETED", "escalate") in events
        assert ("BOUNDARY_EVENT_OCCURRED", "deadline") not in events
        assert len(worker.handled) == 1
        timer_intents = [
            TimerIntent(r.metadata.intent).name
            for r in broker.records(0)
            if r.metadata.value_type == ValueType.TIMER
        ]
        assert "CANCELED" in timer_intents
        # firing the clock later must not resurrect anything
        clock.advance(10_000)
        broker.tick()
        broker.run_until_idle()
        assert ("BOUNDARY_EVENT_OCCURRED", "deadline") not in wi_events(broker)

    def test_non_interrupting_timer_keeps_host_active(self, broker, client, clock):
        client.deploy_model(timer_boundary_model(interrupting=False))
        escalated = JobWorker(broker, "escalation-service", lambda ctx: {})
        client.create_instance("escalate", {})
        broker.run_until_idle()
        clock.advance(6_000)
        broker.tick()
        broker.run_until_idle()
        events = wi_events(broker)
        # boundary path ran, host stays active (no termination)
        assert ("BOUNDARY_EVENT_OCCURRED", "deadline") in events
        assert ("ELEMENT_TERMINATED", "work") not in events
        assert len(escalated.handled) == 1
        # the host can still complete normally afterwards
        worker = JobWorker(broker, "slow-service", lambda ctx: {})
        broker.run_until_idle()
        events = wi_events(broker)
        assert ("ELEMENT_COMPLETED", "work") in events
        assert ("ELEMENT_COMPLETED", "escalate") in events
        assert len(worker.handled) == 1


class TestMessageBoundaryEvent:
    def test_interrupting_message_boundary(self, broker, client, clock):
        model = (
            Bpmn.create_process("cancelable")
            .start_event("start")
            .service_task("ship", type="shipping")
            .boundary_event(
                "canceled",
                message_name="cancel-order",
                correlation_key="$.orderId",
            )
            .end_event("aborted")
            .move_to("ship")
            .end_event("shipped")
            .done()
        )
        client.deploy_model(model)
        client.create_instance("cancelable", {"orderId": "o-77"})
        broker.run_until_idle()
        client.publish_message("cancel-order", "o-77", {"reason": "changed mind"})
        broker.run_until_idle()
        events = wi_events(broker)
        assert ("ELEMENT_TERMINATED", "ship") in events
        assert ("BOUNDARY_EVENT_OCCURRED", "canceled") in events
        assert ("ELEMENT_COMPLETED", "cancelable") in events
        # the boundary token carries the message payload
        occurred = [
            r for r in broker.records(0)
            if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
            and r.metadata.record_type == RecordType.EVENT
            and WI(r.metadata.intent) == WI.BOUNDARY_EVENT_OCCURRED
        ]
        assert occurred[0].value.payload == {"reason": "changed mind"}


class TestNonInterruptingMessageBoundary:
    def test_fires_repeatedly_while_host_active(self, broker, client):
        model = (
            Bpmn.create_process("notify")
            .start_event("start")
            .service_task("work", type="long-work")
            .boundary_event(
                "nudge",
                message_name="nudge-msg",
                correlation_key="$.orderId",
                interrupting=False,
            )
            .end_event("nudged")
            .move_to("work")
            .end_event("done")
            .done()
        )
        client.deploy_model(model)
        client.create_instance("notify", {"orderId": "o-1"})
        broker.run_until_idle()
        client.publish_message("nudge-msg", "o-1", {"n": 1})
        broker.run_until_idle()
        client.publish_message("nudge-msg", "o-1", {"n": 2})
        broker.run_until_idle()
        events = wi_events(broker)
        # the subscription stays open: both messages fired the boundary
        assert events.count(("BOUNDARY_EVENT_OCCURRED", "nudge")) == 2
        assert ("ELEMENT_TERMINATED", "work") not in events


class TestMultiInstanceSubProcess:
    def mi_model(self, **mi):
        builder = Bpmn.create_process("batch")
        sub = (
            builder.start_event("start")
            .sub_process("each-item", multi_instance=mi)
        )
        sub.start_event("sub-start").service_task(
            "handle", type="item-service"
        ).end_event("sub-end")
        return sub.embedded_done().end_event("done").done()

    def test_collection_spawns_one_body_per_item(self, broker, client):
        model = self.mi_model(
            input_collection="$.items", input_element="item"
        )
        client.deploy_model(model)
        seen = []
        JobWorker(
            broker, "item-service",
            lambda ctx: seen.append(
                (ctx.job.payload["loopCounter"], ctx.job.payload["item"])
            ) or {},
        )
        client.create_instance("batch", {"items": ["a", "b", "c"]})
        broker.run_until_idle()
        events = wi_events(broker)
        assert events.count(("ELEMENT_ACTIVATED", "handle")) == 3
        assert sorted(seen) == [(1, "a"), (2, "b"), (3, "c")]
        # the container completes only after ALL iterations
        assert ("ELEMENT_COMPLETED", "each-item") in events
        assert ("ELEMENT_COMPLETED", "batch") in events

    def test_cardinality_without_collection(self, broker, client):
        model = self.mi_model(cardinality=4)
        client.deploy_model(model)
        counters = []
        JobWorker(
            broker, "item-service",
            lambda ctx: counters.append(ctx.job.payload["loopCounter"]) or {},
        )
        client.create_instance("batch", {})
        broker.run_until_idle()
        assert sorted(counters) == [1, 2, 3, 4]
        assert ("ELEMENT_COMPLETED", "batch") in wi_events(broker)

    def test_empty_collection_completes_immediately(self, broker, client):
        model = self.mi_model(input_collection="$.items")
        client.deploy_model(model)
        client.create_instance("batch", {"items": []})
        broker.run_until_idle()
        events = wi_events(broker)
        assert events.count(("ELEMENT_ACTIVATED", "handle")) == 0
        assert ("ELEMENT_COMPLETED", "each-item") in events
        assert ("ELEMENT_COMPLETED", "batch") in events

    def test_output_collection_in_order_without_loop_var_leak(self, broker, client):
        model = self.mi_model(
            input_collection="$.items",
            input_element="item",
            output_collection="results",
            output_element="$.price",
        )
        client.deploy_model(model)
        JobWorker(
            broker, "item-service",
            lambda ctx: {"price": ctx.job.payload["item"] * 10},
        )
        client.create_instance("batch", {"items": [3, 1, 2]})
        broker.run_until_idle()
        completing = [
            r for r in broker.records(0)
            if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
            and WI(r.metadata.intent) == WI.ELEMENT_COMPLETING
            and r.value.activity_id == "each-item"
        ]
        payload = completing[-1].value.payload
        # outputs collected per iteration (completion order here: the job
        # result replaced the iteration payload, dropping loopCounter —
        # reference semantics; in-process workers complete in creation
        # order, so the orders coincide)
        assert payload["results"] == [30, 10, 20]
        # iteration-local variables do not leak into the container payload
        assert "loopCounter" not in payload
        assert "item" not in payload

    def test_multi_instance_without_collection_or_cardinality_rejected(
        self, broker, client
    ):
        from zeebe_tpu.gateway.client import ClientException

        model = self.mi_model()
        with pytest.raises(ClientException):
            client.deploy_model(model)

    def test_non_array_collection_raises_incident(self, broker, client):
        from zeebe_tpu.protocol.intents import IncidentIntent

        model = self.mi_model(input_collection="$.items")
        client.deploy_model(model)
        client.create_instance("batch", {"items": "not-a-list"})
        broker.run_until_idle()
        incidents = [
            IncidentIntent(r.metadata.intent).name
            for r in broker.records(0)
            if r.metadata.value_type == ValueType.INCIDENT
        ]
        assert "CREATED" in incidents

    def test_malformed_mi_input_collection_rejected_at_deploy(self, broker, client):
        """Round-3 advisor: a path like 'items' (no '$') must reject at
        deploy, not raise inside the engine hot loop at activation."""
        from zeebe_tpu.gateway.client import ClientException

        model = self.mi_model(input_collection="items")
        with pytest.raises(ClientException) as e:
            client.deploy_model(model)
        assert "input collection" in str(e.value)

    def test_malformed_mi_output_element_rejected_at_deploy(self, broker, client):
        from zeebe_tpu.gateway.client import ClientException

        model = self.mi_model(
            input_collection="$.items",
            output_collection="results",
            output_element="result",  # not a JSONPath
        )
        with pytest.raises(ClientException) as e:
            client.deploy_model(model)
        assert "output element" in str(e.value)


class TestPoisonRecordIsolation:
    """A record whose handler raises is skipped and recorded — it must not
    wedge the partition by re-raising on every drain (round-3 advisor;
    reference StreamProcessorController onError)."""

    def test_process_batch_isolates_poison_record(self):
        from zeebe_tpu.engine.interpreter import PartitionEngine
        from zeebe_tpu.models.transform.transformer import transform_model
        from zeebe_tpu.protocol.enums import RecordType, ValueType
        from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI
        from zeebe_tpu.protocol.records import (
            Record, RecordMetadata, WorkflowInstanceRecord,
        )

        engine = PartitionEngine()
        model = (
            Bpmn.create_process("p")
            .start_event("s")
            .end_event("e")
            .done()
        )
        workflows = transform_model(model)
        for wf in workflows:
            wf.key, wf.version = 1, 1
        engine.repository.merge(workflows)

        def make(pos, intent, wf_key=1):
            return Record(
                key=-1,
                position=pos,
                timestamp=0,
                metadata=RecordMetadata(
                    record_type=RecordType.COMMAND,
                    value_type=ValueType.WORKFLOW_INSTANCE,
                    intent=int(intent),
                ),
                value=WorkflowInstanceRecord(
                    bpmn_process_id="p", workflow_key=wf_key, payload={}
                ),
            )

        good1 = make(1, WI.CREATE)
        poison = make(2, WI.CREATE)
        # sabotage: make the poison record's value explode on copy
        class Bomb:
            def __deepcopy__(self, memo):
                raise RuntimeError("boom")

            def __reduce__(self):
                raise RuntimeError("boom")

        poison.value.payload = {"x": Bomb()}
        good2 = make(3, WI.CREATE)
        result = engine.process_batch([good1, poison, good2])
        # both good records produced follow-ups; the poison one is recorded
        assert len(engine.processing_failures) == 1
        assert engine.processing_failures[0][0] == 2
        created = [
            r for r in result.written
            if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
        ]
        assert len(created) >= 2
        # and a subsequent batch still processes normally
        more = engine.process_batch([make(4, WI.CREATE)])
        assert more.written
