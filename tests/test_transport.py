"""Transport tests: loopback request/response + messages.

Reference parity: ``transport/src/test`` (request/response with correlation
+ retries, single-message mode, server restart handling; 3,262 LoC).
"""

import threading
import time

import pytest

from zeebe_tpu.transport import (
    ClientTransport,
    RemoteAddress,
    ServerTransport,
    TransportError,
)


@pytest.fixture
def client():
    c = ClientTransport(default_timeout_ms=2000)
    yield c
    c.close()


class TestRequestResponse:
    def test_roundtrip(self, client):
        server = ServerTransport(request_handler=lambda p: b"echo:" + p)
        try:
            response = client.send_request(server.address, b"hello").join(5)
            assert response == b"echo:hello"
        finally:
            server.close()

    def test_many_concurrent_requests_correlate(self, client):
        server = ServerTransport(request_handler=lambda p: p * 2)
        try:
            futures = [
                client.send_request(server.address, f"m{i}".encode())
                for i in range(200)
            ]
            for i, f in enumerate(futures):
                assert f.join(5) == f"m{i}".encode() * 2
        finally:
            server.close()

    def test_concurrent_callers(self, client):
        server = ServerTransport(request_handler=lambda p: p)
        errors = []

        def caller(tid):
            try:
                for i in range(50):
                    payload = f"{tid}:{i}".encode()
                    # generous timeout: suite runs share the machine with
                    # TPU compiles; a loaded box must not flake this test
                    future = client.send_request(
                        server.address, payload, timeout_ms=15000
                    )
                    assert future.join(20) == payload
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        try:
            threads = [threading.Thread(target=caller, args=(t,)) for t in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
        finally:
            server.close()

    def test_timeout_when_no_response(self, client):
        server = ServerTransport(request_handler=lambda p: None)  # never responds
        try:
            with pytest.raises(TransportError):
                client.send_request(server.address, b"x", timeout_ms=200).join(5)
        finally:
            server.close()

    def test_connect_failure_fails_future(self, client):
        with pytest.raises(TransportError):
            client.send_request(RemoteAddress("127.0.0.1", 1), b"x").join(5)

    def test_reconnect_after_server_restart(self, client):
        server = ServerTransport(request_handler=lambda p: b"v1")
        addr = server.address
        assert client.send_request(addr, b"a").join(5) == b"v1"
        server.close()
        time.sleep(0.05)
        # same port: new server
        server2 = ServerTransport(
            host=addr.host, port=addr.port, request_handler=lambda p: b"v2"
        )
        try:
            deadline = time.time() + 5
            while time.time() < deadline:
                try:
                    assert client.send_request(addr, b"b").join(5) == b"v2"
                    break
                except TransportError:
                    time.sleep(0.05)
            else:
                pytest.fail("never reconnected")
        finally:
            server2.close()

    def test_large_payload(self, client):
        server = ServerTransport(request_handler=lambda p: p)
        try:
            payload = bytes(range(256)) * 4096  # 1 MiB
            assert client.send_request(server.address, payload, timeout_ms=10000).join(15) == payload
        finally:
            server.close()


class TestMessages:
    def test_fire_and_forget(self, client):
        received = []
        event = threading.Event()

        def on_message(p):
            received.append(p)
            if len(received) == 3:
                event.set()

        server = ServerTransport(message_handler=on_message)
        try:
            for i in range(3):
                assert client.send_message(server.address, f"m{i}".encode())
            assert event.wait(5)
            assert received == [b"m0", b"m1", b"m2"]
        finally:
            server.close()

    def test_message_to_dead_server_returns_false(self, client):
        assert not client.send_message(RemoteAddress("127.0.0.1", 1), b"x")


class TestRobustness:
    def test_malformed_frame_does_not_kill_server(self, client):
        """A garbage frame drops that connection only; the listener and other
        connections keep working (regression: struct.error killed the IO
        thread)."""
        import socket as socket_mod

        server = ServerTransport(request_handler=lambda p: b"ok:" + p)
        try:
            raw = socket_mod.create_connection(
                (server.address.host, server.address.port)
            )
            raw.sendall(b"\x00\x00\x00\x00")  # frame_length=0 < header size
            time.sleep(0.1)
            raw.close()
            assert client.send_request(server.address, b"still-up").join(5) == b"ok:still-up"
        finally:
            server.close()

    def test_stale_pooled_connection_reconnects_and_retries_once(self, client):
        """Regression: after a peer restart, the first send_request on the
        stale pooled connection must reconnect-and-retry internally instead
        of surfacing a TransportError to the caller. The server kills the
        connection under the second request; the internal retry redials and
        the caller sees a normal response."""
        import socket as socket_mod

        from zeebe_tpu.runtime.metrics import event_count

        calls = []

        def handler(payload, conn):
            calls.append(payload)
            if len(calls) == 2:
                # simulate the peer restarting under the pooled connection
                conn._conn.sock.shutdown(socket_mod.SHUT_RDWR)
                return None
            return b"ok:" + payload

        server = ServerTransport(request_handler=handler)
        try:
            assert client.send_request(server.address, b"a").join(5) == b"ok:a"
            r0 = event_count("transport_reconnects")
            # second request: the server tears the connection down instead
            # of answering — one internal reconnect-and-retry must succeed
            assert client.send_request(server.address, b"b").join(5) == b"ok:b"
            assert len(calls) == 3
            assert event_count("transport_reconnects") - r0 == 1
        finally:
            server.close()

    def test_fresh_connection_failure_is_not_retried(self, client):
        """The stale-connection retry must not loop on a server that kills
        EVERY connection: a request whose connection was dialed fresh for it
        fails without retry (and a retried request fails on the second
        kill)."""
        import socket as socket_mod

        calls = []

        def handler(payload, conn):
            calls.append(payload)
            conn._conn.sock.shutdown(socket_mod.SHUT_RDWR)
            return None

        server = ServerTransport(request_handler=handler)
        try:
            with pytest.raises(TransportError):
                client.send_request(server.address, b"x", timeout_ms=3000).join(5)
            assert len(calls) <= 2  # at most the original + one retry
        finally:
            server.close()

    def test_pending_request_fails_fast_on_disconnect(self, client):
        server = ServerTransport(request_handler=lambda p: None)
        addr = server.address
        future = client.send_request(addr, b"x", timeout_ms=30_000)
        time.sleep(0.05)
        server.close()  # drops the connection with the request in flight
        t0 = time.monotonic()
        with pytest.raises(TransportError):
            future.join(10)
        assert time.monotonic() - t0 < 5  # failed fast, not via the 30s timeout


class TestCloseListeners:
    def test_listener_fires_when_client_disconnects(self, client):
        """Server-side close listeners are the teardown hook for per-
        connection state (job subscriptions); they must fire when the peer
        goes away."""
        handles = []
        fired = threading.Event()

        def handler(payload, conn):
            handles.append(conn)
            conn.on_close(fired.set)
            return b"ok"

        server = ServerTransport(request_handler=handler)
        try:
            addr = server.address
            assert client.send_request(addr, b"hi").join(5) == b"ok"
            client.close()
            assert fired.wait(5), "close listener did not fire on disconnect"
            assert not handles[0].open
        finally:
            server.close()

    def test_listener_fires_on_server_shutdown(self):
        """Shutting the server down must also run close listeners and flip
        handles to closed — retained handles must not silently buffer."""
        c = ClientTransport(default_timeout_ms=2000)
        handles = []
        fired = threading.Event()

        def handler(payload, conn):
            handles.append(conn)
            conn.on_close(fired.set)
            return b"ok"

        server = ServerTransport(request_handler=handler)
        try:
            assert c.send_request(server.address, b"hi").join(5) == b"ok"
        finally:
            server.close()
        assert fired.wait(5), "close listener did not fire on server close"
        assert not handles[0].open
        assert handles[0].push(b"data") is False
        c.close()

    def test_listener_registered_after_close_fires_immediately(self, client):
        done = threading.Event()
        captured = []

        def handler(payload, conn):
            captured.append(conn)
            return b"ok"

        server = ServerTransport(request_handler=handler)
        try:
            assert client.send_request(server.address, b"hi").join(5) == b"ok"
        finally:
            server.close()
        captured[0].on_close(done.set)
        assert done.wait(1)

    def test_keyword_only_handler_gets_no_conn(self):
        """Arity detection must count only positional parameters: a handler
        with keyword-only extras is a one-arg handler."""
        c = ClientTransport(default_timeout_ms=2000)
        server = ServerTransport(
            request_handler=lambda payload, *, log=None: b"kw:" + payload
        )
        try:
            assert c.send_request(server.address, b"x").join(5) == b"kw:x"
        finally:
            server.close()
            c.close()

    def test_varargs_handler_gets_conn(self):
        c = ClientTransport(default_timeout_ms=2000)
        seen = []

        def handler(*args):
            seen.append(len(args))
            return b"ok"

        server = ServerTransport(request_handler=handler)
        try:
            assert c.send_request(server.address, b"x").join(5) == b"ok"
            assert seen == [2]
        finally:
            server.close()
            c.close()
