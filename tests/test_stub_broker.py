"""Client-side tests against the scriptable stub broker.

Reference parity: ``protocol-test-util/.../brokerapi/StubBrokerRule`` —
the gateway/client tests run against a FAKE broker with scripted
responses and failure injection (timeouts, rejections, leader
redirects), never a real engine. Covers both native-protocol clients:
the Python ``ClusterClient`` and the C++ ``clients/cpp/zbclient``.
"""

import os
import subprocess
import time

import pytest

from zeebe_tpu.gateway.client import ClientException
from zeebe_tpu.gateway.cluster_client import ClusterClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.models.bpmn.xml import write_model
from zeebe_tpu.protocol import codec
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import JobIntent
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import JobHeaders, JobRecord, Record
from zeebe_tpu.testing import StubBroker
from zeebe_tpu.transport import TransportError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLIENT_DIR = os.path.join(REPO, "clients", "cpp")
CLIENT_BIN = os.path.join(CLIENT_DIR, "zbclient")


@pytest.fixture
def stub():
    s = StubBroker()
    yield s
    s.close()


@pytest.fixture
def client(stub):
    c = ClusterClient([stub.address], request_timeout_ms=4000)
    yield c
    c.close()


class TestPythonClientAgainstStub:
    def test_requests_are_recorded(self, stub, client):
        record = client.create_instance("order-process", {"x": 1})
        assert record.value.workflow_instance_key > 0
        commands = stub.requests_of("command")
        assert len(commands) == 1
        sent, _ = codec.decode_record(bytes(commands[0]["frame"]))
        assert sent.value.bpmn_process_id == "order-process"
        assert sent.value.payload == {"x": 1}

    def test_rejection_surfaces_as_client_exception(self, stub, client):
        stub.reject_next("command", reason="scripted: not today")
        with pytest.raises(ClientException) as e:
            client.create_instance("order-process", {})
        assert "not today" in str(e.value)

    def test_dropped_response_is_retried(self, stub, client):
        """One lost response is survivable: the per-attempt timeout is a
        fraction of the overall budget, so the client retries and the
        second attempt answers (reference: gateway request retries)."""
        stub.drop_next("command")
        t0 = time.monotonic()
        record = client.create_instance("order-process", {})
        assert record.value.workflow_instance_key > 0
        assert time.monotonic() - t0 >= 0.9  # waited out one attempt
        assert len(stub.requests_of("command")) == 2

    def test_all_responses_dropped_times_out(self, stub, client):
        """A dead broker exhausts the overall budget and surfaces as a
        timeout."""
        for _ in range(8):
            stub.drop_next("command")
        t0 = time.monotonic()
        with pytest.raises(TransportError):
            client.create_instance("order-process", {})
        assert time.monotonic() - t0 >= 3.0  # waited out the deadline

    def test_not_leader_redirect_retries_via_topology(self, stub, client):
        stub.redirect_next("command")
        record = client.create_instance("order-process", {})
        assert record.value.workflow_instance_key > 0
        # the client re-fetched topology between the redirect and the retry
        types = [t for t, _ in stub.requests]
        assert types.count("command") == 2
        assert "topology" in types[types.index("command") + 1 :]

    def test_worker_receives_scripted_push_and_completes(self, stub, client):
        done = []
        worker = client.open_job_worker(
            "payment-service", lambda pid, rec: done.append(rec.key) or {"ok": 1}
        )
        deadline = time.time() + 5
        while time.time() < deadline and not stub.requests_of("job-subscription"):
            time.sleep(0.02)
        subs = stub.requests_of("job-subscription")
        assert subs and subs[0]["action"] == "add"
        key = int(subs[0]["subscriber_key"])

        job = Record(
            key=77,
            position=5,
            metadata=RecordMetadata(
                record_type=RecordType.EVENT,
                value_type=ValueType.JOB,
                intent=int(JobIntent.ACTIVATED),
            ),
            value=JobRecord(
                type="payment-service", retries=3, payload={"total": 9},
                headers=JobHeaders(workflow_instance_key=1),
            ),
        )
        stub.push_job(key, job)
        deadline = time.time() + 5
        while time.time() < deadline and not done:
            time.sleep(0.02)
        assert done == [77]
        # the worker sent COMPLETE and replenished its credit
        deadline = time.time() + 5
        while time.time() < deadline:
            completes = [
                m for m in stub.requests_of("command")
                if codec.decode_record(bytes(m["frame"]))[0].metadata.value_type
                == ValueType.JOB
            ]
            credits = [
                m for m in stub.requests_of("job-subscription")
                if m.get("action") == "credits"
            ]
            if completes and credits:
                break
            time.sleep(0.02)
        assert completes and credits
        worker.close()

    def test_latency_injection_within_deadline(self, stub, client):
        stub.delay("command", 500)
        t0 = time.monotonic()
        client.create_instance("order-process", {})
        assert time.monotonic() - t0 >= 0.5


@pytest.fixture(scope="module")
def client_bin():
    proc = subprocess.run(
        ["make", "-C", CLIENT_DIR], capture_output=True, text=True
    )
    if proc.returncode != 0:
        pytest.skip(f"C++ toolchain unavailable: {proc.stderr[-300:]}")
    return CLIENT_BIN


class TestCppClientAgainstStub:
    def test_topology(self, client_bin, stub):
        out = subprocess.run(
            [client_bin, stub.address.host, str(stub.address.port), "topology"],
            capture_output=True, text=True, timeout=30,
        )
        assert out.returncode == 0, out.stderr
        assert "partition 0 leader" in out.stdout

    def test_order_process_flow_with_scripted_push(self, client_bin, stub, tmp_path):
        """The full C++ flow (deploy → subscribe → create → push →
        complete) against the stub: the push is scripted, no engine."""
        model = (
            Bpmn.create_process("order-process")
            .start_event("s")
            .service_task("collect-money", type="payment-service")
            .end_event("e")
            .done()
        )
        bpmn = tmp_path / "order.bpmn"
        bpmn.write_bytes(write_model(model))

        import threading

        def push_when_subscribed():
            deadline = time.time() + 15
            while time.time() < deadline:
                subs = stub.requests_of("job-subscription")
                if subs:
                    job = Record(
                        key=901,
                        position=9,
                        metadata=RecordMetadata(
                            record_type=RecordType.EVENT,
                            value_type=ValueType.JOB,
                            intent=int(JobIntent.ACTIVATED),
                        ),
                        value=JobRecord(
                            type="payment-service", retries=3,
                            payload={"orderId": 31243},
                            headers=JobHeaders(workflow_instance_key=1),
                        ),
                    )
                    time.sleep(0.2)  # let the worker enter its poll loop
                    stub.push_job(int(subs[0]["subscriber_key"]), job)
                    return
                time.sleep(0.05)

        pusher = threading.Thread(target=push_when_subscribed)
        pusher.start()
        out = subprocess.run(
            [client_bin, stub.address.host, str(stub.address.port),
             "run-order-process", str(bpmn)],
            capture_output=True, text=True, timeout=60,
        )
        pusher.join()
        assert out.returncode == 0, (out.stdout, out.stderr)
        assert "ORDER-PROCESS-OK" in out.stdout
        assert "job pushed key=901" in out.stdout
        # COMPLETE arrived at the stub
        completes = [
            m for m in stub.requests_of("command")
            if codec.decode_record(bytes(m["frame"]))[0].metadata.value_type
            == ValueType.JOB
        ]
        assert completes

    def test_cpp_client_times_out_cleanly_on_dropped_topology(self, client_bin, stub):
        stub.drop_next("topology")
        out = subprocess.run(
            [client_bin, stub.address.host, str(stub.address.port), "topology"],
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode != 0
