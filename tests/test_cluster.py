"""Clustered-broker integration tests: 3 real brokers in one process.

Reference parity: ``qa/integration-tests/.../clustering/ClusteringRule``
(3 brokers from configs in temp dirs + a real client over real sockets;
BrokerLeaderChangeTest kills the leader and the cluster continues;
DeploymentClusteredTest deploys on one broker and runs instances on
partitions led by others).
"""

import time

import pytest

from zeebe_tpu.gateway.cluster_client import ClusterClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.runtime.cluster_broker import ClusterBroker
from zeebe_tpu.runtime.config import BrokerCfg


def wait_until(predicate, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def order_process():
    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


def make_cfg(node_id, partitions=1):
    cfg = BrokerCfg()
    cfg.network.client_port = 0
    cfg.network.management_port = 0
    cfg.network.subscription_port = 0
    cfg.metrics.port = 0
    cfg.cluster.node_id = node_id
    cfg.cluster.partitions = partitions
    cfg.raft.heartbeat_interval_ms = 30
    cfg.raft.election_timeout_ms = 200
    cfg.gossip.probe_interval_ms = 50
    cfg.gossip.probe_timeout_ms = 250
    cfg.gossip.sync_interval_ms = 500
    cfg.data.snapshot_replication_period_ms = 300
    cfg.metrics.enabled = False
    return cfg


class ClusterUnderTest:
    """ClusteringRule analogue."""

    def __init__(self, tmp_path, n_brokers=3, partitions=1, engine="host"):
        self.brokers = {}
        self.partitions = partitions
        factory = None
        if engine == "tpu":
            from zeebe_tpu.tpu import TpuPartitionEngine

            def factory(pid, broker):
                return TpuPartitionEngine(
                    pid,
                    partitions,
                    repository=broker.repository,
                    clock=broker.clock,
                )

        for i in range(n_brokers):
            node_id = f"b{i}"
            self.brokers[node_id] = ClusterBroker(
                make_cfg(node_id, partitions),
                str(tmp_path / node_id),
                engine_factory=factory,
            )
        nodes = list(self.brokers.values())
        for broker in nodes[1:]:
            broker.join([nodes[0].gossip_address]).join(10)
        # every partition replicated on every broker (replication factor n)
        for pid in range(partitions):
            addrs = {
                node_id: broker.open_partition(pid).join(10)
                for node_id, broker in self.brokers.items()
            }
            for node_id, broker in self.brokers.items():
                members = {nid: a for nid, a in addrs.items() if nid != node_id}
                broker.bootstrap_partition(pid, members)

    def await_leaders(self, timeout=60):
        def all_led():
            return all(
                any(
                    pid in b.partitions and b.partitions[pid].is_leader
                    for b in self.brokers.values()
                )
                for pid in range(self.partitions)
            )

        assert wait_until(all_led, timeout), {
            nid: {pid: p.is_leader for pid, p in b.partitions.items()}
            for nid, b in self.brokers.items()
        }

    def leader_of(self, pid):
        for broker in self.brokers.values():
            server = broker.partitions.get(pid)
            if server is not None and server.is_leader:
                return broker
        return None

    def client(self):
        return ClusterClient(
            [b.client_address for b in self.brokers.values()],
            num_partitions=self.partitions,
        )

    def close(self):
        for broker in self.brokers.values():
            broker.close()


@pytest.fixture
def cluster3(tmp_path):
    c = ClusterUnderTest(tmp_path, n_brokers=3, partitions=1)
    yield c
    c.close()


class TestClusterHappyPath:
    def test_deploy_and_complete_instance_through_the_wire(self, cluster3):
        cluster3.await_leaders()
        client = cluster3.client()
        try:
            deployed = client.deploy_model(order_process())
            assert deployed.value.deployed_workflows[0].bpmn_process_id == "order-process"

            done = []
            worker = client.open_job_worker(
                "payment-service", lambda pid, rec: done.append(rec.key) or {"paid": True}
            )
            created = client.create_instance("order-process", {"orderId": 42})
            assert created.value.workflow_instance_key > 0
            assert wait_until(lambda: len(done) == 1), done
            worker.close()
        finally:
            client.close()

    def test_all_brokers_replicate_the_log(self, cluster3):
        cluster3.await_leaders()
        client = cluster3.client()
        try:
            client.deploy_model(order_process())
            client.create_instance("order-process", partition_id=0)
            leader = cluster3.leader_of(0)
            target = leader.partitions[0].log.next_position
            assert wait_until(
                lambda: all(
                    b.partitions[0].log.next_position >= target
                    for b in cluster3.brokers.values()
                ),
            ), {
                nid: b.partitions[0].log.next_position
                for nid, b in cluster3.brokers.items()
            }
        finally:
            client.close()

    def test_topology_request_names_the_leader(self, cluster3):
        cluster3.await_leaders()
        client = cluster3.client()
        try:
            # topology is gossip-disseminated, i.e. eventually consistent —
            # poll until the reported leader matches the actual one
            def topology_converged():
                leaders = client.refresh_topology()
                leader_broker = cluster3.leader_of(0)
                return (
                    0 in leaders
                    and leader_broker is not None
                    and leaders[0].port == leader_broker.client_address.port
                )

            assert wait_until(topology_converged)
        finally:
            client.close()


class TestLeaderChange:
    def test_cluster_survives_leader_kill(self, cluster3, tmp_path):
        """BrokerLeaderChangeTest: kill the partition leader; a new leader
        takes over and clients keep working (state rebuilt by replay on the
        new leader)."""
        cluster3.await_leaders()
        client = cluster3.client()
        try:
            client.deploy_model(order_process())
            client.create_instance("order-process")

            old_leader = cluster3.leader_of(0)
            old_id = old_leader.node_id
            old_leader.close()
            del cluster3.brokers[old_id]

            assert wait_until(
                lambda: cluster3.leader_of(0) is not None
            ), "no new leader elected"

            # the new leader replayed the log: deployment + first instance
            new_leader = cluster3.leader_of(0)
            assert wait_until(
                lambda: new_leader.repository.latest("order-process") is not None,
                timeout=10,
            )

            done = []
            worker = client.open_job_worker(
                "payment-service", lambda pid, rec: done.append(rec.key)
            )
            client.create_instance("order-process")
            # both instances' jobs eventually reach the worker (the first
            # job was CREATED before the failover, rebuilt by replay)
            assert wait_until(lambda: len(done) >= 2), done
            worker.close()
        finally:
            client.close()


class TestWorkerDisconnect:
    def test_dead_worker_connection_tears_down_subscription(self, cluster3):
        """A worker whose connection dies (no clean 'remove') must not keep
        holding credits — the broker removes the subscription on connection
        close so jobs re-route to live workers."""
        cluster3.await_leaders()

        def sub_count():
            # query the CURRENT leader: a re-election installs a fresh engine
            leader = cluster3.leader_of(0)
            if leader is None or leader.partitions[0].engine is None:
                return -1
            return len(leader.partitions[0].engine.job_subscriptions)

        dead_client = cluster3.client()
        dead_client.deploy_model(order_process())
        dead_client.open_job_worker("payment-service", lambda pid, rec: None)
        assert wait_until(lambda: sub_count() >= 1, timeout=10)
        # abrupt close: transport goes away without an explicit remove
        dead_client.close()
        assert wait_until(lambda: sub_count() == 0, timeout=10)

        # a live worker now receives the work
        client = cluster3.client()
        try:
            done = []
            worker = client.open_job_worker(
                "payment-service", lambda pid, rec: done.append(rec.key) or {}
            )
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) == 1), done
            worker.close()
        finally:
            client.close()


class TestTopicSubscriptions:
    def test_push_over_the_wire_with_acks(self, cluster3):
        """Records stream to the subscriber over its own connection; acks
        persist in the log (TopicSubscriptionPushProcessor parity)."""
        from zeebe_tpu.protocol.enums import ValueType

        cluster3.await_leaders()
        client = cluster3.client()
        try:
            sub = client.open_topic_subscription("audit", lambda pid, r: None)
            client.deploy_model(order_process())
            client.create_instance("order-process")
            assert wait_until(
                lambda: any(
                    r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
                    for r in sub.records
                ),
            ), [r.metadata.value_type for r in sub.records]
            assert any(
                r.metadata.value_type == ValueType.DEPLOYMENT for r in sub.records
            )
            sub.close()
        finally:
            client.close()

    def test_resumes_after_leader_change(self, cluster3):
        """After a leader kill the subscriber reopens on the new leader and
        resumes from its last logged ack — no duplicate deliveries of acked
        records (modulo the unacked in-flight window, which re-delivers)."""
        cluster3.await_leaders()
        client = cluster3.client()
        try:
            sub = client.open_topic_subscription("resume", lambda pid, r: None, ack_batch=1)
            client.deploy_model(order_process())
            client.create_instance("order-process")
            assert wait_until(lambda: len(sub.records) >= 5)
            assert wait_until(lambda: sub._since_ack == 0)
            acked_through = sub.records[-1].position

            old = cluster3.leader_of(0)
            old_id = old.node_id
            old.close()
            del cluster3.brokers[old_id]
            assert wait_until(lambda: cluster3.leader_of(0) is not None)

            before = len(sub.records)
            client.create_instance("order-process")
            # acks are at-least-once: the in-flight tail (acks not yet
            # committed when the leader died) re-delivers first; wait until
            # records BEYOND the acked point (the new instance's) arrive
            assert wait_until(
                lambda: any(r.position > acked_through for r in sub.records[before:]),
            ), [r.position for r in sub.records[before:]]
            fresh = sub.records[before:]
            assert fresh[0].position > 0, "subscription rewound to log start"
            positions = [r.position for r in fresh]
            assert positions == sorted(positions)
            sub.close()
        finally:
            client.close()


class TestTopicOrchestration:
    def test_create_topic_brings_up_partitions_on_members(self, cluster3):
        """Reference TopicCreationService flow: (TOPIC, CREATE) on the system
        partition assigns partition ids, partitions come up on selected
        members, and the client is answered once every partition is led."""
        cluster3.await_leaders()
        client = cluster3.client()
        try:
            created = client.create_topic("orders", partitions=2, replication_factor=2)
            pids = created.value.partition_ids
            assert len(pids) == 2
            assert all(pid >= 1 for pid in pids)

            # every new partition has a leader somewhere in the cluster
            def all_led():
                return all(
                    any(
                        pid in b.partitions and b.partitions[pid].is_leader
                        for b in cluster3.brokers.values()
                    )
                    for pid in pids
                )

            assert wait_until(all_led)

            # replication factor: each partition exists on 2 brokers
            for pid in pids:
                holders = [
                    b.node_id for b in cluster3.brokers.values() if pid in b.partitions
                ]
                assert len(holders) == 2, holders

            # the new partitions process workflow instances end to end
            # (deployment fetched on demand from the system partition)
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {},
                partitions=pids,
            )
            for pid in pids:
                client.create_instance("order-process", partition_id=pid)
            assert wait_until(lambda: len(done) == 2), done
            worker.close()
        finally:
            client.close()

    def test_duplicate_topic_rejected(self, cluster3):
        from zeebe_tpu.gateway.client import ClientException

        cluster3.await_leaders()
        client = cluster3.client()
        try:
            # The FIRST create tolerates a spurious "already exists": the
            # client's command dedup (cid) is PER BROKER, so under box
            # saturation a timed-out attempt retried across a leader
            # change appends a SECOND CREATE on the new leader, and the
            # duplicate's rejection can answer the retry even though the
            # ORIGINAL command created the topic (at-least-once across
            # failover — same semantics as the reference; the PR-8 flake
            # note traced exactly this window). Either way the topic
            # exists afterwards, which is the precondition this test
            # needs; any OTHER failure still fails the test.
            try:
                client.create_topic("dup-topic", partitions=1)
            except ClientException as e:
                assert "already exists" in str(e), e
                # the documented at-least-once window was taken: leave its
                # forensics in the test log — the flight recorder holds
                # the leadership churn that made the retry cross leaders
                from zeebe_tpu.tracing.recorder import FLIGHT

                print(
                    "[duplicate-topic tolerance branch taken] recent "
                    "flight-recorder events:\n" + FLIGHT.format_slice(40)
                )
                # the tolerance is ONLY for the duplicate-command window:
                # the topic must genuinely exist (created by our own
                # first command) — any other spurious rejection fails
                leader = cluster3.leader_of(0)
                assert leader is not None
                assert "dup-topic" in leader.partitions[0].engine.topics
            with pytest.raises(ClientException, match="already exists"):
                client.create_topic("dup-topic", partitions=1)
        finally:
            client.close()


class TestSnapshotReplication:
    def test_followers_fetch_leader_snapshots(self, tmp_path):
        """SnapshotReplicationTest parity: the leader's snapshot propagates
        to followers chunk-wise; after a leader kill the new leader recovers
        from the replicated snapshot (not a full-log replay)."""
        cluster = ClusterUnderTest(tmp_path, n_brokers=3, partitions=1)
        try:
            cluster.await_leaders()
            client = cluster.client()
            try:
                client.deploy_model(order_process())
                client.create_instance("order-process")
                leader = cluster.leader_of(0)
                leader.snapshot_all()

                def followers_have_snapshot():
                    return all(
                        b.partitions[0].snapshots.storage.list()
                        for b in cluster.brokers.values()
                    )

                assert wait_until(followers_have_snapshot), {
                    nid: len(b.partitions[0].snapshots.storage.list())
                    for nid, b in cluster.brokers.items()
                }

                # kill the leader; the successor restores from the
                # replicated snapshot and keeps serving
                old_id = leader.node_id
                leader.close()
                del cluster.brokers[old_id]
                assert wait_until(lambda: cluster.leader_of(0) is not None)
                new_leader = cluster.leader_of(0)
                assert wait_until(
                    lambda: new_leader.repository.latest("order-process") is not None,
                    timeout=10,
                )
                done = []
                worker = client.open_job_worker(
                    "payment-service", lambda pid, rec: done.append(rec.key)
                )
                assert wait_until(lambda: len(done) >= 1), done
                worker.close()
            finally:
                client.close()
        finally:
            cluster.close()


class TestSelfAssembly:
    def test_cluster_bootstraps_itself_from_config(self, tmp_path):
        """Reference bootstrap flow: brokers start from config alone (contact
        points + bootstrapExpect), gossip until the expected count is alive,
        the elector bootstraps the replicated system partition, and the
        configured [[topics]] are created — no manual partition wiring."""
        from zeebe_tpu.runtime.config import TopicCfg

        brokers = {}
        first = None
        for i in range(3):
            cfg = make_cfg(f"b{i}")
            cfg.cluster.bootstrap_expect = 3
            cfg.cluster.replication_factor = 3
            # every broker ships the same config file (reference dist model)
            cfg.topics.append(TopicCfg(name="orders", partitions=2,
                                       replication_factor=2))
            if first is not None:
                cfg.cluster.initial_contact_points = [
                    f"{first.gossip_address.host}:{first.gossip_address.port}"
                ]
            broker = ClusterBroker(cfg, str(tmp_path / f"b{i}"))
            brokers[f"b{i}"] = broker
            if first is None:
                first = broker
        try:
            # system partition comes up replicated on all three
            assert wait_until(
                lambda: any(
                    0 in b.partitions and b.partitions[0].is_leader
                    for b in brokers.values()
                ),
            )
            assert wait_until(
                lambda: all(0 in b.partitions for b in brokers.values()), 20
            )
            # the configured default topic gets orchestrated
            def topic_created():
                for b in brokers.values():
                    server = b.partitions.get(0)
                    if server and server.is_leader and server.engine:
                        t = server.engine.topics.get("orders")
                        return t is not None and t["state"] == "CREATED"
                return False

            assert wait_until(topic_created)
            # and it serves real work
            client = ClusterClient([b.client_address for b in brokers.values()])
            try:
                client.deploy_model(order_process())
                done = []
                worker = client.open_job_worker(
                    "payment-service", lambda pid, rec: done.append(rec.key) or {},
                    partitions=[1],
                )
                client.create_instance("order-process", partition_id=1)
                assert wait_until(lambda: len(done) == 1), done
                worker.close()
            finally:
                client.close()
        finally:
            for b in brokers.values():
                b.close()


class TestMultiPartition:
    def test_cross_partition_message_correlation(self, tmp_path):
        """Message published on its hash-routed partition correlates to a
        workflow instance waiting on another partition, over the
        subscription transport between leader brokers."""
        cluster = ClusterUnderTest(tmp_path, n_brokers=3, partitions=3)
        try:
            cluster.await_leaders()
            client = cluster.client()
            try:
                model = (
                    Bpmn.create_process("msg-flow")
                    .start_event()
                    .message_catch_event(
                        "wait", message_name="order-paid", correlation_key="$.orderId"
                    )
                    .end_event("end")
                    .done()
                )
                client.deploy_model(model)
                created = client.create_instance(
                    "msg-flow", {"orderId": "order-9"}, partition_id=1
                )
                instance_key = created.value.workflow_instance_key
                # wait until the subscription is actually OPEN on the
                # hash-routed message partition before publishing: with
                # the default TTL of 0 a message that finds no open
                # subscription is deleted immediately (reference
                # semantics), so publishing on a fixed sleep raced the
                # cross-partition OPEN command under CI load and the
                # instance waited forever
                from zeebe_tpu.gateway.cluster_client import _correlation_hash

                msg_partition = _correlation_hash("order-9") % 3

                def subscription_open():
                    leader = cluster.leader_of(msg_partition)
                    if leader is None:
                        return False
                    engine = leader.partitions[msg_partition].engine
                    return engine is not None and any(
                        s.message_name == "order-paid"
                        and s.correlation_key == "order-9"
                        for s in engine.message_subscriptions
                    )

                assert wait_until(subscription_open), (
                    "message subscription never opened on the message partition"
                )
                client.publish_message("order-paid", "order-9", {"paid": True})

                def instance_completed():
                    leader = cluster.leader_of(1)
                    if leader is None or leader.partitions[1].engine is None:
                        return False
                    return (
                        leader.partitions[1].engine.element_instances.get(instance_key)
                        is None
                    )

                assert wait_until(instance_completed)
            finally:
                client.close()
        finally:
            cluster.close()


@pytest.mark.slow
class TestTpuClusterServing:
    """VERDICT round-2 bar: the TPU device engine is the cluster serving
    path — installed per partition on raft leadership
    (``PartitionInstallService.java:106-291`` analogue), with device
    snapshots replicating to followers and restore+replay on failover.

    Tier-2 (``pytest -m slow``): 3-broker clusters serving from the device
    kernel pay multi-ten-second cold XLA compiles PER LEADERSHIP INSTALL;
    on a shared-CPU container that exceeds the in-test client budgets and
    the whole class runs 200s+ — too heavy (and too machine-sensitive) for
    the tier-1 wall budget."""

    def test_device_partitions_serve_and_failover(self, tmp_path):
        cluster = ClusterUnderTest(tmp_path, n_brokers=3, partitions=1, engine="tpu")
        try:
            cluster.await_leaders()
            from zeebe_tpu.tpu import TpuPartitionEngine

            leader = cluster.leader_of(0)
            assert isinstance(leader.partitions[0].engine, TpuPartitionEngine)

            client = cluster.client()
            try:
                client.deploy_model(order_process())
                done = []
                worker = client.open_job_worker(
                    "payment-service", lambda pid, rec: done.append(rec.key)
                )
                client.create_instance("order-process", {"orderId": 1})
                assert wait_until(lambda: len(done) >= 1), done

                # checkpoint on the leader; followers fetch the device
                # snapshot chunk-wise (it must decode as the device envelope)
                leader.snapshot_all()

                def followers_have_snapshot():
                    return all(
                        b.partitions[0].snapshots.storage.list()
                        for b in cluster.brokers.values()
                    )

                assert wait_until(followers_have_snapshot)

                old_id = leader.node_id
                leader.close()
                del cluster.brokers[old_id]
                assert wait_until(lambda: cluster.leader_of(0) is not None)
                new_leader = cluster.leader_of(0)
                assert isinstance(new_leader.partitions[0].engine, TpuPartitionEngine)

                # the recovered device engine keeps serving: new instance
                # completes end-to-end (worker re-subscribes internally via
                # the cluster client's reconnect)
                client.create_instance("order-process", {"orderId": 2})
                assert wait_until(lambda: len(done) >= 2), done
                worker.close()
            finally:
                client.close()
        finally:
            cluster.close()

    def test_multi_partition_device_cluster(self, tmp_path):
        """Two device-backed partitions serving independently (the DP
        sharding axis of SURVEY §2: partitions are the shards)."""
        cluster = ClusterUnderTest(tmp_path, n_brokers=2, partitions=2, engine="tpu")
        try:
            cluster.await_leaders()
            from zeebe_tpu.tpu import TpuPartitionEngine

            for pid in (0, 1):
                leader = cluster.leader_of(pid)
                assert isinstance(leader.partitions[pid].engine, TpuPartitionEngine)
            client = cluster.client()
            try:
                client.deploy_model(order_process())
                done = []
                worker = client.open_job_worker(
                    "payment-service", lambda pid, rec: done.append((pid, rec.key))
                )
                for i in range(6):  # round-robins over both partitions
                    client.create_instance("order-process", {"orderId": i})
                assert wait_until(lambda: len(done) >= 6), done
                assert {pid for pid, _ in done} == {0, 1}
                worker.close()
            finally:
                client.close()
        finally:
            cluster.close()

    def test_mixed_host_and_device_workflows_across_failover(self, tmp_path):
        """A device-eligible workflow and a host-demoted one (message
        receive) served by the same device partition, surviving a leader
        kill (VERDICT round-2 item 10)."""
        cluster = ClusterUnderTest(tmp_path, n_brokers=3, partitions=1, engine="tpu")
        try:
            cluster.await_leaders()
            client = cluster.client()
            try:
                client.deploy_model(order_process())
                client.deploy_model(
                    Bpmn.create_process("await-payment")
                    .start_event()
                    .receive_task(
                        "wait", message_name="paid", correlation_key="$.oid"
                    )
                    .end_event()
                    .done()
                )
                done = []
                worker = client.open_job_worker(
                    "payment-service",
                    lambda pid, rec: done.append(rec.key),
                    timeout_ms=8_000,
                )
                client.create_instance("order-process", {"orderId": 1})
                client.create_instance("await-payment", {"oid": "a-1"})
                assert wait_until(lambda: len(done) >= 1), done

                old = cluster.leader_of(0)
                old.close()
                del cluster.brokers[old.node_id]
                assert wait_until(lambda: cluster.leader_of(0) is not None)

                # device workflow still serves...
                client.create_instance("order-process", {"orderId": 2})
                assert wait_until(lambda: len(done) >= 2), done
                # ...and the host-demoted instance still correlates
                client.publish_message("paid", "a-1", {"ok": True})

                def host_done():
                    leader = cluster.leader_of(0)
                    records = [
                        r for r in leader.partitions[0].log.reader(0)
                        if getattr(r.value, "bpmn_process_id", "") == "await-payment"
                        and r.metadata.intent == 9  # ELEMENT_COMPLETED
                    ]
                    return bool(records)

                assert wait_until(host_done)
                worker.close()
            finally:
                client.close()
        finally:
            cluster.close()

    def test_device_snapshot_under_load(self, tmp_path):
        """Checkpointing while instances are in flight (jobs outstanding)
        must capture a restorable state: kill the leader mid-stream and
        the successor finishes the backlog."""
        cluster = ClusterUnderTest(tmp_path, n_brokers=3, partitions=1, engine="tpu")
        try:
            cluster.await_leaders()
            client = cluster.client()
            try:
                client.deploy_model(order_process())
                done = []

                def handler(pid, rec):
                    done.append(rec.key)
                    return {"paid": True}

                # short job timeout: a job in flight when the leader dies
                # must re-activate within the test window (at-least-once)
                worker = client.open_job_worker(
                    "payment-service", handler, timeout_ms=8_000
                )
                for i in range(8):
                    client.create_instance("order-process", {"orderId": i})
                # snapshot while some jobs are still outstanding
                leader = cluster.leader_of(0)
                leader.snapshot_all()
                assert wait_until(
                    lambda: all(
                        b.partitions[0].snapshots.storage.list()
                        for b in cluster.brokers.values()
                    ),
                )
                old_id = leader.node_id
                leader.close()
                del cluster.brokers[old_id]
                assert wait_until(lambda: cluster.leader_of(0) is not None)
                assert wait_until(lambda: len(done) >= 8, timeout=40), len(done)
                worker.close()
            finally:
                client.close()
        finally:
            cluster.close()

    def test_fresh_worker_after_failover_gets_backlog(self, tmp_path):
        """A worker that does NOT re-subscribe across the failover: jobs
        created before the leader died are activated for a NEW worker that
        first connects to the successor (backlog activation on subscribe —
        reference ActivateJobStreamProcessor reads the log from the
        start)."""
        cluster = ClusterUnderTest(tmp_path, n_brokers=3, partitions=1, engine="tpu")
        try:
            cluster.await_leaders()
            client = cluster.client()
            try:
                client.deploy_model(order_process())
                # no worker yet: jobs pile up as CREATED
                for i in range(3):
                    client.create_instance("order-process", {"orderId": i})

                def jobs_created():
                    leader = cluster.leader_of(0)
                    return (
                        sum(
                            1 for r in leader.partitions[0].log.reader(0)
                            if r.metadata.value_type == 0  # JOB
                            and r.metadata.intent == 1  # CREATED
                        )
                        >= 3
                    )

                assert wait_until(jobs_created)
                old = cluster.leader_of(0)
                old.close()
                del cluster.brokers[old.node_id]
                assert wait_until(lambda: cluster.leader_of(0) is not None)
            finally:
                client.close()
            # a FRESH client+worker connects only after the failover
            client2 = cluster.client()
            try:
                done = []
                worker = client2.open_job_worker(
                    "payment-service", lambda pid, rec: done.append(rec.key)
                )
                assert wait_until(lambda: len(done) >= 3), done
                worker.close()
            finally:
                client2.close()
        finally:
            cluster.close()


@pytest.mark.slow
class TestTpuClusterDeadlines:
    """Tier-2 with TestTpuClusterServing (same device-engine cluster
    bring-up cost). Round-4 regression (deadline sweeps dead on clustered TPU
    partitions): the broker tick must fire job timeouts, timer events and
    host-oracle deadlines on a TPU-backed partition — the async device
    probe (``tpu/engine.deadlines_due_probe``) gates the expensive device
    column sweeps, while host-oracle deadlines are swept unconditionally
    every tick. Reference periodic jobs: ``JobTimeOutStreamProcessor``,
    ``MessageTimeToLiveChecker`` (broker-core job/message processors)."""

    def _cluster(self, tmp_path):
        return ClusterUnderTest(tmp_path, n_brokers=3, partitions=1, engine="tpu")

    def test_device_timer_fires_through_the_tick(self, tmp_path):
        cluster = self._cluster(tmp_path)
        try:
            cluster.await_leaders()
            from zeebe_tpu.tpu import TpuPartitionEngine

            assert isinstance(
                cluster.leader_of(0).partitions[0].engine, TpuPartitionEngine
            )
            client = cluster.client()
            try:
                model = (
                    Bpmn.create_process("timer-flow")
                    .start_event()
                    .timer_catch_event("wait", duration_ms=700)
                    .service_task("after", type="timer-done")
                    .end_event("end")
                    .done()
                )
                # device-eligible: the timer lives in the DEVICE timer
                # table; its TRIGGER only fires if the probe-gated sweep runs
                from zeebe_tpu.models.transform import transform_model
                from zeebe_tpu.tpu.graph import check_device_compatible

                wf = transform_model(model)[0]
                assert check_device_compatible(wf) is None

                client.deploy_model(model)
                done = []
                worker = client.open_job_worker(
                    "timer-done", lambda pid, rec: done.append(rec.key) or {}
                )
                client.create_instance("timer-flow", {})
                assert wait_until(lambda: len(done) == 1, timeout=30), done
                worker.close()
            finally:
                client.close()
        finally:
            cluster.close()

    def test_device_job_timeout_reactivates_through_the_tick(self, tmp_path):
        from zeebe_tpu.gateway.cluster_client import RemoteJobWorker

        class NoCompleteWorker(RemoteJobWorker):
            """Takes pushes but never completes/fails: the job can only
            come back via a server-side TIME_OUT sweep."""

            def _on_record(self, partition, record, epoch=-1):
                self.handled.append(record)
                self._return_credit(partition)

        cluster = self._cluster(tmp_path)
        try:
            cluster.await_leaders()
            client = cluster.client()
            try:
                client.deploy_model(order_process())
                worker = NoCompleteWorker(
                    client, "payment-service", handler=None,
                    worker_name="sloth", credits=4, timeout_ms=800,
                    partitions=[0],
                )
                client.create_instance("order-process", {"orderId": 1})
                # 1st push = activation; 2nd push of the SAME job key can
                # only happen after the tick swept its deadline (TIME_OUT)
                assert wait_until(
                    lambda: len(worker.handled) >= 2, timeout=30
                ), [r.key for r in worker.handled]
                keys = {r.key for r in worker.handled}
                assert len(keys) == 1, keys
                worker.close()
            finally:
                client.close()
        finally:
            cluster.close()

    def test_host_demoted_timer_fires_every_tick_unconditionally(self, tmp_path):
        """Host-oracle deadlines (device-INELIGIBLE workflows inside a TPU
        partition) must fire even when no device-side deadline is ever due
        — the round-4 bug gated them behind the device probe."""
        cluster = self._cluster(tmp_path)
        try:
            cluster.await_leaders()
            client = cluster.client()
            try:
                builder = (
                    Bpmn.create_process("host-timer-flow")
                    .start_event()
                    .timer_catch_event("wait", duration_ms=700)
                    .service_task("after", type="host-timer-done")
                )
                sub = builder.sub_process(
                    "each", multi_instance={"input_collection": "$.items",
                                            "input_element": "item"}
                )
                sub.start_event("s").end_event("e")
                model = sub.embedded_done().end_event("end").done()

                from zeebe_tpu.models.transform import transform_model
                from zeebe_tpu.tpu.graph import check_device_compatible

                wf = transform_model(model)[0]
                assert check_device_compatible(wf) is not None  # host-demoted

                client.deploy_model(model)
                done = []
                worker = client.open_job_worker(
                    "host-timer-done", lambda pid, rec: done.append(rec.key) or {}
                )
                client.create_instance("host-timer-flow", {"items": []})
                assert wait_until(lambda: len(done) == 1, timeout=30), done
                worker.close()
            finally:
                client.close()
        finally:
            cluster.close()
