"""Deployment rejection surface for unsupported BPMN 2.0 constructs.

Reference: ``broker-core/.../workflow/model/validation/`` — a resource the
engine cannot execute rejects at deploy with the element id and a reason;
silently dropping an element would run a different process than the one
modeled. The executable subset and the rejection behavior are documented
in ``docs/reference/bpmn-workflows.md``.
"""

import os

import pytest

from zeebe_tpu.gateway import ZeebeClient
from zeebe_tpu.gateway.client import ClientException
from zeebe_tpu.protocol.records import DeploymentResource
from zeebe_tpu.models.bpmn.xml import UnsupportedBpmnElement, read_model
from zeebe_tpu.runtime import Broker, ControlledClock

REF_SAMPLES = "/root/reference/samples/src/main/resources"
REF_QA = "/root/reference/qa/integration-tests/src/test/resources/workflows"
REF_GATEWAY = "/root/reference/gateway/src/test/resources/workflows"


def _deploy(xml: bytes):
    broker = Broker(num_partitions=1, clock=ControlledClock())
    try:
        client = ZeebeClient(broker)
        return client.deploy_resources([
            DeploymentResource(
                resource=xml, resource_type="BPMN_XML",
                resource_name="wf.bpmn",
            )
        ])
    finally:
        broker.close()


UNSUPPORTED = """<?xml version="1.0" encoding="UTF-8"?>
<bpmn:definitions xmlns:bpmn="http://www.omg.org/spec/BPMN/20100524/MODEL">
  <bpmn:process id="p" isExecutable="true">
    <bpmn:startEvent id="s"/>
    <bpmn:{tag} id="bad-{tag}"/>
    <bpmn:endEvent id="e"/>
    <bpmn:sequenceFlow id="f1" sourceRef="s" targetRef="bad-{tag}"/>
    <bpmn:sequenceFlow id="f2" sourceRef="bad-{tag}" targetRef="e"/>
  </bpmn:process>
</bpmn:definitions>
"""


class TestUnsupportedElementRejection:
    @pytest.mark.parametrize("tag", [
        "userTask", "scriptTask", "callActivity", "businessRuleTask",
        "eventBasedGateway", "inclusiveGateway", "intermediateThrowEvent",
        "manualTask", "sendTask", "transaction",
    ])
    def test_reader_raises_with_element_id(self, tag):
        xml = UNSUPPORTED.format(tag=tag)
        with pytest.raises(UnsupportedBpmnElement) as e:
            read_model(xml)
        assert tag in str(e.value)
        assert f"bad-{tag}" in str(e.value)
        assert "supported elements" in str(e.value)

    def test_deployment_rejects_with_diagnostic(self):
        xml = UNSUPPORTED.format(tag="callActivity").encode()
        with pytest.raises(ClientException) as e:
            _deploy(xml)
        assert "callActivity" in str(e.value)
        assert "bad-callActivity" in str(e.value)

    def test_non_executable_content_still_parses(self):
        xml = """<?xml version="1.0"?>
<bpmn:definitions xmlns:bpmn="http://www.omg.org/spec/BPMN/20100524/MODEL">
  <bpmn:process id="p" isExecutable="true">
    <bpmn:documentation>docs are fine</bpmn:documentation>
    <bpmn:extensionElements/>
    <bpmn:laneSet id="lanes"/>
    <bpmn:textAnnotation id="note"/>
    <bpmn:association id="assoc"/>
    <bpmn:dataObject id="data"/>
    <bpmn:startEvent id="s"/>
    <bpmn:endEvent id="e"/>
    <bpmn:sequenceFlow id="f" sourceRef="s" targetRef="e"/>
  </bpmn:process>
</bpmn:definitions>"""
        model = read_model(xml)
        assert "p" in model.elements


class TestReferenceCorpus:
    """The reference's own sample/test BPMN files within the executable
    subset must parse and deploy."""

    @pytest.mark.parametrize("path", [
        os.path.join(REF_SAMPLES, "demoProcess.bpmn"),
        os.path.join(REF_QA, "one-task-process.bpmn"),
    ])
    def test_reference_sample_parses_and_deploys(self, path):
        if not os.path.exists(path):
            pytest.skip(f"reference file missing: {path}")
        with open(path, "rb") as f:
            xml = f.read()
        model = read_model(xml)
        assert model.processes
        deployed = _deploy(xml)
        assert deployed is not None

    def test_non_executable_process_parses(self):
        path = os.path.join(REF_QA, "nonExecutableProcess.bpmn")
        if not os.path.exists(path):
            pytest.skip("reference file missing")
        with open(path, "rb") as f:
            model = read_model(f.read())
        assert model.processes

    def test_abstract_task_rejects_like_the_reference_broker(self):
        """The gateway test resource uses a bare <bpmn:task> — an element
        the 2018 reference broker's transformer does not execute either;
        deployment rejects with the element id."""
        path = os.path.join(REF_GATEWAY, "one-task-process.bpmn")
        if not os.path.exists(path):
            pytest.skip("reference file missing")
        with open(path, "rb") as f:
            xml = f.read()
        with pytest.raises(ClientException) as e:
            _deploy(xml)
        assert "task" in str(e.value)
