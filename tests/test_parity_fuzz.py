"""Property-based event-replay parity fuzzing: random workflow graphs x
random command/worker interleavings, host oracle vs TPU device engine.

The architecture keeps two full engines semantically equivalent (the host
interpreter and the SIMD kernel); hand-written scenarios cover the known
paths, this fuzzer searches for divergence in their composition — the
cheap, high-yield test for exactly this design (SURVEY.md §5: replay
determinism is the correctness contract; the event log IS the trace).

Workflows are assembled from randomly chosen pattern segments (service
task, exclusive gateway with json-el conditions, parallel fork/join, timer
catch, message receive task, sub-process, timer boundary event,
cardinality and collection multi-instance sub-process) chained linearly —
every generated model is valid by construction while the cross product of
segments x payloads x worker behaviors x cancels x payload-updates x
incident-resolves explores the state space.

Device residency (round-4 eligibility, re-audited round 5): message
receive, timer catch, boundary events, plain sub-processes and
CARDINALITY multi-instance all run ON DEVICE; only collection-driven
multi-instance ("mi" segments — collections have no device column form)
demotes the workflow to the TPU broker's host-backed path. Every case
ASSERTS its expected residency (check_device_compatible + the engine's
device/host record counters), so a silent eligibility regression —
device workflows quietly demoting to the host path while records stay
identical — fails the fuzz, not just the perf ceiling. Cancel/
update-payload still demote individual instances mid-flight on either
kind of workflow (the demotion boundary the round-3 fuzz hunted).

Seed policy (VERDICT round-2 item 6, revised for CI determinism): tier-1
fuzzes a FIXED base seed (785858646 — itself a past real-divergence
finder: device-emitted job incidents lost their failure-event position and
RESOLVE silently no-opped) so CI is reproducible run-to-run;
``FUZZ_SEED=<n>`` overrides the base, ``FUZZ_CASES=<n>`` scales the case
count (nightly: ``FUZZ_CASES=200``). The SEARCHING time-drawn base lives
behind ``@pytest.mark.slow`` (tier-2) and prints its drawn base up front;
every failing case prints its exact seed in the failure message — add it
to FAILING_SEEDS (V1 scenarios) or fold it into the fixed base to regress
it forever.
"""

import os
import random
import time

import pytest

from zeebe_tpu.models.bpmn.builder import Bpmn

from tests.test_tpu_parity import DualRig, record_signature


N_CASES = int(os.environ.get("FUZZ_CASES", "12"))
N_SEGMENTS = (1, 4)   # segments per workflow
N_INSTANCES = (1, 6)  # instances per case
# seeds that found real bugs, pinned forever (round 3: list-payload
# demotion crashes, host timer/job sweep stalls, keyspace collisions)
FAILING_SEEDS = [785538535, 785538536, 785538537]

# fixed regression base + a second fixed base for tier-1 (deterministic
# CI); the time-drawn searching base runs in tier-2 (slow)
_FIXED_BASE = 7_000
_RANDOM_BASE = int(os.environ.get("FUZZ_SEED", "0")) or 785_858_646


_DRAWN = []


def _drawn_base() -> int:
    """Searching base for the slow tier, drawn ONCE per run (memoized —
    re-drawing per parametrized case would drift the base with wall clock
    and cover a gapped seed set instead of base..base+N-1); FUZZ_SEED
    pins it too."""
    if not _DRAWN:
        _DRAWN.append(int(os.environ.get("FUZZ_SEED", "0")) or (
            int(time.time()) % 1_000_000_000 + 100_000
        ))
        print(f"fuzz time-drawn base: {_DRAWN[0]}")
    return _DRAWN[0]

# V1 = the round-3 generator's kind table. FAILING_SEEDS were found under
# V1 and every draw below is order-stable against it, so the pinned seeds
# reproduce their ORIGINAL scenarios byte-for-byte; fresh fuzzing draws
# from the extended table (cardmi = device cardinality MI, round 4).
SEGMENT_KINDS_V1 = (
    "task", "xor", "fork", "timer", "task",
    "receive", "sub", "boundary", "mi",
)
SEGMENT_KINDS = SEGMENT_KINDS_V1 + ("cardmi",)

# collection-driven MI is the ONLY remaining host-demoting segment;
# everything else must compile to the device graph (round-4 kernel)
HOST_ONLY_KINDS = {"mi"}


def build_random_model(rng: random.Random, pid: str, kinds=SEGMENT_KINDS):
    b = Bpmn.create_process(pid).start_event(f"{pid}-start")
    n = rng.randint(*N_SEGMENTS)
    used = []
    for i in range(n):
        kind = rng.choice(kinds)
        used.append(kind)
        if kind == "task":
            b = b.service_task(f"{pid}-t{i}", type=f"{pid}-svc{i % 2}")
        elif kind == "xor":
            b = b.exclusive_gateway(f"{pid}-x{i}")
            threshold = rng.choice([10, 50, 250])
            hi = b.branch(f"$.orderValue >= {threshold}").service_task(
                f"{pid}-hi{i}", type=f"{pid}-svc0"
            )
            lo = b.branch(default=True).service_task(
                f"{pid}-lo{i}", type=f"{pid}-svc1"
            )
            hi.exclusive_gateway(f"{pid}-xm{i}")
            lo.connect_to(f"{pid}-xm{i}")
            b = b.move_to(f"{pid}-xm{i}")
        elif kind == "fork":
            b = b.parallel_gateway(f"{pid}-f{i}")
            br1 = b.branch().service_task(f"{pid}-a{i}", type=f"{pid}-svc0")
            br2 = b.branch().service_task(f"{pid}-b{i}", type=f"{pid}-svc1")
            br1.parallel_gateway(f"{pid}-j{i}")
            br2.connect_to(f"{pid}-j{i}")
            b = b.move_to(f"{pid}-j{i}")
        elif kind == "timer":
            b = b.timer_catch_event(
                f"{pid}-w{i}", duration_ms=rng.choice([5_000, 30_000])
            )
        elif kind == "receive":
            # message correlation — device-served since round 4 (open/
            # publish/correlate/close run in the kernel's message tables)
            b = b.receive_task(
                f"{pid}-r{i}",
                message_name=f"{pid}-msg{i}",
                correlation_key="$.corr",
            )
        elif kind == "sub":
            sub = b.sub_process(f"{pid}-s{i}")
            sub.start_event(f"{pid}-ss{i}").service_task(
                f"{pid}-st{i}", type=f"{pid}-svc{i % 2}"
            ).end_event(f"{pid}-se{i}")
            b = sub.embedded_done()
        elif kind == "boundary":
            b = b.service_task(f"{pid}-bt{i}", type=f"{pid}-slow{i}")
            b = b.boundary_event(
                f"{pid}-bd{i}",
                duration_ms=rng.choice([5_000, 30_000]),
                interrupting=rng.random() < 0.7,
            )
            b = b.service_task(f"{pid}-esc{i}", type=f"{pid}-svc0")
            b = b.exclusive_gateway(f"{pid}-bm{i}")
            b = b.move_to(f"{pid}-bt{i}")
            b = b.connect_to(f"{pid}-bm{i}")
            b = b.move_to(f"{pid}-bm{i}")
        elif kind == "mi":
            sub = b.sub_process(
                f"{pid}-m{i}",
                multi_instance={
                    "input_collection": "$.items",
                    "input_element": "item",
                    "output_collection": f"out{i}",
                },
            )
            sub.start_event(f"{pid}-ms{i}").service_task(
                f"{pid}-mt{i}", type=f"{pid}-svc{i % 2}"
            ).end_event(f"{pid}-me{i}")
            b = sub.embedded_done()
        elif kind == "cardmi":
            # cardinality MI runs ON DEVICE (round 4) — fan-out through
            # the kernel's emission slots, no collection involved
            sub = b.sub_process(
                f"{pid}-cm{i}",
                multi_instance={"cardinality": rng.randint(1, 3)},
            )
            sub.start_event(f"{pid}-cs{i}").service_task(
                f"{pid}-ct{i}", type=f"{pid}-svc{i % 2}"
            ).end_event(f"{pid}-ce{i}")
            b = sub.embedded_done()
    return b.end_event(f"{pid}-end").done(), used


def run_case(seed: int, kinds=SEGMENT_KINDS, force_list_payloads=None):
    rng = random.Random(seed)
    rig = DualRig()
    try:
        pid = f"fuzz{seed}"
        model, segments = build_random_model(rng, pid, kinds)
        n_instances = rng.randint(*N_INSTANCES)
        # deterministic worker behavior: decisions keyed on the job's
        # payload (identical across both rigs when parity holds)
        fail_mod = rng.choice([0, 3, 5])       # fail every k-th orderId once
        exhaust_mod = rng.choice([0, 0, 4])    # fail to zero retries → incident
        # draw order below matches the V1 generator exactly (items ALWAYS
        # drawn, in its original position) so pinned seeds reproduce their
        # original scenarios; whether the list is KEPT is decided after
        # the legacy stream, see list_payloads below
        payloads = [
            {
                "orderValue": rng.choice([5, 25, 100, 400]),
                "orderId": i,
                "corr": f"c-{i}",
                "items": [1, 2][: rng.randint(1, 2)],
                "tag": rng.choice(["a", "bb", "ccc"]),
            }
            for i in range(n_instances)
        ]
        cancel_ids = set(
            i for i in range(n_instances) if rng.random() < 0.2
        )
        update_ids = set(
            i for i in range(n_instances) if rng.random() < 0.2
        )
        timer_advances = rng.randint(1, 3)
        # a LIST payload value has no device column form: instances carrying
        # one are born host-side even under a device-compiled workflow.
        # Collection-MI needs $.items; other cases get flat scalar payloads
        # so device-eligible workflows REALLY run on device — plus a random
        # 15% that keep the list anyway to keep fuzzing the payload-demotion
        # boundary (the round-3 bug class). Drawn AFTER the legacy stream so
        # pinned V1 seeds reproduce; they force list_payloads=True (the V1
        # behavior) via force_list_payloads.
        needs_items = any(k in HOST_ONLY_KINDS for k in segments)
        list_payloads = (
            force_list_payloads
            if force_list_payloads is not None
            else needs_items or rng.random() < 0.15
        )
        if not list_payloads:
            for p in payloads:
                p.pop("items")
        has_receive = any(k == "receive" for k in segments)
        msg_names = [
            f"{pid}-msg{i}" for i, k in enumerate(segments) if k == "receive"
        ]

        def scenario(broker, client, clock):
            from zeebe_tpu.gateway import JobWorker
            from zeebe_tpu.protocol.enums import ValueType
            from zeebe_tpu.protocol.intents import IncidentIntent

            client.deploy_model(model)

            def handler(ctx):
                oid = int(ctx.payload.get("orderId", 0))
                retries = int(ctx.job.retries)
                if exhaust_mod and oid % exhaust_mod == 1 and retries > 0:
                    # drive retries to zero → incident
                    ctx.fail(retries=0)
                    return None
                if fail_mod and oid % fail_mod == 0 and retries > 1:
                    ctx.fail(retries=retries - 1)
                    return None
                return {"res": oid * 2}

            workers = [
                JobWorker(broker, f"{pid}-svc{k}", handler) for k in (0, 1)
            ]
            created = []
            for i, payload in enumerate(payloads):
                inst = client.create_instance(pid, dict(payload))
                created.append(inst.workflow_instance_key)
                if i in update_ids:
                    broker.run_until_idle()
                    try:
                        client.update_payload(
                            created[-1], {**payload, "updated": True}
                        )
                    except Exception:
                        pass  # completed already: rejection compared anyway
                if i in cancel_ids:
                    broker.run_until_idle()
                    try:
                        client.cancel_instance(created[-1])
                    except Exception:
                        pass  # already completed: rejection is fine (parity
                        # still compares the rejection records)
            broker.run_until_idle()
            # correlate messages for receive segments (after first idle so
            # open subscriptions exist — order is deterministic)
            if has_receive:
                for name in msg_names:
                    for i in range(n_instances):
                        client.publish_message(
                            name, f"c-{i}", {"paid": i}
                        )
                broker.run_until_idle()
            for _ in range(timer_advances):
                clock.advance(31_000)
                broker.tick()
                broker.run_until_idle()
            # resolve any open incidents once via payload update
            incidents = [
                r for r in broker.records(0)
                if r.metadata.value_type == ValueType.INCIDENT
                and r.metadata.intent == int(IncidentIntent.CREATED)
            ]
            for inc in incidents:
                try:
                    client.resolve_incident(
                        inc.key,
                        {"orderId": 999, "orderValue": 100, "corr": "c-0",
                         **({"items": [1]} if list_payloads else {})},
                    )
                except Exception:
                    pass
            broker.run_until_idle()
            for _ in range(2):
                clock.advance(31_000)
                broker.tick()
                broker.run_until_idle()
            return workers

        rig.run(scenario)
        rig.assert_parity()
        oracle_records = record_signature(rig.brokers[0].records(0))
        assert oracle_records, "fuzz case produced no records"

        # device-residency audit: the case must run where the eligibility
        # rules say it runs, and the rules must say what we expect
        from zeebe_tpu.models.transform.transformer import transform_model
        from zeebe_tpu.tpu.graph import check_device_compatible

        wf = transform_model(model)[0]
        reason = check_device_compatible(wf)
        expect_host = bool(set(segments) & HOST_ONLY_KINDS)
        assert (reason is not None) == expect_host, (
            f"eligibility drift: segments={segments} "
            f"expected {'host' if expect_host else 'device'}, "
            f"check_device_compatible said {reason!r}"
        )
        engine = rig.brokers[1].partitions[0].engine
        wf_keys = {w.key for w in engine.repository.by_key.values()}
        residency = (
            "host" if expect_host
            else "payload-demoted" if list_payloads
            else "device"
        )
        print(
            f"fuzz seed {seed}: segments={segments} residency={residency} "
            f"device_records={engine.device_records_processed} "
            f"host_records={engine.host_records_processed}"
        )
        if expect_host:
            assert engine._host_only_keys & wf_keys or not wf_keys, (
                "collection-MI workflow not registered host-only"
            )
        elif not list_payloads:
            # flat payloads + device-compiled workflow: the instance
            # lifecycle MUST have run through the kernel
            assert engine.device_records_processed > 0, (
                f"device-eligible case produced ZERO device-processed "
                f"records (segments={segments}) — the case silently ran "
                f"on the host path"
            )
    finally:
        rig.close()


def _run_with_repro(seed):
    try:
        run_case(seed)
    except AssertionError:
        pytest.fail(
            f"parity divergence at seed {seed} — reproduce with "
            f"FUZZ_SEED={seed} FUZZ_CASES=1, or run_case({seed}); "
            f"shrink via N_SEGMENTS/N_INSTANCES"
        )


@pytest.mark.parametrize("case", range(N_CASES // 2))
def test_fuzz_parity_pinned_space(case):
    _run_with_repro(_FIXED_BASE + case)


@pytest.mark.parametrize("case", range(N_CASES - N_CASES // 2))
def test_fuzz_parity_random_space(case):
    # FIXED base in tier-1: the same cases replay every CI run (the
    # time-drawn search lives in the slow tier below)
    seed = _RANDOM_BASE + case
    print(f"fuzz random seed: {seed}")
    _run_with_repro(seed)


@pytest.mark.slow
@pytest.mark.parametrize("case", range(N_CASES))
def test_fuzz_parity_time_drawn_space(case):
    # searching tier: a fresh base per run; the drawn seed prints before
    # the case runs AND rides the failure message, so any hit reproduces
    # with FUZZ_SEED=<seed> FUZZ_CASES=1
    seed = _drawn_base() + case
    print(f"fuzz time-drawn seed: {seed}")
    _run_with_repro(seed)


@pytest.mark.parametrize("seed", FAILING_SEEDS)
def test_pinned_seeds(seed):
    # V1 kind table + forced list payloads = the exact round-3 scenarios
    # these seeds crashed (list-payload demotion, sweep stalls, key
    # collisions) — pinned forever in their original form
    run_case(seed, kinds=SEGMENT_KINDS_V1, force_list_payloads=True)


# round-8 mega-pass pin: config-5-shaped models — MI fan-out ONLY
# (device cardinality MI + collection MI subprocesses), the acid-test
# shape for the fused phase-B/C gather pass (bench config
# "5-multi-instance-subprocess"). Fan-out bursts stress exactly the
# slices the pass absorbed: the 3-role ei row gather, emission-slot
# assembly, and the packed output compaction.
CONFIG5_SEEDS = [785858646, 785858653]


@pytest.mark.parametrize("seed", CONFIG5_SEEDS)
def test_pinned_config5_fanout(seed):
    run_case(seed, kinds=("cardmi", "mi", "cardmi"))
