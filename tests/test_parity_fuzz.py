"""Property-based event-replay parity fuzzing: random workflow graphs x
random command/worker interleavings, host oracle vs TPU device engine.

The architecture keeps two full engines semantically equivalent (the host
interpreter and the SIMD kernel); hand-written scenarios cover the known
paths, this fuzzer searches for divergence in their composition — the
cheap, high-yield test for exactly this design (SURVEY.md §5: replay
determinism is the correctness contract; the event log IS the trace).

Workflows are assembled from randomly chosen pattern segments (service
task, exclusive gateway with json-el conditions, parallel fork/join, timer
catch) chained linearly — every generated model is valid by construction
while the cross product of segments x payloads x worker behaviors x
cancels explores the state space. Each case prints its seed on failure;
re-run a failing seed directly with
``pytest tests/test_parity_fuzz.py -k seed_<n>`` after adding it to
FAILING_SEEDS, or shrink by lowering N_SEGMENTS / N_INSTANCES.
"""

import random

import pytest

from zeebe_tpu.models.bpmn.builder import Bpmn

from tests.test_tpu_parity import DualRig, record_signature


N_CASES = 12          # per CI run; each case is a full dual-engine scenario
N_SEGMENTS = (1, 4)   # segments per workflow
N_INSTANCES = (1, 6)  # instances per case
FAILING_SEEDS = []    # pin seeds here to reproduce/regress


def build_random_model(rng: random.Random, pid: str):
    b = Bpmn.create_process(pid).start_event(f"{pid}-start")
    n = rng.randint(*N_SEGMENTS)
    for i in range(n):
        kind = rng.choice(["task", "xor", "fork", "timer", "task"])
        if kind == "task":
            b = b.service_task(f"{pid}-t{i}", type=f"{pid}-svc{i % 2}")
        elif kind == "xor":
            b = b.exclusive_gateway(f"{pid}-x{i}")
            threshold = rng.choice([10, 50, 250])
            hi = b.branch(f"$.orderValue >= {threshold}").service_task(
                f"{pid}-hi{i}", type=f"{pid}-svc0"
            )
            lo = b.branch(default=True).service_task(
                f"{pid}-lo{i}", type=f"{pid}-svc1"
            )
            hi.exclusive_gateway(f"{pid}-xm{i}")
            lo.connect_to(f"{pid}-xm{i}")
            b = b.move_to(f"{pid}-xm{i}")
        elif kind == "fork":
            b = b.parallel_gateway(f"{pid}-f{i}")
            br1 = b.branch().service_task(f"{pid}-a{i}", type=f"{pid}-svc0")
            br2 = b.branch().service_task(f"{pid}-b{i}", type=f"{pid}-svc1")
            br1.parallel_gateway(f"{pid}-j{i}")
            br2.connect_to(f"{pid}-j{i}")
            b = b.move_to(f"{pid}-j{i}")
        elif kind == "timer":
            b = b.timer_catch_event(
                f"{pid}-w{i}", duration_ms=rng.choice([5_000, 30_000])
            )
    return b.end_event(f"{pid}-end").done(), n


def run_case(seed: int):
    rng = random.Random(seed)
    rig = DualRig()
    try:
        pid = f"fuzz{seed}"
        model, n_segments = build_random_model(rng, pid)
        n_instances = rng.randint(*N_INSTANCES)
        # deterministic worker behavior: decisions keyed on the job's
        # payload (identical across both rigs when parity holds)
        fail_mod = rng.choice([0, 3, 5])       # fail every k-th orderId once
        payloads = [
            {
                "orderValue": rng.choice([5, 25, 100, 400]),
                "orderId": i,
                "tag": rng.choice(["a", "bb", "ccc"]),
            }
            for i in range(n_instances)
        ]
        cancel_ids = set(
            i for i in range(n_instances) if rng.random() < 0.25
        )
        timer_advances = rng.randint(1, 3)

        def scenario(broker, client, clock):
            from zeebe_tpu.gateway import JobWorker

            client.deploy_model(model)

            def handler(ctx):
                oid = int(ctx.payload.get("orderId", 0))
                if (
                    fail_mod
                    and oid % fail_mod == 0
                    and int(ctx.job.retries) > 1
                ):
                    ctx.fail(retries=ctx.job.retries - 1)
                    return None
                return {"res": oid * 2}

            workers = [
                JobWorker(broker, f"{pid}-svc{k}", handler) for k in (0, 1)
            ]
            created = []
            for i, payload in enumerate(payloads):
                inst = client.create_instance(pid, dict(payload))
                created.append(inst.workflow_instance_key)
                if i in cancel_ids:
                    broker.run_until_idle()
                    try:
                        client.cancel_instance(created[-1])
                    except Exception:
                        pass  # already completed: rejection is fine (parity
                        # still compares the rejection records)
            broker.run_until_idle()
            for _ in range(timer_advances):
                clock.advance(31_000)
                broker.tick()
                broker.run_until_idle()
            return workers

        rig.run(scenario)
        rig.assert_parity()
        oracle_records = record_signature(rig.brokers[0].records(0))
        assert oracle_records, "fuzz case produced no records"
    finally:
        rig.close()


@pytest.mark.parametrize("case", range(N_CASES))
def test_fuzz_parity(case):
    seed = 7_000 + case
    try:
        run_case(seed)
    except AssertionError:
        pytest.fail(
            f"parity divergence at seed {seed} — reproduce with "
            f"run_case({seed}); shrink via N_SEGMENTS/N_INSTANCES"
        )


@pytest.mark.parametrize("seed", FAILING_SEEDS)
def test_pinned_seeds(seed):
    run_case(seed)
