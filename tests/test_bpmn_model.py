"""BPMN model / builder / XML / YAML tests (reference: bpmn-model tests)."""

import pytest

from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.models.bpmn.model import (
    ElementType,
    ExclusiveGateway,
    ServiceTask,
)
from zeebe_tpu.models.bpmn.validation import validate_model
from zeebe_tpu.models.bpmn.xml import read_model, write_model
from zeebe_tpu.models.bpmn.yaml_front import read_yaml_workflow


def order_process():
    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


class TestBuilder:
    def test_linear_process(self):
        model = order_process()
        task = model.element("collect-money")
        assert isinstance(task, ServiceTask)
        assert task.task_definition.type == "payment-service"
        assert len(task.incoming) == 1
        assert len(task.outgoing) == 1
        assert task.incoming[0].source_id == "start"
        assert task.outgoing[0].target_id == "end"

    def test_exclusive_gateway_branches(self):
        b = Bpmn.create_process("p").start_event("start").exclusive_gateway("split")
        b.branch("$.orderValue >= 100").service_task(
            "ship-insured", type="ship"
        ).end_event("end1")
        b.branch(default=True).service_task("ship-plain", type="ship").end_event("end2")
        model = b.done()

        gw = model.element("split")
        assert isinstance(gw, ExclusiveGateway)
        assert len(gw.outgoing) == 2
        conditions = {f.target_id: f.condition_expression for f in gw.outgoing}
        assert conditions["ship-insured"] == "$.orderValue >= 100"
        assert conditions["ship-plain"] is None
        assert gw.default_flow_id == [
            f.id for f in gw.outgoing if f.target_id == "ship-plain"
        ][0]

    def test_parallel_gateway_fork_join(self):
        b = Bpmn.create_process("p").start_event().parallel_gateway("fork")
        branch1 = b.branch().service_task("a", type="ta")
        branch2 = b.branch().service_task("b", type="tb")
        branch1.parallel_gateway("join")
        branch2.connect_to("join")
        b.move_to("join").end_event("end")
        model = b.done()
        join = model.element("join")
        assert len(join.incoming) == 2
        assert len(join.outgoing) == 1

    def test_subprocess(self):
        b = Bpmn.create_process("p").start_event("s")
        sub = b.sub_process("sub")
        sub.start_event("sub-start").service_task("inner", type="t").end_event("sub-end")
        sub.embedded_done().end_event("outer-end")
        model = b.done()
        inner = model.element("inner")
        assert inner.scope_id == "sub"
        assert model.element("sub").scope_id == "p"
        assert model.element("outer-end").incoming[0].source_id == "sub"

    def test_duplicate_id_rejected(self):
        with pytest.raises(ValueError):
            Bpmn.create_process("p").start_event("x").end_event("x").done()


class TestXml:
    def test_round_trip(self):
        model = order_process()
        xml_bytes = write_model(model)
        parsed = read_model(xml_bytes)
        assert parsed.processes[0].id == "order-process"
        task = parsed.element("collect-money")
        assert isinstance(task, ServiceTask)
        assert task.task_definition.type == "payment-service"
        assert task.incoming[0].source_id == "start"

    def test_round_trip_gateway_conditions(self):
        b = Bpmn.create_process("p").start_event("start").exclusive_gateway("split")
        b.branch("$.x < 5").end_event("small")
        b.branch(default=True).end_event("big")
        xml_bytes = write_model(b.done())
        parsed = read_model(xml_bytes)
        gw = parsed.element("split")
        conds = {f.target_id: f.condition_expression for f in gw.outgoing}
        assert conds["small"] == "$.x < 5"
        assert gw.default_flow_id is not None

    def test_round_trip_message_catch(self):
        model = (
            Bpmn.create_process("p")
            .start_event()
            .message_catch_event(
                "wait", message_name="order-paid", correlation_key="$.orderId"
            )
            .end_event()
            .done()
        )
        parsed = read_model(write_model(model))
        catch = parsed.element("wait")
        assert catch.message.name == "order-paid"
        assert catch.message.correlation_key == "$.orderId"

    def test_round_trip_subprocess_and_io(self):
        b = Bpmn.create_process("p").start_event("s")
        b.service_task(
            "t",
            type="x",
            headers={"k": "v"},
            inputs=[("$.a", "$.b")],
            outputs=[("$.c", "$.d")],
        )
        sub = b.sub_process("sub")
        sub.start_event("ss").end_event("se")
        sub.embedded_done().end_event("e")
        parsed = read_model(write_model(b.done()))
        t = parsed.element("t")
        assert t.task_headers == {"k": "v"}
        assert [(m.source, m.target) for m in t.input_mappings] == [("$.a", "$.b")]
        assert [(m.source, m.target) for m in t.output_mappings] == [("$.c", "$.d")]
        assert parsed.element("ss").scope_id == "sub"

    def test_round_trip_timer(self):
        model = (
            Bpmn.create_process("p")
            .start_event()
            .timer_catch_event("wait", duration_ms=5000)
            .end_event()
            .done()
        )
        parsed = read_model(write_model(model))
        assert parsed.element("wait").timer_duration_ms == 5000


class TestYaml:
    def test_simple_workflow(self):
        # mirror of reference simple-workflow.yaml
        model = read_yaml_workflow(
            """
name: yaml-workflow
tasks:
  - id: task1
    type: foo
  - id: task2
    type: bar
"""
        )
        t1, t2 = model.element("task1"), model.element("task2")
        assert t1.task_definition.type == "foo"
        assert t1.outgoing[0].target_id == "task2"
        assert t2.outgoing[0].target_id.startswith("end")

    def test_switch_cases(self):
        model = read_yaml_workflow(
            """
name: flow
tasks:
  - id: decide
    type: t
    switch:
      - case: $.x > 10
        goto: big
      - default: small
  - id: big
    type: t
    end: true
  - id: small
    type: t
"""
        )
        gw = model.element("split-decide")
        assert isinstance(gw, ExclusiveGateway)
        targets = {f.target_id for f in gw.outgoing}
        assert targets == {"big", "small"}
        assert gw.default_flow_id is not None

    def test_headers_and_mappings(self):
        model = read_yaml_workflow(
            """
name: w
tasks:
  - id: t
    type: x
    retries: 5
    headers: {a: b}
    inputs:
      - source: $.in
        target: $.v
    outputs:
      - source: $.v
        target: $.out
"""
        )
        t = model.element("t")
        assert t.task_definition.retries == 5
        assert t.task_headers == {"a": "b"}
        assert t.input_mappings[0].source == "$.in"


class TestValidation:
    def test_valid_model(self):
        assert validate_model(order_process()) == []

    def test_missing_task_type(self):
        model = (
            Bpmn.create_process("p").start_event().service_task("t").end_event().done()
        )
        errors = validate_model(model)
        assert any("task type" in str(e) for e in errors)

    def test_missing_start_event(self):
        b = Bpmn.create_process("p")
        b.service_task("t", type="x")
        errors = validate_model(b.done())
        assert any("start event" in str(e) for e in errors)

    def test_gateway_flow_without_condition(self):
        b = Bpmn.create_process("p").start_event().exclusive_gateway("gw")
        b.branch("$.x == 1").end_event("e1")
        b.branch().end_event("e2")  # no condition, not default
        errors = validate_model(b.done())
        assert any("condition" in str(e) for e in errors)

    def test_bad_condition_expression(self):
        b = Bpmn.create_process("p").start_event().exclusive_gateway("gw")
        b.branch("$.x === 1").end_event("e1")
        b.branch(default=True).end_event("e2")
        errors = validate_model(b.done())
        assert any("gw" in str(e) or "expected" in str(e).lower() for e in errors)
