"""Pipelined/batched serving plane: parity + group-commit recovery.

The wave drain (runtime/broker.run_until_idle, cluster drain chunks) and
the raft group commit are PERF changes — the log is the contract, so each
is pinned against the unbatched baseline:

- the wave-drained broker produces a BIT-IDENTICAL log to record-at-a-time
  processing (wave_size=1), for both the host oracle and the device
  engine (CPU backend), and the committed log replays deterministically
  through the chaos plane's ``replay_oracle``;
- a crash mid-batch-append (group commit writes many frames in one block)
  recovers to a whole-record boundary and loses nothing that was flushed
  before the torn batch;
- concurrent ``raft.append`` calls coalesce into one log append + one
  fsync, in call order, with every future observing its own records.
"""

import threading
import time

import pytest

from zeebe_tpu.gateway import JobWorker, ZeebeClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.protocol import codec
from zeebe_tpu.protocol.records import Record, WorkflowInstanceRecord
from zeebe_tpu.runtime import Broker, ControlledClock
from zeebe_tpu.testing.chaos import (
    DiskFaults,
    oracle_state_bytes,
    replay_oracle,
)


def order_model():
    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


def xor_model():
    builder = (
        Bpmn.create_process("xor-process")
        .start_event("start")
        .exclusive_gateway("split")
    )
    builder.branch("$.orderValue > 50").service_task(
        "big", type="payment-service"
    ).end_event("end-big")
    builder.branch(default=True).service_task(
        "small", type="payment-service"
    ).end_event("end-small")
    return builder.done()


def _run_workload(data_dir, wave_size, engine_factory=None):
    """One deterministic serving workload; returns the committed records
    and the encoded frame bytes (the bit-identity witness)."""
    import itertools

    from zeebe_tpu.gateway import workers as workers_mod

    # process-global subscriber-key counter: reset so both runs of a
    # comparison see identical subscriber keys in their logs
    workers_mod._subscriber_keys = itertools.count(1)
    clock = ControlledClock(start_ms=1_000_000)
    if engine_factory is not None:
        broker = Broker(
            num_partitions=1, data_dir=data_dir, clock=clock,
            engine_factory=engine_factory(clock),
        )
    else:
        broker = Broker(num_partitions=1, data_dir=data_dir, clock=clock)
    broker.wave_size = wave_size
    try:
        client = ZeebeClient(broker)
        client.deploy_model(order_model())
        client.deploy_model(xor_model())
        JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        for i in range(20):
            client.create_instance("order-process", {"orderId": i})
        for i in range(10):
            client.create_instance(
                "xor-process", {"orderValue": 10 + 10 * i}
            )
        # exercise the timer/deadline path inside the same log
        clock.advance(1_000)
        broker.tick()
        broker.run_until_idle()
        records = broker.records(0)
        frames = [codec.encode_record(r) for r in records]
        return records, frames
    finally:
        broker.close()


class TestWaveDrainParity:
    def test_host_engine_log_bit_identical_to_record_at_a_time(self, tmp_path):
        records_wave, frames_wave = _run_workload(str(tmp_path / "wave"), 256)
        records_one, frames_one = _run_workload(str(tmp_path / "one"), 1)
        assert len(frames_wave) > 100
        assert frames_wave == frames_one
        # and the committed sequence replays deterministically: two
        # independent oracle replays agree bit-for-bit, and the wave log
        # replays to the same state as the unbatched log
        assert oracle_state_bytes(replay_oracle(records_wave)) == (
            oracle_state_bytes(replay_oracle(records_one))
        )

    def test_device_engine_log_bit_identical_to_record_at_a_time(self, tmp_path):
        from zeebe_tpu.engine.interpreter import WorkflowRepository
        from zeebe_tpu.tpu import TpuPartitionEngine

        def factory(clock):
            repo = WorkflowRepository()
            return lambda pid: TpuPartitionEngine(
                pid, 1, repository=repo, clock=clock
            )

        _, frames_wave = _run_workload(
            str(tmp_path / "wave"), 256, engine_factory=factory
        )
        _, frames_one = _run_workload(
            str(tmp_path / "one"), 1, engine_factory=factory
        )
        assert len(frames_wave) > 100
        assert frames_wave == frames_one

    def test_pure_wave_drain_materializes_zero_rows(self, tmp_path):
        """The columnar-plane proof metric: a pure host wave drain —
        client commands → codec → append → interpreter wave → exporter
        egress → responses — materializes ZERO lazy rows from columnar
        views (``serving_rows_materialized_total``). Rows on this path
        are engine-built ``Record`` objects; only a columnar batch whose
        rows were never Records (device readback) may count."""
        import os

        from zeebe_tpu.exporter import InMemoryExporter
        from zeebe_tpu.gateway import workers as workers_mod
        from zeebe_tpu.protocol.columnar import rows_materialized_total
        from zeebe_tpu.runtime.config import ExporterCfg
        import itertools

        InMemoryExporter.reset()
        workers_mod._subscriber_keys = itertools.count(1)
        clock = ControlledClock(start_ms=1_000_000)
        audit_dir = os.path.join(str(tmp_path), "audit")
        broker = Broker(
            num_partitions=1, data_dir=str(tmp_path / "d"), clock=clock,
            exporters=[
                ExporterCfg(id="audit", type="jsonl",
                            args={"path": audit_dir}),
                ExporterCfg(id="metrics", type="metrics", args={}),
            ],
        )
        broker.wave_size = 256
        before = rows_materialized_total()
        try:
            client = ZeebeClient(broker)
            client.deploy_model(order_model())
            JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
            for i in range(16):
                client.create_instance("order-process", {"orderId": i})
            clock.advance(1_000)
            broker.tick()
            broker.run_until_idle()
        finally:
            broker.close()
        assert rows_materialized_total() - before == 0
        InMemoryExporter.reset()

    def test_wave_metrics_observed(self, tmp_path):
        from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY

        waves = GLOBAL_REGISTRY.counter("serving_waves_total")
        recs = GLOBAL_REGISTRY.counter("serving_wave_records_total")
        w0, r0 = waves.value, recs.value
        _, frames = _run_workload(str(tmp_path / "m"), 256)
        assert waves.value > w0
        assert recs.value - r0 >= len(frames)
        # the gauges render on the global registry (the /metrics surface)
        text = GLOBAL_REGISTRY.dump()
        assert "zb_serving_wave_fill" in text
        assert "zb_serving_wave_occupancy" in text
        assert "zb_serving_host_seconds_total" in text


class TestGroupCommit:
    def _single_raft(self, tmp_path):
        from zeebe_tpu.cluster.raft import Raft, RaftConfig, RaftState
        from zeebe_tpu.log import LogStream, SegmentedLogStorage
        from zeebe_tpu.runtime.actors import ActorScheduler

        scheduler = ActorScheduler(cpu_threads=2, io_threads=2).start()
        storage = SegmentedLogStorage(str(tmp_path / "log"))
        log = LogStream(storage, recover_commit=False)
        raft = Raft(
            "n0", log, scheduler,
            config=RaftConfig(
                heartbeat_interval_ms=50, election_timeout_ms=100,
                election_jitter_ms=50,
            ),
            storage_path=str(tmp_path / "raft.meta"),
        )
        raft.bootstrap({"n0": raft.address})
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and raft.state != RaftState.LEADER:
            time.sleep(0.01)
        assert raft.state == RaftState.LEADER
        return raft, log, storage, scheduler

    @staticmethod
    def _command(i):
        from zeebe_tpu.protocol.enums import RecordType, ValueType
        from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI
        from zeebe_tpu.protocol.metadata import RecordMetadata

        return Record(
            key=i,
            metadata=RecordMetadata(
                record_type=RecordType.COMMAND,
                value_type=ValueType.WORKFLOW_INSTANCE,
                intent=int(WI.CREATE),
            ),
            value=WorkflowInstanceRecord(
                bpmn_process_id="p", payload={"i": i}
            ),
        )

    def test_concurrent_appends_coalesce_in_order(self, tmp_path):
        from zeebe_tpu.runtime.metrics import event_count

        raft, log, storage, scheduler = self._single_raft(tmp_path)
        try:
            fsyncs_before = event_count("log_fsyncs")
            coalesced_before = event_count("log_group_commit_coalesced")
            # wedge the raft actor so every append queues behind one drain
            gate = threading.Event()
            raft.actor.run(lambda: gate.wait(5))
            futures = [raft.append([self._command(i)]) for i in range(16)]
            gate.set()
            positions = [f.join(10) for f in futures]
            # call order == log order, and every future saw its own record
            assert positions == sorted(positions)
            got = [log.record_at(p).key for p in positions]
            assert got == list(range(16))
            # the burst shared fsyncs: strictly fewer syncs than appends
            assert event_count("log_group_commit_coalesced") > coalesced_before
            assert (
                event_count("log_fsyncs") - fsyncs_before
                < len(futures)
            )
        finally:
            raft.close()
            storage.close()
            scheduler.stop()

    def test_torn_mid_batch_append_recovers_to_record_boundary(self, tmp_path):
        """Group commit writes many frames in one storage block; a crash
        mid-write must recover every whole record and lose only the torn
        frame — acked (flushed) batches survive untouched."""
        from zeebe_tpu.log import LogStream, SegmentedLogStorage

        d = str(tmp_path / "log")
        storage = SegmentedLogStorage(d)
        log = LogStream(storage)
        acked = [self._command(i) for i in range(8)]
        log.append(acked)
        log.flush()  # the acked group
        tail = [self._command(100 + i) for i in range(8)]
        log.append(tail)  # crash before this batch's flush
        storage.close()
        # tear into the LAST frame of the unflushed batch (partial write)
        DiskFaults.tear_log_tail(d, nbytes=5)

        storage2 = SegmentedLogStorage(d)
        log2 = LogStream(storage2)
        recovered = list(log2.reader(0))
        # every surviving record is whole; the acked batch is intact
        assert [r.key for r in recovered[:8]] == list(range(8))
        assert len(recovered) == 15  # 16 written, exactly the torn one lost
        assert [r.key for r in recovered[8:]] == [100 + i for i in range(7)]
        # appends resume cleanly at the recovered boundary
        log2.append([self._command(999)])
        assert list(log2.reader(0))[-1].key == 999
        storage2.close()
