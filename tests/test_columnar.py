"""Columnar record plane: batch codec bit-identity, lazy row views, and
the zero-materialization proof metric.

The wave is the currency from readback to log/exporter/gateway (ROADMAP
item 4); the log is the contract — so the batch codec is pinned
bit-identical to per-record encoding, and the pure host wave path is
pinned to ZERO lazy row materializations
(``serving_rows_materialized_total``)."""

import pytest

from zeebe_tpu.protocol import codec, msgpack
from zeebe_tpu.protocol.columnar import (
    ColumnarBatch,
    RecordsView,
    rows_materialized_total,
)
from zeebe_tpu.protocol.enums import ErrorType, RecordType, RejectionType, ValueType
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import (
    DeployedWorkflowMeta,
    DeploymentRecord,
    DeploymentResource,
    IncidentRecord,
    JobHeaders,
    JobRecord,
    MessageRecord,
    Record,
    TimerRecord,
    WorkflowInstanceRecord,
)


def _assorted_records():
    """One record per interesting shape: every value class family, unicode
    rejection reasons, binary resources, nested headers, empty values."""
    return [
        Record(
            position=5, key=7, timestamp=123, raft_term=2, producer_id=3,
            source_record_position=4,
            metadata=RecordMetadata(
                record_type=RecordType.COMMAND,
                value_type=ValueType.WORKFLOW_INSTANCE,
                intent=0, request_id=9, request_stream_id=1, incident_key=11,
            ),
            value=WorkflowInstanceRecord(
                bpmn_process_id="p", payload={
                    "a": 1, "s": "héllo", "n": -1.5, "b": True, "z": None,
                    "big": 2 ** 40, "neg": -77, "lst": [1, "x"],
                },
            ),
        ),
        Record(
            position=6,
            metadata=RecordMetadata(
                record_type=RecordType.COMMAND_REJECTION,
                value_type=ValueType.JOB, intent=3,
                rejection_type=RejectionType.BAD_VALUE,
                rejection_reason="bad ünicode reason " + "x" * 100,
            ),
            value=JobRecord(
                type="t" * 40, retries=-3,
                headers=JobHeaders(workflow_instance_key=5),
                custom_headers={"h": "v"},
            ),
        ),
        Record(
            position=7,
            metadata=RecordMetadata(value_type=ValueType.DEPLOYMENT),
            value=DeploymentRecord(
                topic_name="x",
                resources=[DeploymentResource(resource=b"\x00\xffbin" * 100)],
                deployed_workflows=[
                    DeployedWorkflowMeta(bpmn_process_id="p", version=1, key=2)
                ],
            ),
        ),
        Record(
            position=8,
            metadata=RecordMetadata(value_type=ValueType.INCIDENT),
            value=IncidentRecord(
                error_type=int(ErrorType.UNKNOWN), error_message="m" * 300
            ),
        ),
        Record(
            position=9,
            metadata=RecordMetadata(value_type=ValueType.MESSAGE),
            value=MessageRecord(name="n", correlation_key="ck"),
        ),
        Record(
            position=10,
            metadata=RecordMetadata(value_type=ValueType.TIMER),
            value=TimerRecord(due_date=-5),
        ),
        Record(position=11),  # no value → EMPTY_DOCUMENT
    ]


class TestBatchCodec:
    def test_encode_records_bit_identical_to_per_record(self):
        records = _assorted_records()
        buf, offsets = codec.encode_records(records)
        reference = b"".join(codec.encode_record(r) for r in records)
        assert bytes(buf) == reference
        # offsets point exactly at each frame start
        for record, off in zip(records, offsets):
            decoded, _ = codec.decode_record(bytes(buf), off)
            assert codec.encode_record(decoded) == codec.encode_record(record)

    def test_encode_columnar_bit_identical(self):
        records = _assorted_records()
        reference = b"".join(codec.encode_record(r) for r in records)
        batch = ColumnarBatch.from_records(records)
        assert bytes(codec.encode_columnar(batch)[0]) == reference
        view = RecordsView(list(records))
        assert bytes(codec.encode_columnar(view)[0]) == reference

    def test_fused_value_encode_matches_document_pack(self):
        for record in _assorted_records():
            if record.value is None:
                continue
            assert record.value.encode() == msgpack.pack(
                record.value.to_document()
            )

    def test_value_copy_is_deep(self):
        value = WorkflowInstanceRecord(
            bpmn_process_id="p", payload={"a": [1, {"b": 2}], "c": "x"}
        )
        clone = value.copy()
        clone.payload["a"][1]["b"] = 99
        clone.payload["c"] = "y"
        assert value.payload == {"a": [1, {"b": 2}], "c": "x"}


class TestLazyRows:
    def test_from_records_rows_precached_no_materializations(self):
        before = rows_materialized_total()
        records = _assorted_records()
        batch = ColumnarBatch.from_records(records)
        # column reads AND row reads: everything is pre-cached
        assert batch.positions() == [r.position for r in records]
        assert batch.value_types() == [
            int(r.metadata.value_type) for r in records
        ]
        assert list(batch) == records
        assert batch[0] is records[0]
        assert rows_materialized_total() == before

    def test_lazy_batch_materializes_on_row_access_and_counts(self):
        records = _assorted_records()
        built = []

        def materializer(i):
            built.append(i)
            return records[i].copy()

        batch = ColumnarBatch(
            len(records),
            {
                "position": [r.position for r in records],
                "value_type": [int(r.metadata.value_type) for r in records],
            },
            materializer=materializer,
        )
        before = rows_materialized_total()
        # column access never materializes
        assert batch.value_types() == [
            int(r.metadata.value_type) for r in records
        ]
        assert rows_materialized_total() == before
        assert built == []
        # row access materializes ONCE per row (cached) and counts
        row = batch.row(2)
        assert batch.row(2) is row
        assert built == [2]
        assert rows_materialized_total() == before + 1

    def test_records_view_columns_from_lazy_entries(self):
        records = _assorted_records()
        batch = ColumnarBatch(
            len(records),
            {
                "position": [r.position for r in records],
                "value_type": [int(r.metadata.value_type) for r in records],
            },
            materializer=lambda i: records[i].copy(),
        )
        view = RecordsView(batch.log_entries())
        before = rows_materialized_total()
        assert view.positions() == [r.position for r in records]
        assert view.value_types() == [
            int(r.metadata.value_type) for r in records
        ]
        sub = view.select([0, 2])
        assert sub.positions() == [records[0].position, records[2].position]
        assert rows_materialized_total() == before  # columns stayed lazy
        # iteration materializes (and shares row identity with the batch)
        rows = list(sub)
        assert rows[0] is batch.row(0)
        assert rows_materialized_total() > before


class TestColumnarLogAppend:
    def test_columnar_append_bit_identical_and_lazy(self, tmp_path):
        from zeebe_tpu.log import LogStream, SegmentedLogStorage

        def command(i):
            return Record(
                key=i,
                metadata=RecordMetadata(
                    record_type=RecordType.COMMAND,
                    value_type=ValueType.WORKFLOW_INSTANCE, intent=0,
                ),
                value=WorkflowInstanceRecord(
                    bpmn_process_id="p", payload={"i": i}
                ),
            )

        # reference log: plain record appends
        s1 = SegmentedLogStorage(str(tmp_path / "a"))
        log1 = LogStream(s1, clock=lambda: 42)
        log1.append([command(i) for i in range(10)])

        # columnar log: lazy batch (rows built only through the batch)
        template = [command(i) for i in range(10)]
        batch = ColumnarBatch(
            10,
            {
                "key": [r.key for r in template],
                "record_type": [int(r.metadata.record_type) for r in template],
                "value_type": [int(r.metadata.value_type) for r in template],
                "intent": [0] * 10,
            },
            materializer=lambda i: template[i],
        )
        s2 = SegmentedLogStorage(str(tmp_path / "b"))
        log2 = LogStream(s2, clock=lambda: 42)
        before = rows_materialized_total()
        log2.append(batch)
        # the append itself had to encode values (template rows), counted
        # as materializations only for rows the batch had to build
        a = [codec.encode_record(r) for r in log1.reader(0).read_committed()]
        b = [codec.encode_record(r) for r in log2.reader(0).read_committed()]
        assert a == b
        # reopen: recovery decodes the same bytes
        s2.close()
        s3 = SegmentedLogStorage(str(tmp_path / "b"))
        log3 = LogStream(s3, clock=lambda: 42)
        assert [
            codec.encode_record(r) for r in log3.reader(0).read_committed()
        ] == a
        s1.close()
        s3.close()
        assert rows_materialized_total() >= before

    def test_committed_view_reads_columns_without_lock_per_record(self, tmp_path):
        from zeebe_tpu.log import LogStream, SegmentedLogStorage

        storage = SegmentedLogStorage(str(tmp_path))
        log = LogStream(storage, clock=lambda: 1)
        records = _assorted_records()
        for r in records:
            r.position = -1
        log.append(records)
        view = log.committed_view(0)
        assert len(view) == len(records)
        assert view.positions() == list(range(len(records)))
        assert view.value_types() == [
            int(r.metadata.value_type) for r in records
        ]
        # bounded reads
        assert len(log.committed_view(2, 3)) == 3
        assert log.committed_view(2, 3).positions() == [2, 3, 4]
        storage.close()
