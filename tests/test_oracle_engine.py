"""End-to-end tests of the host reference engine through the broker runtime.

Reference parity: these mirror the reference's broker-core stream-processor
tests (StreamProcessorRule + EmbeddedBrokerRule asserts on the record
stream) — the event log IS the observable behavior.
"""

import pytest

from zeebe_tpu.gateway import JobWorker, ZeebeClient, ClientException
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.protocol.enums import ErrorType, RecordType, ValueType
from zeebe_tpu.protocol.intents import (
    IncidentIntent,
    JobIntent,
    MessageIntent,
    TimerIntent,
    WorkflowInstanceIntent as WI,
)
from zeebe_tpu.runtime import Broker, ControlledClock


@pytest.fixture
def clock():
    return ControlledClock(start_ms=1_000_000)


@pytest.fixture
def broker(tmp_path, clock):
    b = Broker(num_partitions=1, data_dir=str(tmp_path / "data"), clock=clock)
    yield b
    b.close()


@pytest.fixture
def client(broker):
    return ZeebeClient(broker)


def order_process_model():
    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


def wi_intents(broker, partition=0):
    return [
        (WI(r.metadata.intent).name, r.value.activity_id)
        for r in broker.records(partition)
        if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
        and r.metadata.record_type == RecordType.EVENT
    ]


class TestHappyPath:
    def test_deploy_and_complete_instance(self, broker, client):
        client.deploy_model(order_process_model())
        worker = JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        instance = client.create_instance("order-process", {"orderId": 31243})
        broker.run_until_idle()

        assert instance.workflow_instance_key > 0
        assert instance.version == 1
        assert len(worker.handled) == 1
        job = worker.handled[0].value
        assert job.type == "payment-service"
        assert job.payload == {"orderId": 31243}
        assert job.headers.bpmn_process_id == "order-process"
        assert job.headers.activity_id == "collect-money"
        assert job.worker == "default-worker"

        # the canonical element lifecycle (reference internal-processing docs)
        assert wi_intents(broker) == [
            ("CREATED", "order-process"),
            ("ELEMENT_READY", "order-process"),
            ("ELEMENT_ACTIVATED", "order-process"),
            ("START_EVENT_OCCURRED", "start"),
            ("SEQUENCE_FLOW_TAKEN", "flow-start-collect-money-0"),
            ("ELEMENT_READY", "collect-money"),
            ("ELEMENT_ACTIVATED", "collect-money"),
            ("ELEMENT_COMPLETING", "collect-money"),
            ("ELEMENT_COMPLETED", "collect-money"),
            ("SEQUENCE_FLOW_TAKEN", "flow-collect-money-end-1"),
            ("END_EVENT_OCCURRED", "end"),
            ("ELEMENT_COMPLETING", "order-process"),
            ("ELEMENT_COMPLETED", "order-process"),
        ]
        # payload carried through job completion
        completed = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
            and r.metadata.intent == WI.ELEMENT_COMPLETED
            and r.value.activity_id == "order-process"
        ]
        assert completed[0].value.payload == {"orderId": 31243, "paid": True}
        # element instance cleaned up
        assert broker.partitions[0].engine.element_instances.instances == {}

    def test_create_by_workflow_key_and_version(self, broker, client):
        client.deploy_model(order_process_model())
        client.deploy_model(order_process_model())  # version 2
        latest = client.create_instance("order-process")
        assert latest.version == 2
        v1 = client.create_instance("order-process", version=1)
        assert v1.version == 1
        by_key = client.create_instance(workflow_key=v1.workflow_key)
        assert by_key.version == 1

    def test_create_unknown_workflow_rejected(self, broker, client):
        with pytest.raises(ClientException, match="not deployed"):
            client.create_instance("missing-process")

    def test_yaml_deploy_and_run(self, broker, client):
        client.deploy_yaml(
            """
name: yaml-flow
tasks:
  - id: task1
    type: foo
  - id: task2
    type: bar
"""
        )
        done = []
        JobWorker(broker, "foo", lambda ctx: done.append("foo"))
        JobWorker(broker, "bar", lambda ctx: done.append("bar"))
        client.create_instance("yaml-flow")
        broker.run_until_idle()
        assert done == ["foo", "bar"]
        final = wi_intents(broker)[-1]
        assert final == ("ELEMENT_COMPLETED", "yaml-flow")


class TestExclusiveGateway:
    def gateway_model(self):
        b = Bpmn.create_process("flow").start_event("start").exclusive_gateway("split")
        b.branch("$.orderValue >= 100").service_task("insured", type="insured-t").end_event("e1")
        b.branch(default=True).service_task("plain", type="plain-t").end_event("e2")
        return b.done()

    def test_condition_routes_true_branch(self, broker, client):
        client.deploy_model(self.gateway_model())
        taken = []
        JobWorker(broker, "insured-t", lambda ctx: taken.append("insured"))
        JobWorker(broker, "plain-t", lambda ctx: taken.append("plain"))
        client.create_instance("flow", {"orderValue": 150})
        broker.run_until_idle()
        assert taken == ["insured"]

    def test_default_flow(self, broker, client):
        client.deploy_model(self.gateway_model())
        taken = []
        JobWorker(broker, "insured-t", lambda ctx: taken.append("insured"))
        JobWorker(broker, "plain-t", lambda ctx: taken.append("plain"))
        client.create_instance("flow", {"orderValue": 10})
        broker.run_until_idle()
        assert taken == ["plain"]

    def test_condition_error_raises_incident(self, broker, client):
        client.deploy_model(self.gateway_model())
        client.create_instance("flow", {})  # $.orderValue missing
        broker.run_until_idle()
        incidents = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.INCIDENT
            and r.metadata.intent == IncidentIntent.CREATED
        ]
        assert len(incidents) == 1
        assert incidents[0].value.error_type == ErrorType.CONDITION_ERROR
        assert incidents[0].value.activity_id == "split"

    def test_incident_resolution_via_payload_update(self, broker, client):
        client.deploy_model(self.gateway_model())
        taken = []
        JobWorker(broker, "plain-t", lambda ctx: taken.append("plain"))
        JobWorker(broker, "insured-t", lambda ctx: taken.append("insured"))
        instance = client.create_instance("flow", {})
        broker.run_until_idle()
        incident = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.INCIDENT
            and r.metadata.intent == IncidentIntent.CREATED
        ][0]
        # resolve: update payload at the failed token → RESOLVE → re-run split
        client.update_payload(
            instance.workflow_instance_key,
            {"orderValue": 500},
            activity_instance_key=incident.value.activity_instance_key,
        )
        broker.run_until_idle()
        assert taken == ["insured"]
        resolved = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.INCIDENT
            and r.metadata.intent == IncidentIntent.RESOLVED
        ]
        assert len(resolved) == 1
        assert resolved[0].key == incident.key
        assert wi_intents(broker)[-1] == ("ELEMENT_COMPLETED", "flow")


class TestParallelGateway:
    def fork_join_model(self):
        b = Bpmn.create_process("par").start_event().parallel_gateway("fork")
        branch1 = b.branch().service_task("a", type="ta")
        branch2 = b.branch().service_task("b", type="tb")
        branch1.parallel_gateway("join")
        branch2.connect_to("join")
        b.move_to("join").end_event("end")
        return b.done()

    def test_fork_join_completes(self, broker, client):
        client.deploy_model(self.fork_join_model())
        ran = []
        JobWorker(broker, "ta", lambda ctx: ran.append("a") or {"a": 1})
        JobWorker(broker, "tb", lambda ctx: ran.append("b") or {"b": 2})
        client.create_instance("par", {"init": True})
        broker.run_until_idle()
        assert sorted(ran) == ["a", "b"]
        intents = wi_intents(broker)
        assert intents[-1] == ("ELEMENT_COMPLETED", "par")
        # join activation happened exactly once
        assert sum(1 for name, aid in intents if name == "GATEWAY_ACTIVATED" and aid == "join") == 1
        # both branch payloads merged at the join
        completed = [
            r
            for r in broker.records()
            if r.metadata.intent == WI.ELEMENT_COMPLETED
            and r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
            and r.value.activity_id == "par"
        ][0]
        assert completed.value.payload == {"init": True, "a": 1, "b": 2}
        assert broker.partitions[0].engine.element_instances.instances == {}

    def test_fork_without_join_completes_on_last_token(self, broker, client):
        b = Bpmn.create_process("par2").start_event().parallel_gateway("fork")
        b.branch().service_task("a", type="ta").end_event("e1")
        b.branch().service_task("b", type="tb").end_event("e2")
        client.deploy_model(b.done())
        JobWorker(broker, "ta", lambda ctx: None)
        JobWorker(broker, "tb", lambda ctx: None)
        client.create_instance("par2")
        broker.run_until_idle()
        intents = wi_intents(broker)
        # process completes exactly once, after both tokens consumed
        assert [x for x in intents if x[0] == "ELEMENT_COMPLETED" and x[1] == "par2"] == [
            ("ELEMENT_COMPLETED", "par2")
        ]


class TestCancel:
    def test_cancel_running_instance_cancels_job(self, broker, client, clock):
        client.deploy_model(order_process_model())
        # no worker: job stays CREATED... but must exist to cancel
        instance = client.create_instance("order-process")
        broker.run_until_idle()
        response = client.cancel_instance(instance.workflow_instance_key)
        broker.run_until_idle()
        assert response.metadata.intent == WI.CANCELING
        intents = wi_intents(broker)
        assert ("ELEMENT_TERMINATING", "order-process") in intents
        assert ("ELEMENT_TERMINATED", "collect-money") in intents
        assert ("ELEMENT_TERMINATED", "order-process") in intents
        job_canceled = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.JOB
            and r.metadata.intent == JobIntent.CANCELED
        ]
        assert len(job_canceled) == 1
        assert broker.partitions[0].engine.jobs == {}
        assert broker.partitions[0].engine.element_instances.instances == {}

    def test_cancel_finished_instance_rejected(self, broker, client):
        client.deploy_model(order_process_model())
        JobWorker(broker, "payment-service", lambda ctx: None)
        instance = client.create_instance("order-process")
        broker.run_until_idle()
        with pytest.raises(ClientException, match="not running"):
            client.cancel_instance(instance.workflow_instance_key)


class TestJobLifecycle:
    def test_fail_and_retry(self, broker, client):
        client.deploy_model(order_process_model())
        attempts = []

        def handler(ctx):
            attempts.append(ctx.job.retries)
            if len(attempts) == 1:
                ctx.fail(retries=ctx.job.retries - 1)

        JobWorker(broker, "payment-service", handler)
        client.create_instance("order-process")
        broker.run_until_idle()
        # first attempt failed with retries left → re-activated
        assert attempts == [3, 2]
        assert wi_intents(broker)[-1] == ("ELEMENT_COMPLETED", "order-process")

    def test_fail_without_retries_raises_incident_then_update_retries_resolves(
        self, broker, client
    ):
        client.deploy_model(order_process_model())
        attempts = []

        def handler(ctx):
            attempts.append(1)
            if len(attempts) == 1:
                ctx.fail(retries=0)

        JobWorker(broker, "payment-service", handler)
        client.create_instance("order-process")
        broker.run_until_idle()
        incidents = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.INCIDENT
            and r.metadata.intent == IncidentIntent.CREATED
        ]
        assert len(incidents) == 1
        assert incidents[0].value.error_type == ErrorType.JOB_NO_RETRIES
        job_key = incidents[0].value.job_key

        client.update_job_retries(job_key, retries=1)
        broker.run_until_idle()
        assert len(attempts) == 2
        assert wi_intents(broker)[-1] == ("ELEMENT_COMPLETED", "order-process")
        resolved = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.INCIDENT
            and r.metadata.intent == IncidentIntent.RESOLVED
        ]
        assert len(resolved) == 1

    def test_job_timeout_reactivates(self, broker, client, clock):
        client.deploy_model(order_process_model())
        seen = []

        def slow_handler(ctx):
            seen.append(ctx.key)
            if len(seen) == 1:
                ctx.finished = True  # simulate a worker that never completes

        JobWorker(broker, "payment-service", slow_handler, timeout_ms=5_000)
        client.create_instance("order-process")
        broker.run_until_idle()
        assert len(seen) == 1
        clock.advance(10_000)
        broker.tick()
        broker.run_until_idle()
        # re-pushed after TIMED_OUT
        assert len(seen) == 2
        timed_out = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.JOB
            and r.metadata.intent == JobIntent.TIMED_OUT
        ]
        assert len(timed_out) == 1

    def test_standalone_job(self, broker, client):
        created = client.create_job("standalone", {"x": 1})
        done = []
        worker = JobWorker(broker, "standalone", lambda ctx: done.append(ctx.payload))
        # the job created before the worker subscribed is assigned from the
        # backlog (reference: ActivateJobStreamProcessor reads the log from
        # the start), then the new one is pushed on creation
        second = client.create_job("standalone", {"x": 2})
        broker.run_until_idle()
        assert done == [{"x": 1}, {"x": 2}]


class TestPayloadMappings:
    def test_input_output_mappings(self, broker, client):
        model = (
            Bpmn.create_process("map")
            .start_event()
            .service_task(
                "work",
                type="t",
                inputs=[("$.order.total", "$.price")],
                outputs=[("$.paid", "$.order.paid")],
            )
            .end_event()
            .done()
        )
        client.deploy_model(model)
        seen = []

        def handler(ctx):
            seen.append(dict(ctx.payload))
            return {"paid": True}

        JobWorker(broker, "t", handler)
        client.create_instance("map", {"order": {"total": 42}})
        broker.run_until_idle()
        # input mapping narrowed the job payload
        assert seen == [{"price": 42}]
        completed = [
            r
            for r in broker.records()
            if r.metadata.intent == WI.ELEMENT_COMPLETED
            and r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
            and r.value.activity_id == "map"
        ][0]
        assert completed.value.payload == {"order": {"total": 42, "paid": True}}

    def test_input_mapping_error_raises_incident(self, broker, client):
        model = (
            Bpmn.create_process("map2")
            .start_event()
            .service_task("work", type="t", inputs=[("$.missing", "$.x")])
            .end_event()
            .done()
        )
        client.deploy_model(model)
        client.create_instance("map2", {})
        broker.run_until_idle()
        incidents = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.INCIDENT
            and r.metadata.intent == IncidentIntent.CREATED
        ]
        assert len(incidents) == 1
        assert incidents[0].value.error_type == ErrorType.IO_MAPPING_ERROR


class TestMessages:
    def catch_model(self):
        return (
            Bpmn.create_process("msg-flow")
            .start_event()
            .message_catch_event(
                "wait", message_name="order-paid", correlation_key="$.orderId"
            )
            .end_event()
            .done()
        )

    def test_subscription_then_publish_correlates(self, broker, client):
        client.deploy_model(self.catch_model())
        client.create_instance("msg-flow", {"orderId": "order-123"})
        broker.run_until_idle()
        client.publish_message("order-paid", "order-123", {"amount": 100})
        broker.run_until_idle()
        assert wi_intents(broker)[-1] == ("ELEMENT_COMPLETED", "msg-flow")
        completed = [
            r
            for r in broker.records()
            if r.metadata.intent == WI.ELEMENT_COMPLETED
            and r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
            and r.value.activity_id == "msg-flow"
        ][0]
        # message payload merges into the scope payload (output mapping merge)
        assert completed.value.payload == {"orderId": "order-123", "amount": 100}

    def test_publish_before_subscription_with_ttl_correlates(self, broker, client, clock):
        client.deploy_model(self.catch_model())
        client.publish_message(
            "order-paid", "order-9", {"ok": 1}, time_to_live_ms=60_000
        )
        broker.run_until_idle()
        client.create_instance("msg-flow", {"orderId": "order-9"})
        broker.run_until_idle()
        assert wi_intents(broker)[-1] == ("ELEMENT_COMPLETED", "msg-flow")

    def test_publish_without_ttl_is_deleted_immediately(self, broker, client):
        client.deploy_model(self.catch_model())
        client.publish_message("order-paid", "order-9", {"ok": 1})
        broker.run_until_idle()
        client.create_instance("msg-flow", {"orderId": "order-9"})
        broker.run_until_idle()
        # message was not buffered → instance still waiting
        intents = wi_intents(broker)
        assert ("ELEMENT_ACTIVATED", "wait") in intents
        assert intents[-1] != ("ELEMENT_COMPLETED", "msg-flow")

    def test_message_ttl_expiry(self, broker, client, clock):
        client.deploy_model(self.catch_model())
        client.publish_message("order-paid", "o1", {}, time_to_live_ms=1_000)
        broker.run_until_idle()
        clock.advance(5_000)
        broker.tick()
        broker.run_until_idle()
        deleted = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.MESSAGE
            and r.metadata.intent == MessageIntent.DELETED
        ]
        assert len(deleted) == 1
        # late instance does not correlate
        client.create_instance("msg-flow", {"orderId": "o1"})
        broker.run_until_idle()
        assert wi_intents(broker)[-1] != ("ELEMENT_COMPLETED", "msg-flow")

    def test_duplicate_message_id_rejected(self, broker, client):
        client.deploy_model(self.catch_model())
        client.publish_message("order-paid", "o1", {}, time_to_live_ms=60_000, message_id="m1")
        with pytest.raises(ClientException, match="already published"):
            client.publish_message(
                "order-paid", "o1", {}, time_to_live_ms=60_000, message_id="m1"
            )

    def test_multi_partition_correlation(self, tmp_path, clock):
        broker = Broker(num_partitions=4, data_dir=str(tmp_path / "mp"), clock=clock)
        client = ZeebeClient(broker)
        client.deploy_model(self.catch_model())
        instance = client.create_instance(
            "msg-flow", {"orderId": "corr-xyz"}, partition_id=2
        )
        broker.run_until_idle()
        client.publish_message("order-paid", "corr-xyz", {"done": 1})
        broker.run_until_idle()
        assert wi_intents(broker, 2)[-1] == ("ELEMENT_COMPLETED", "msg-flow")
        broker.close()


class TestTimers:
    def test_timer_catch_event_fires(self, broker, client, clock):
        model = (
            Bpmn.create_process("timed")
            .start_event()
            .timer_catch_event("wait", duration_ms=10_000)
            .end_event()
            .done()
        )
        client.deploy_model(model)
        client.create_instance("timed")
        broker.run_until_idle()
        intents = wi_intents(broker)
        assert ("ELEMENT_ACTIVATED", "wait") in intents
        assert intents[-1] != ("ELEMENT_COMPLETED", "timed")
        clock.advance(11_000)
        broker.tick()
        broker.run_until_idle()
        assert wi_intents(broker)[-1] == ("ELEMENT_COMPLETED", "timed")
        triggered = [
            r
            for r in broker.records()
            if r.metadata.value_type == ValueType.TIMER
            and r.metadata.intent == TimerIntent.TRIGGERED
        ]
        assert len(triggered) == 1


class TestSubProcess:
    def test_subprocess_completes(self, broker, client):
        b = Bpmn.create_process("outer").start_event("s")
        sub = b.sub_process("sub")
        sub.start_event("ss").service_task("inner", type="t").end_event("se")
        sub.embedded_done().end_event("e")
        client.deploy_model(b.done())
        JobWorker(broker, "t", lambda ctx: {"done": 1})
        client.create_instance("outer", {"in": 1})
        broker.run_until_idle()
        intents = wi_intents(broker)
        assert ("ELEMENT_READY", "sub") in intents
        assert ("ELEMENT_ACTIVATED", "sub") in intents
        assert ("START_EVENT_OCCURRED", "ss") in intents
        assert ("ELEMENT_COMPLETED", "sub") in intents
        assert intents[-1] == ("ELEMENT_COMPLETED", "outer")
        completed = [
            r
            for r in broker.records()
            if r.metadata.intent == WI.ELEMENT_COMPLETED
            and r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
            and r.value.activity_id == "outer"
        ][0]
        assert completed.value.payload == {"in": 1, "done": 1}


class TestUpdatePayload:
    def test_update_payload(self, broker, client):
        client.deploy_model(order_process_model())
        instance = client.create_instance("order-process", {"a": 1})
        broker.run_until_idle()
        response = client.update_payload(instance.workflow_instance_key, {"a": 2})
        assert response.metadata.intent == WI.PAYLOAD_UPDATED
        assert response.value.payload == {"a": 2}

    def test_update_payload_unknown_instance_rejected(self, broker, client):
        with pytest.raises(ClientException, match="not running"):
            client.update_payload(99999, {})
