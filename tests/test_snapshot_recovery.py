"""Snapshot storage + broker restart/recovery tests.

Reference parity: ``qa/integration-tests/.../BrokerReprocessingTest`` (restart
the broker, state is rebuilt by replay, workflows continue), plus
``FsSnapshotStorage``/``StateSnapshotController`` unit behavior (checksums,
commit-rename, stale-snapshot validation against the log).
"""

import os
import pickle

import pytest

from zeebe_tpu.gateway import JobWorker, ZeebeClient
from zeebe_tpu.log.snapshot import (
    SnapshotController,
    SnapshotMetadata,
    SnapshotStorage,
)
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import (
    JobIntent,
    MessageIntent,
    WorkflowInstanceIntent as WI,
)
from zeebe_tpu.runtime import Broker, ControlledClock


def order_process_model():
    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


def wi_events(broker, partition=0):
    return [
        (WI(r.metadata.intent).name, r.value.activity_id)
        for r in broker.records(partition)
        if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
        and r.metadata.record_type == RecordType.EVENT
    ]


# ---------------------------------------------------------------------------
# snapshot storage unit tests
# ---------------------------------------------------------------------------


class TestSnapshotStorage:
    def test_write_read_roundtrip(self, tmp_path):
        storage = SnapshotStorage(str(tmp_path))
        meta = SnapshotMetadata(10, 12, 1)
        storage.write(meta, b"hello-state")
        assert storage.list() == [meta]
        assert storage.read(meta) == b"hello-state"

    def test_newest_first_ordering(self, tmp_path):
        storage = SnapshotStorage(str(tmp_path))
        for pos in (5, 20, 10):
            storage.write(SnapshotMetadata(pos, pos + 1, 0), b"x")
        assert [m.last_processed_position for m in storage.list()] == [20, 10, 5]

    def test_corrupt_payload_rejected(self, tmp_path):
        storage = SnapshotStorage(str(tmp_path))
        meta = SnapshotMetadata(3, 4, 0)
        storage.write(meta, b"good")
        with open(os.path.join(str(tmp_path), meta.dirname, "state.bin"), "wb") as f:
            f.write(b"evil")
        assert storage.read(meta) is None

    def test_torn_tmp_dir_swept_on_open(self, tmp_path):
        os.makedirs(tmp_path / "snapshot_1_2_0.tmp")
        storage = SnapshotStorage(str(tmp_path))
        assert storage.list() == []
        assert not (tmp_path / "snapshot_1_2_0.tmp").exists()

    def test_purge_older(self, tmp_path):
        storage = SnapshotStorage(str(tmp_path))
        old = SnapshotMetadata(5, 6, 0)
        new = SnapshotMetadata(9, 11, 0)
        storage.write(old, b"a")
        storage.write(new, b"b")
        storage.purge_older_than(new)
        assert storage.list() == [new]

    def test_controller_skips_snapshot_ahead_of_log(self, tmp_path):
        """A snapshot whose written position exceeds the log end is stale
        (log truncated/diverged) — recovery falls back to an older one."""
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        controller.take({"v": 1}, SnapshotMetadata(5, 6, 0))
        # take() purges older snapshots, so write the newer one directly
        controller.storage.write(
            SnapshotMetadata(50, 60, 0), pickle.dumps({"v": 2})
        )
        state, meta = controller.recover(log_last_position=10)
        assert state == {"v": 1}
        assert meta.last_processed_position == 5

    def test_controller_skips_corrupt_falls_back(self, tmp_path):
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        controller.take({"v": 1}, SnapshotMetadata(5, 6, 0))
        bad = SnapshotMetadata(9, 9, 0)
        controller.storage.write(bad, b"not-a-pickle")
        with open(
            os.path.join(str(tmp_path), bad.dirname, "checksum.crc32"), "w"
        ) as f:
            f.write("0")
        state, meta = controller.recover(log_last_position=100)
        assert state == {"v": 1}

    def test_recover_empty(self, tmp_path):
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        assert controller.recover(100) == (None, None)


# ---------------------------------------------------------------------------
# incremental checkpoints (content-addressed segment store)
# ---------------------------------------------------------------------------


def _device_like_state(**overrides):
    """A device-engine-shaped snapshot state (SoA tables as arrays)."""
    import numpy as np

    from zeebe_tpu.log import stateser

    arrays = {
        "instances.state": np.zeros((4096,), np.int32),
        "instances.elem": np.full((4096,), -1, np.int32),
        "payload": np.zeros((4096, 64), np.float32),
        "jobs.keys": np.full((1024,), -1, np.int64),
    }
    arrays.update(overrides)
    return {
        "fmt": stateser.FORMAT_DEVICE_V1,
        "arrays": arrays,
        "meta": {"last_processed_position": 7},
        "host": None,
    }


class TestIncrementalCheckpoints:
    """VERDICT round-3 #6: checkpoints keyed by (processed, written, term)
    whose write cost tracks CHANGED state, not total state size (reference
    StateSnapshotController: RocksDB checkpoints share unchanged SSTs)."""

    def test_unchanged_tables_are_not_rewritten(self, tmp_path):
        import numpy as np

        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        state = _device_like_state()
        controller.take(state, SnapshotMetadata(10, 12, 1))
        first = dict(controller.last_take_stats)
        assert first["new_bytes"] == first["total_bytes"]

        # mutate ONE small table; the big payload matrix is untouched
        state2 = _device_like_state(
            **{"instances.state": np.ones((4096,), np.int32)}
        )
        controller.take(state2, SnapshotMetadata(20, 22, 1))
        second = dict(controller.last_take_stats)
        assert second["total_bytes"] == first["total_bytes"]
        # incremental cost ≈ the changed table + the small root part
        assert second["new_bytes"] < first["total_bytes"] // 4
        assert second["new_segments"] < second["parts"]

        state_r, meta = controller.recover(log_last_position=100)
        assert meta == SnapshotMetadata(20, 22, 1)
        assert (state_r["arrays"]["instances.state"] == 1).all()
        assert (state_r["arrays"]["payload"] == 0).all()

    def test_identical_checkpoint_costs_near_zero(self, tmp_path):
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        controller.take(_device_like_state(), SnapshotMetadata(10, 12, 1))
        controller.take(_device_like_state(), SnapshotMetadata(20, 22, 1))
        assert controller.last_take_stats["new_bytes"] == 0
        assert controller.last_take_stats["new_segments"] == 0

    def test_missing_segment_falls_back_to_older(self, tmp_path):
        from zeebe_tpu.log import snapshot as snapmod
        from zeebe_tpu.log import stateser

        storage = SnapshotStorage(str(tmp_path))
        controller = SnapshotController(storage)
        # write directly (take() would purge the older snapshot)
        storage.write_parts(
            SnapshotMetadata(5, 6, 0),
            stateser.encode_state_parts({"v": 1}),
        )
        storage.write_parts(
            SnapshotMetadata(9, 11, 0),
            stateser.encode_state_parts({"v": 2}),
        )
        # corrupt the NEWER snapshot by deleting a segment unique to it
        newer = storage.manifest(SnapshotMetadata(9, 11, 0))
        older = {e["h"] for e in storage.manifest(SnapshotMetadata(5, 6, 0))}
        unique = [e for e in newer if e["h"] not in older]
        assert unique, "distinct states must produce distinct segments"
        os.unlink(os.path.join(
            str(tmp_path), snapmod._SEGMENTS_DIR, unique[0]["h"] + ".seg"
        ))
        state, meta = controller.recover(log_last_position=100)
        assert state == {"v": 1}
        assert meta == SnapshotMetadata(5, 6, 0)

    def test_purge_gcs_unreferenced_segments(self, tmp_path, monkeypatch):
        from zeebe_tpu.log import snapshot as snapmod

        monkeypatch.setattr(snapmod, "_SEGMENT_GC_GRACE_SEC", 0.0)
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        controller.take({"v": 1}, SnapshotMetadata(5, 6, 0))
        controller.take({"v": 2}, SnapshotMetadata(9, 11, 0))
        seg_dir = os.path.join(str(tmp_path), snapmod._SEGMENTS_DIR)
        live = {e["h"] + ".seg"
                for e in controller.storage.manifest(SnapshotMetadata(9, 11, 0))}
        assert set(os.listdir(seg_dir)) == live
        state, _ = controller.recover(log_last_position=100)
        assert state == {"v": 2}

    def test_legacy_single_blob_snapshot_still_recovers(self, tmp_path):
        from zeebe_tpu.log import stateser

        storage = SnapshotStorage(str(tmp_path))
        meta = SnapshotMetadata(10, 12, 1)
        storage.write(meta, stateser.encode_state({"v": 42}))
        controller = SnapshotController(storage)
        state, got = controller.recover(log_last_position=100)
        assert state == {"v": 42}
        assert got == meta


# ---------------------------------------------------------------------------
# broker restart / replay tests
# ---------------------------------------------------------------------------


class TestBrokerRecovery:
    def _restart(self, broker, data_dir, clock):
        broker.close()
        return Broker(
            num_partitions=len(broker.partitions), data_dir=data_dir, clock=clock
        )

    def test_restart_resumes_mid_workflow(self, tmp_path):
        """Create an instance, restart before the job completes, then complete
        it on the restarted broker — the instance finishes (replay rebuilt
        element-instance + job state)."""
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        client.create_instance("order-process", payload={"orderId": 1})
        broker.run_until_idle()
        assert ("ELEMENT_ACTIVATED", "collect-money") in wi_events(broker)

        broker = self._restart(broker, data, clock)
        client = ZeebeClient(broker)
        worker = JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        broker.run_until_idle()
        assert ("ELEMENT_COMPLETED", "order-process") in wi_events(broker)
        assert len(worker.handled) == 1
        broker.close()

    def test_restart_preserves_deployments_and_versions(self, tmp_path):
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        client.deploy_model(order_process_model())  # version 2

        broker = self._restart(broker, data, clock)
        client = ZeebeClient(broker)
        JobWorker(broker, "payment-service", lambda ctx: None)
        result = client.create_instance("order-process")
        assert result.version == 2
        broker.run_until_idle()
        assert ("ELEMENT_COMPLETED", "order-process") in wi_events(broker)
        broker.close()

    def test_replay_rebuilds_identical_state(self, tmp_path):
        """Replay parity: restarting from the log alone reproduces the exact
        engine state of the live run (the correctness contract of SURVEY.md
        §5 — deterministic processing is what makes snapshots optional)."""
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        client.create_instance("order-process", payload={"orderId": 7})
        client.create_instance("order-process", payload={"orderId": 8})
        broker.run_until_idle()
        live = broker.partitions[0].engine.snapshot_state()

        broker = self._restart(broker, data, clock)
        # replay stops at the last source event position; the tail records
        # (no follow-ups of their own) are handled by the normal loop — run
        # to quiescence before comparing, and require that doing so appends
        # nothing new (no duplicated side effects)
        n_records = len(broker.records(0))
        broker.run_until_idle()
        assert len(broker.records(0)) == n_records
        replayed = broker.partitions[0].engine.snapshot_state()
        assert sorted(replayed["jobs"]) == sorted(live["jobs"])
        assert sorted(replayed["element_instances"].instances) == sorted(
            live["element_instances"].instances
        )
        assert replayed["wf_keys"].peek == live["wf_keys"].peek
        assert replayed["job_keys"].peek == live["job_keys"].peek
        assert replayed["last_processed_position"] == live["last_processed_position"]
        for key, job in live["jobs"].items():
            assert replayed["jobs"][key].state == job.state
            assert replayed["jobs"][key].deadline == job.deadline
        broker.close()

    def test_crash_between_append_and_process_still_executes_command(self, tmp_path):
        """A command appended to the log but never processed (crash right
        after append) must be processed after restart — replay only covers
        records whose follow-ups are already in the log, the tail runs
        through the normal loop with effects."""
        from zeebe_tpu.protocol.intents import WorkflowInstanceIntent
        from zeebe_tpu.protocol.records import WorkflowInstanceRecord

        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        broker.run_until_idle()
        # append the CREATE command without giving the loop a chance to run
        broker.write_command(
            0,
            WorkflowInstanceRecord(bpmn_process_id="order-process", payload={}),
            WorkflowInstanceIntent.CREATE,
            with_response=False,
        )
        broker = self._restart(broker, data, clock)
        worker = JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        broker.run_until_idle()
        intents = [
            int(r.metadata.intent)
            for r in broker.records(0)
            if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
        ]
        assert int(WorkflowInstanceIntent.ELEMENT_COMPLETED) in intents
        broker.close()

    def test_snapshot_shortens_replay(self, tmp_path):
        """With a snapshot, recovery replays only the records after the
        snapshot position (reference: reprocessing starts at the snapshot's
        last-processed position)."""
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        client.create_instance("order-process")
        broker.run_until_idle()
        broker.snapshot()
        snap_position = broker.partitions[0].next_read_position - 1
        client.create_instance("order-process")
        broker.run_until_idle()
        broker.close()

        processed = []
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        # the restored engine replayed only positions after the snapshot
        assert broker.partitions[0].engine.last_processed_position > snap_position
        # and the state includes BOTH instances (snapshot + replayed)
        keys = [
            i.value.workflow_instance_key
            for i in broker.partitions[0].engine.element_instances.instances.values()
        ]
        assert len(set(keys)) == 2
        broker.close()

    def test_restart_after_snapshot_only_no_tail(self, tmp_path):
        """Snapshot taken at the log end: recovery restores and replays
        nothing; processing continues seamlessly."""
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        client.create_instance("order-process")
        broker.run_until_idle()
        broker.snapshot()
        broker.close()

        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        JobWorker(broker, "payment-service", lambda ctx: None)
        broker.run_until_idle()
        assert ("ELEMENT_COMPLETED", "order-process") in wi_events(broker)
        broker.close()

    def test_message_ttl_survives_restart_deterministically(self, tmp_path):
        """A published message's TTL deadline derives from the record
        timestamp, so a restarted broker expires it at the same absolute
        time the live broker would have."""
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        client.publish_message("order-shipped", "order-1", time_to_live_ms=5_000)
        broker.run_until_idle()
        live_deadline = next(
            iter(broker.partitions[0].engine.messages.values())
        ).deadline

        broker = self._restart(broker, data, clock)
        msg = next(iter(broker.partitions[0].engine.messages.values()))
        assert msg.deadline == live_deadline

        clock.advance(6_000)
        broker.tick()
        broker.run_until_idle()
        deleted = [
            r
            for r in broker.records(0)
            if r.metadata.value_type == ValueType.MESSAGE
            and r.metadata.record_type == RecordType.EVENT
            and r.metadata.intent == int(MessageIntent.DELETED)
        ]
        assert deleted
        assert not broker.partitions[0].engine.messages
        broker.close()

    def test_multi_partition_restart(self, tmp_path):
        """Cross-partition message correlation state survives restart on
        both the message partition and the workflow partition."""
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = Broker(num_partitions=3, data_dir=data, clock=clock)
        client = ZeebeClient(broker)
        model = (
            Bpmn.create_process("msg-process")
            .start_event("start")
            .message_catch_event(
                "wait", message_name="order-shipped", correlation_key="$.orderId"
            )
            .end_event("end")
            .done()
        )
        client.deploy_model(model)
        client.create_instance(
            "msg-process", payload={"orderId": "order-77"}, partition_id=1
        )
        broker.run_until_idle()

        broker = self._restart(broker, data, clock)
        client = ZeebeClient(broker)
        client.publish_message("order-shipped", "order-77", payload={"ok": 1})
        broker.run_until_idle()
        assert ("ELEMENT_COMPLETED", "msg-process") in wi_events(broker, 1)
        broker.close()


# ---------------------------------------------------------------------------
# device-engine (TPU) snapshot + replay recovery
# ---------------------------------------------------------------------------


class TestTpuEngineRecovery:
    """The device engine checkpoints its SoA tables (device_get -> the
    data-only device envelope, log/stateser.py) keyed by last-processed
    position, and recovers by restore + suppressed-side-effect replay —
    the same contract the reference's StateSnapshotController +
    StreamProcessorController recovery give RocksDB-backed processors."""

    def _tpu_broker(self, data, clock):
        from tests.conftest import make_tpu_broker

        return make_tpu_broker(data_dir=data, clock=clock)

    def test_restart_resumes_mid_workflow_with_snapshot(self, tmp_path):
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = self._tpu_broker(data, clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        client.create_instance("order-process", payload={"orderId": 1})
        broker.run_until_idle()
        assert ("ELEMENT_ACTIVATED", "collect-money") in wi_events(broker)
        broker.snapshot()
        n_records = len(list(broker.records(0)))
        broker.close()

        broker = self._tpu_broker(data, clock)
        # replay must not duplicate side effects
        assert len(list(broker.records(0))) == n_records
        client = ZeebeClient(broker)
        worker = JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        broker.run_until_idle()
        assert ("ELEMENT_COMPLETED", "order-process") in wi_events(broker)
        assert len(worker.handled) == 1
        broker.close()

    def test_kill_between_snapshots_replays_tail(self, tmp_path):
        """Snapshot early, keep processing, crash: recovery restores the
        snapshot then replays the committed tail to catch up."""
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = self._tpu_broker(data, clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        client.create_instance("order-process", payload={"orderId": 1})
        broker.run_until_idle()
        broker.snapshot()
        # post-snapshot tail: a second instance + first job completes
        worker = JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        client.create_instance("order-process", payload={"orderId": 2})
        broker.run_until_idle()
        assert len(worker.handled) == 2
        completed = [
            e for e in wi_events(broker) if e == ("ELEMENT_COMPLETED", "order-process")
        ]
        assert len(completed) == 2
        n_records = len(list(broker.records(0)))
        broker.close()  # "crash": snapshot is stale, tail must replay

        broker = self._tpu_broker(data, clock)
        assert len(list(broker.records(0))) == n_records
        client = ZeebeClient(broker)
        # a third instance runs end-to-end on the recovered engine
        worker = JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        client.create_instance("order-process", payload={"orderId": 3})
        broker.run_until_idle()
        completed = [
            e for e in wi_events(broker) if e == ("ELEMENT_COMPLETED", "order-process")
        ]
        assert len(completed) == 3
        broker.close()

    def test_replay_only_restart_without_snapshot(self, tmp_path):
        clock = ControlledClock(start_ms=1_000_000)
        data = str(tmp_path / "data")
        broker = self._tpu_broker(data, clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        client.create_instance("order-process", payload={"orderId": 7})
        broker.run_until_idle()
        broker.close()

        broker = self._tpu_broker(data, clock)
        client = ZeebeClient(broker)
        worker = JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        broker.run_until_idle()
        assert len(worker.handled) == 1
        assert ("ELEMENT_COMPLETED", "order-process") in wi_events(broker)
        broker.close()

    def test_device_state_round_trips_exactly(self, tmp_path):
        """snapshot_state -> codec -> restore_state reproduces the SoA
        tables bit-for-bit (keys, payload matrices, hash maps, counters)."""
        import numpy as np

        from zeebe_tpu.log import stateser

        clock = ControlledClock(start_ms=1_000_000)
        broker = self._tpu_broker(str(tmp_path / "a"), clock)
        client = ZeebeClient(broker)
        client.deploy_model(order_process_model())
        client.create_instance("order-process", payload={"orderId": 1, "tag": "x"})
        broker.run_until_idle()
        engine = broker.partitions[0].engine
        snap = stateser.decode_state(
            stateser.encode_state(engine.snapshot_state())
        )

        restored = self._tpu_broker(str(tmp_path / "b"), clock)
        engine2 = restored.partitions[0].engine
        engine2.restore_state(snap)
        import dataclasses as dc

        from zeebe_tpu.tpu import state as state_mod

        # ei/job lookup structures are DERIVED state (re-built from live
        # rows at restore — rebuild_lookup_state), so compare them after
        # normalizing both sides through the same derivation; everything
        # else must round-trip bit-for-bit
        norm_a = state_mod.rebuild_lookup_state(engine.state)
        norm_b = state_mod.rebuild_lookup_state(engine2.state)
        derived = {
            "ei_map", "ei_index", "job_map", "job_index",
            "free_ei", "free_ei_pop", "free_ei_push",
            "free_job", "free_job_pop", "free_job_push",
        }
        for f in dc.fields(engine.state):
            if f.name in derived:
                a, b = getattr(norm_a, f.name), getattr(norm_b, f.name)
            else:
                a, b = getattr(engine.state, f.name), getattr(engine2.state, f.name)
            if f.name.startswith("sub_"):
                continue  # transient worker subscriptions drop on restore
            if hasattr(a, "keys"):
                np.testing.assert_array_equal(np.asarray(a.keys), np.asarray(b.keys))
                np.testing.assert_array_equal(np.asarray(a.vals), np.asarray(b.vals))
            else:
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b), err_msg=f.name
                )
        assert engine2.interns._by_id == engine.interns._by_id
        assert engine2.meta.varspace.names == engine.meta.varspace.names
        broker.close()
        restored.close()
