"""Fused phase-E commit + per-family dispatch (zeebe_tpu/tpu/pallas_ops,
zeebe_tpu/tpu/autotune).

CPU pins the semantics: off-TPU every family resolves to the XLA
fallbacks, so the fused commit must equal the unfused op chain exactly —
the same contract that makes the parity fuzzer meaningful for the TPU
path. The on-chip pallas-vs-XLA leg lives in
benchmarks/pallas_ops_check.py (check_fused_commit).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from zeebe_tpu import tpu as _tpu  # noqa: F401  (enables x64)
from zeebe_tpu.tpu import autotune, pallas_ops as pops


def _rng_ops(rng, T, B, K):
    tbl = jnp.asarray(rng.integers(0, 100, (T, K)), jnp.int32)
    ring = jnp.asarray(rng.integers(0, T, (T,)), jnp.int32)
    slots = jnp.asarray(rng.integers(0, T, (B,)), jnp.int32)
    active = jnp.asarray(rng.random(B) < 0.7)
    vals = jnp.asarray(rng.integers(0, 1000, (B, K)), jnp.int32)
    mask = jnp.asarray(rng.random((B, K)) < 0.4)
    lvals = jnp.asarray(rng.integers(0, 9, (B,)), jnp.int32)
    return tbl, ring, slots, active, vals, mask, lvals


class TestFusedCommitFallback:
    def test_matches_unfused_op_chain(self):
        """fused_table_commit == applying each op in order through the
        standalone ops (which ARE the old kernel chain)."""
        rng = np.random.default_rng(3)
        T, B, K = 512, 128, 8
        tbl, ring, slots, active, vals, mask, lvals = _rng_ops(rng, T, B, K)
        ops = [
            pops.TableOp(0, "add", slots, active, vals, mask),
            pops.TableOp(0, "set", slots, active, vals, mask),
            pops.TableOp(0, "max", slots, active, vals),
            pops.TableOp(0, "set", slots, active, vals),  # blind row
            pops.TableOp(1, "set", slots, active, lvals),
            pops.TableOp(1, "add", slots, active, lvals),
        ]
        got = pops.fused_table_commit([tbl, ring], ops)

        ref_tbl = pops.masked_row_add(tbl, slots, active, vals, mask)
        ref_tbl = pops.masked_row_update(ref_tbl, slots, active, vals, mask)
        ref_tbl = pops.masked_row_max(ref_tbl, slots, active, vals)
        ref_tbl = pops.masked_row_update(ref_tbl, slots, active, vals)
        ref_ring = pops.masked_lane_update(ring, slots, active, lvals)
        ref_ring = pops.masked_lane_accum(ref_ring, slots, active, lvals)
        assert (np.asarray(got[0]) == np.asarray(ref_tbl)).all()
        assert (np.asarray(got[1]) == np.asarray(ref_ring)).all()

    def test_row_add_matches_scatter_add(self):
        rng = np.random.default_rng(5)
        T, B, K = 256, 64, 6
        tbl, _, slots, active, vals, mask, _ = _rng_ops(rng, T, B, K)
        got = pops.masked_row_add(tbl, slots, active, vals, mask)
        ref = tbl.at[jnp.where(active, slots, T)].add(
            jnp.where(mask, vals, 0), mode="drop"
        )
        assert (np.asarray(got) == np.asarray(ref)).all()

    def test_empty_ops_is_identity(self):
        tbl = jnp.ones((8, 4), jnp.int32)
        assert pops.fused_table_commit([tbl], [])[0] is tbl


class TestDispatch:
    def test_env_override_wins(self, monkeypatch):
        monkeypatch.setenv("ZB_PALLAS", "0")
        pops.set_dispatch({f: True for f in pops.FAMILIES})
        try:
            assert not pops.use_pallas("row_update")
            assert not pops.use_pallas("fused")
        finally:
            pops.set_dispatch({})

    def test_forced_context_wins_over_env(self, monkeypatch):
        monkeypatch.setenv("ZB_PALLAS", "1")
        with pops.forced("xla"):
            assert not pops.use_pallas("row_update")
        # off-TPU even forced("pallas") stays on the XLA fallbacks
        with pops.forced("pallas"):
            import jax

            expected = jax.default_backend() == "tpu"
            assert pops.use_pallas("row_update") == expected

    def test_autotune_noop_off_tpu(self, monkeypatch):
        import jax

        if jax.default_backend() == "tpu":
            pytest.skip("CPU-only behavior")
        monkeypatch.delenv("ZB_PALLAS", raising=False)
        decisions = autotune.ensure_autotuned(force=True)
        assert decisions == {}
        assert autotune.dispatch_source() == "off-tpu"

    def test_decisions_table_consulted(self, monkeypatch):
        """Per-family decisions drive use_pallas when no override is set
        (only observable on TPU; off-TPU everything is False)."""
        import jax

        monkeypatch.delenv("ZB_PALLAS", raising=False)
        pops.set_dispatch({"row_update": False, "lookup": True})
        try:
            if jax.default_backend() == "tpu":
                assert not pops.use_pallas("row_update")
                assert pops.use_pallas("lookup")
                assert pops.use_pallas("insert")  # default stays pallas
            else:
                assert not pops.use_pallas("lookup")
        finally:
            pops.set_dispatch({})
