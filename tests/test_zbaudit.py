"""zbaudit suite tests: every pass proves it fires on a seeded
anti-pattern (positive) and stays quiet on the sanctioned idiom
(negative); plus the baseline ratchet, the HBM model vs measured
device-buffer bytes, the donation parity pins the boundary pass forced
on ``kernel.tick`` / ``engine.due_probe``, the runtime recompile guard,
and the live-tree-clean gate pin (the exact CI invocation).

Fixtures go through :func:`tools.zbaudit.audit_program`, which builds an
``AuditedEntry`` WITHOUT touching the jit registry — so nothing here can
trip the coverage pass on the live tree.
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from zeebe_tpu import tpu as _tpu  # noqa: F401  (enables x64)
from zeebe_tpu.tpu import (
    batch as rb,
    engine as engine_mod,
    kernel,
    state as state_mod,
)
from zeebe_tpu.tpu.shard import _shard_map

from tools.zbaudit import audit, audit_program, load_budget
from tools.zbaudit import passes as passes_mod
from tools.zbaudit.core import write_audit_baseline
from tools.zblint.engine import Finding, apply_baseline, load_baseline

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(findings):
    return {f.rule for f in findings}


def f32(*shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


NOW = jax.ShapeDtypeStruct((), jnp.int64)


# -- seeded anti-patterns ----------------------------------------------------

class TestDtypeFlow:
    def test_f64_leak_fires(self):
        def leaky(x):
            return jnp.asarray(x, jnp.float64) * 2.0

        entry = audit_program("fixture.f64", leaky, f32(8))
        result = audit(passes=["dtype-flow"], entries=[entry], budget={})
        assert "dtype-f64" in rules_of(result.findings)

    def test_f32_program_is_quiet(self):
        entry = audit_program("fixture.f32", lambda x: x * 2.0, f32(8))
        result = audit(passes=["dtype-flow"], entries=[entry], budget={})
        assert result.findings == []

    def test_i64_ratchet_fires_over_budget(self):
        def keys(k):
            return k + jnp.int64(1)

        entry = audit_program(
            "fixture.i64", keys, jax.ShapeDtypeStruct((8,), jnp.int64)
        )
        budget = {"dtype": {"i64_budget": {"fixture.i64": 0}}}
        result = audit(passes=["dtype-flow"], entries=[entry], budget=budget)
        assert "dtype-i64" in rules_of(result.findings)

    def test_i64_under_budget_emits_ratchet_hint(self):
        entry = audit_program(
            "fixture.i64", lambda k: k + jnp.int64(1),
            jax.ShapeDtypeStruct((8,), jnp.int64),
        )
        budget = {"dtype": {"i64_budget": {"fixture.i64": 100}}}
        result = audit(passes=["dtype-flow"], entries=[entry], budget=budget)
        assert result.findings == []
        assert result.report["dtype"]["ratchet_hints"]


class TestBoundary:
    def test_undonated_state_arg_fires(self):
        def step(state, now):
            return state + now

        entry = audit_program(
            "fixture.undonated", step, f32(64), NOW, state_args=(0,),
        )
        result = audit(passes=["boundary"], entries=[entry], budget={})
        assert "boundary-donation" in rules_of(result.findings)

    def test_donated_passthrough_is_quiet_and_aliased(self):
        def step(state, now):
            return state, jnp.sum(state) + now

        entry = audit_program(
            "fixture.donated", step, f32(64), NOW,
            state_args=(0,), donate_argnums=(0,),
        )
        result = audit(passes=["boundary"], entries=[entry], budget={})
        assert result.findings == []
        assert result.report["boundary"]["fixture.donated"][
            "alias_materialized"
        ]

    def test_donation_without_aliasing_fires(self):
        # output shape differs from the donated arg: XLA cannot alias,
        # the declared donation buys nothing
        def shrink(state):
            return jnp.sum(state)

        entry = audit_program(
            "fixture.noalias", shrink, f32(64),
            state_args=(0,), donate_argnums=(0,),
        )
        result = audit(passes=["boundary"], entries=[entry], budget={})
        assert "boundary-alias" in rules_of(result.findings)

    def test_host_callback_fires(self):
        def hostly(x):
            return jax.pure_callback(
                lambda a: a, jax.ShapeDtypeStruct((8,), jnp.float32), x
            )

        entry = audit_program("fixture.callback", hostly, f32(8))
        result = audit(passes=["boundary"], entries=[entry], budget={})
        assert "boundary-callback" in rules_of(result.findings)

    def test_suppressed_donation_gap_is_quiet(self):
        entry = audit_program(
            "fixture.waived", lambda s, n: s + n, f32(64), NOW,
            state_args=(0,), suppress=("boundary-donation",),
        )
        result = audit(passes=["boundary"], entries=[entry], budget={})
        assert result.findings == []


class TestCollectiveVolume:
    @staticmethod
    def _psum_program():
        mesh = Mesh(np.asarray(jax.devices()), ("partitions",))
        return _shard_map(
            lambda x: jax.lax.psum(x, "partitions"),
            mesh=mesh, in_specs=P("partitions"), out_specs=P(),
        )

    def test_oversized_collective_fires(self):
        n = len(jax.devices())
        entry = audit_program(
            "fixture.bigcoll", self._psum_program(), f32(n, 256),
            collective=True,
        )
        budget = {"collective": {"per_round_budget_bytes": 1}}
        result = audit(
            passes=["collective-volume"], entries=[entry], budget=budget
        )
        assert "collective-volume" in rules_of(result.findings)

    def test_collective_in_noncollective_entry_fires(self):
        n = len(jax.devices())
        entry = audit_program(
            "fixture.sneaky", self._psum_program(), f32(n, 4),
            collective=False,
        )
        result = audit(
            passes=["collective-volume"], entries=[entry],
            budget={"collective": {"per_round_budget_bytes": 1 << 30}},
        )
        assert "collective-unexpected" in rules_of(result.findings)

    def test_under_budget_collective_is_quiet(self):
        n = len(jax.devices())
        entry = audit_program(
            "fixture.smallcoll", self._psum_program(), f32(n, 4),
            collective=True,
        )
        result = audit(
            passes=["collective-volume"], entries=[entry],
            budget={"collective": {"per_round_budget_bytes": 1 << 30}},
        )
        assert result.findings == []


class TestHbmBudget:
    SMALL = {
        "default_config": {
            "capacity": 64, "num_vars": 8, "sub_capacity": 8, "wave": 16,
        },
        "hbm": {"device_budget_bytes": 16, "capacity_table": [64]},
    }

    def test_oversized_entry_fires(self):
        entry = audit_program("fixture.fat", lambda x: x + 1.0, f32(1024))
        result = audit(
            passes=["hbm-budget"], entries=[entry], budget=self.SMALL
        )
        assert any(
            f.rule == "hbm-budget" and "fixture.fat" in f.message
            for f in result.findings
        )

    def test_within_budget_is_quiet(self):
        budget = {
            "default_config": self.SMALL["default_config"],
            "hbm": {"device_budget_bytes": 1 << 40, "capacity_table": [64]},
        }
        entry = audit_program("fixture.thin", lambda x: x + 1.0, f32(8))
        result = audit(passes=["hbm-budget"], entries=[entry], budget=budget)
        assert result.findings == []


class TestOpCensus:
    @staticmethod
    def _gather_entry():
        def lookup(table, idx):
            return table[idx]

        return audit_program(
            "kernel.step", lookup, f32(64),
            jax.ShapeDtypeStruct((8,), jnp.int32),
        )

    def test_over_budget_census_fires(self, tmp_path, monkeypatch):
        fake = tmp_path / "census_budget.json"
        fake.write_text(json.dumps({
            "backend": "cpu", "gather": 0, "scatter": 0,
            "gather_scatter_total": 0,
        }))
        # os.path.join(REPO_ROOT, <absolute>) resolves to the absolute path
        monkeypatch.setattr(passes_mod, "CENSUS_BUDGET_PATH", str(fake))
        result = audit(
            passes=["op-census"], entries=[self._gather_entry()], budget={}
        )
        assert "op-census" in rules_of(result.findings)

    def test_under_budget_emits_ratchet_hint(self, tmp_path, monkeypatch):
        fake = tmp_path / "census_budget.json"
        fake.write_text(json.dumps({
            "backend": "cpu", "gather": 1000, "scatter": 1000,
            "gather_scatter_total": 1000,
        }))
        monkeypatch.setattr(passes_mod, "CENSUS_BUDGET_PATH", str(fake))
        result = audit(
            passes=["op-census"], entries=[self._gather_entry()], budget={}
        )
        assert result.findings == []
        assert result.report["op-census"]["ratchet_hints"]

    def test_mismatched_backend_skips_gate(self, tmp_path, monkeypatch):
        fake = tmp_path / "census_budget.json"
        fake.write_text(json.dumps({"backend": "tpu", "gather": 0}))
        monkeypatch.setattr(passes_mod, "CENSUS_BUDGET_PATH", str(fake))
        result = audit(
            passes=["op-census"], entries=[self._gather_entry()], budget={}
        )
        assert result.findings == []
        assert "skipped" in result.report["op-census"]


class TestSignatureGuard:
    def test_cache_over_declared_max_fires(self):
        entry = audit_program(
            "fixture.churner", lambda x: x * 2.0, f32(4), max_signatures=1,
        )
        # compile two distinct signatures against a declared max of 1
        entry.entry.fn(jnp.zeros((4,), jnp.float32))
        entry.entry.fn(jnp.zeros((9,), jnp.float32))
        result = audit(
            passes=["signature-guard"], entries=[entry], budget={}
        )
        assert "signature-cache" in rules_of(result.findings)

    def test_cache_within_max_is_quiet(self):
        entry = audit_program(
            "fixture.stable", lambda x: x * 2.0, f32(4), max_signatures=2,
        )
        entry.entry.fn(jnp.zeros((4,), jnp.float32))
        result = audit(
            passes=["signature-guard"], entries=[entry], budget={}
        )
        assert result.findings == []


# -- baseline ratchet --------------------------------------------------------

class TestBaselineRatchet:
    def test_round_trip_and_ratchet(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        f1 = Finding("hbm-budget", "zeebe_tpu/tpu/kernel.py", 10, "msg-a")
        f2 = Finding("dtype-i64", "zeebe_tpu/tpu/drive.py", 20, "msg-b")
        write_audit_baseline(path, [f1, f2])
        baseline = load_baseline(path)
        surfaced, baselined = apply_baseline([f1, f2], baseline)
        assert surfaced == [] and baselined == 2
        # a NEW finding is not grandfathered
        f3 = Finding("boundary-callback", "zeebe_tpu/tpu/shard.py", 5, "new")
        surfaced, baselined = apply_baseline([f1, f3], baseline)
        assert [f.rule for f in surfaced] == ["boundary-callback"]
        # ratchet down: rewrite after fixing f2 — f2 would now surface
        write_audit_baseline(path, [f1])
        surfaced, _ = apply_baseline([f1, f2], load_baseline(path))
        assert [f.rule for f in surfaced] == ["dtype-i64"]

    def test_baseline_comment_names_zbaudit(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        write_audit_baseline(path, [])
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        assert "zbaudit" in doc["comment"]
        assert doc["entries"] == {}

    def test_checked_in_baseline_is_empty(self):
        # the live tree audits clean: nothing is grandfathered
        path = os.path.join(REPO_ROOT, "tools", "zbaudit_baseline.json")
        with open(path, encoding="utf-8") as f:
            assert json.load(f)["entries"] == {}


# -- HBM model accuracy ------------------------------------------------------

class TestHbmModel:
    def test_model_matches_measured_device_bytes(self):
        """The closed-form state model vs real committed buffers: put the
        default-config state on device and sum buffer bytes (issue
        acceptance: within 10%; the model is an exact leaf-bytes sum, so
        this pins equality modulo backend padding)."""
        budget = load_budget()
        report = {}
        passes_mod.pass_hbm([], budget, report)
        model = report["hbm"]
        dc = budget["default_config"]
        state = state_mod.make_state(
            capacity=dc["capacity"], num_vars=dc["num_vars"],
            job_capacity=dc["capacity"], sub_capacity=dc["sub_capacity"],
        )
        measured = sum(
            jax.device_put(leaf).nbytes for leaf in jax.tree.leaves(state)
        )
        modeled = model["state_bytes_at_default_capacity"]
        assert abs(modeled - measured) / measured < 0.10

    def test_capacity_table_is_linear_in_capacity(self):
        budget = load_budget()
        report = {}
        passes_mod.pass_hbm([], budget, report)
        model = report["hbm"]
        slope = model["bytes_per_capacity_row"]
        fixed = model["fixed_bytes"]
        assert slope > 0
        for cap, total in model["capacity_table"].items():
            predicted = slope * int(cap) + fixed
            assert abs(predicted - total) / total < 0.01


# -- donation parity pins ----------------------------------------------------

def _timer_state(capacity=64, num_vars=8, due=3):
    """EngineState with ``due`` timers due at t<=10 (seeded directly,
    like test_job_backlog_probe seeds jobs)."""
    state = state_mod.make_state(
        capacity=capacity, num_vars=num_vars, job_capacity=capacity,
        sub_capacity=8,
    )
    timer_key = np.asarray(state.timer_key).copy()
    timer_due = np.asarray(state.timer_due).copy()
    for i in range(due):
        timer_key[i] = 100 + 7 * i
        timer_due[i] = 10
    return dataclasses.replace(
        state,
        timer_key=jnp.asarray(timer_key), timer_due=jnp.asarray(timer_due),
    )


class TestDonationParity:
    def test_tick_donated_matches_undonated(self):
        """kernel.tick donates its (read-only) state: the triggered batch
        must be bit-identical to the un-donated reference and the
        passthrough state bit-identical to the input."""
        state = _timer_state()
        now = jnp.asarray(100, jnp.int64)
        snapshot = [np.asarray(leaf) for leaf in jax.tree.leaves(state)]
        # un-donated reference first (it leaves `state` alive)
        ref_out, ref_count = kernel.tick_kernel(state, now)
        state2, out, count = kernel.tick_jit(state, now)
        assert int(count) == int(ref_count) == 3
        for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(ref_out)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(state2), snapshot):
            np.testing.assert_array_equal(np.asarray(a), b)

    def test_due_probe_donated_matches_undonated(self):
        eng = engine_mod.TpuPartitionEngine(capacity=64, sub_capacity=8)
        now = jnp.asarray(0, jnp.int64)
        ref = int(engine_mod._due_probe_kernel(eng.state, now))
        eng.state, mask = engine_mod._due_probe_jit(eng.state, now)
        assert int(mask) == ref
        # the rebound state is alive and probes identically again
        eng.state, mask2 = engine_mod._due_probe_jit(eng.state, now)
        assert int(mask2) == ref


# -- runtime recompile guard -------------------------------------------------

class TestRecompileGuard:
    def test_step_waves_of_varying_record_count_share_one_signature(self):
        """The serving-latency cliff zbaudit's signature guard exists
        for: waves carry a varying VALID count inside a fixed wave shape,
        so stepping different record counts must not recompile."""
        import bench

        graph, _meta = bench.build_graph()
        num_vars = max(graph.num_vars, 8)
        graph = dataclasses.replace(graph, num_vars=num_vars)
        state = state_mod.make_state(
            capacity=128, num_vars=num_vars, job_capacity=128,
            sub_capacity=8,
        )
        wave = rb.empty(16, num_vars)
        state, _em, _stats = kernel.step_jit(
            graph, state, wave, jnp.asarray(0, jnp.int64),
            synthetic_workers=False,
        )
        before = kernel.step_jit._cache_size()
        for count, now in ((1, 1000), (3, 2000)):
            wave = rb.empty(16, num_vars)
            wave = dataclasses.replace(
                wave,
                valid=wave.valid.at[:count].set(True),
                rtype=wave.rtype.at[:count].set(kernel.RT_CMD),
            )
            state, _em, _stats = kernel.step_jit(
                graph, state, wave, jnp.asarray(now, jnp.int64),
                synthetic_workers=False,
            )
        assert kernel.step_jit._cache_size() == before


# -- the gate itself ---------------------------------------------------------

class TestGate:
    def test_unknown_pass_rejected(self):
        with pytest.raises(ValueError, match="unknown zbaudit pass"):
            audit(passes=["no-such-pass"], entries=[], budget={})

    def test_budget_file_parses_with_required_sections(self):
        budget = load_budget()
        for section in ("default_config", "audit_config", "hbm", "dtype",
                        "collective"):
            assert section in budget

    def test_live_tree_audits_clean(self, tmp_path):
        """The CI invocation, in a clean subprocess (the in-process
        registry carries compile-cache state from other tests): exit 0,
        zero findings, every driver entry built."""
        out = str(tmp_path / "report.json")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.zbaudit", "--json", "--out", out],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        with open(out, encoding="utf-8") as f:
            doc = json.load(f)
        assert doc["findings"] == []
        for name in ("kernel.step", "kernel.tick", "engine.due_probe",
                     "drive.round", "drive.quiesce", "shard.sharded_step",
                     "shard.frame_exchange", "shard.sharded_drive"):
            assert name in doc["entries"]
        assert doc["report"]["hbm"]["serving_peak_bytes"] > 0
