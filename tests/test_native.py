"""Native runtime layer tests (ring buffer, log storage, frame scan, kv).

Reference-parity targets per component:
- RingBuffer ↔ dispatcher tests (claim/commit, wrap, backpressure,
  concurrent producers; ``dispatcher/src/test``, 4,123 LoC).
- NativeLogStorage ↔ FsLogStorage tests (append/read/roll/truncate), plus
  cross-backend disk-format compatibility with the Python storage.
- frame_scan ↔ recovery scan (torn/corrupt tail discard).
- KvStore ↔ zb-map tests (put/get/remove/iterate/snapshot, 7,123 LoC).
"""

import threading
import zlib

import pytest

from zeebe_tpu import native
from zeebe_tpu.log.storage import SegmentedLogStorage
from zeebe_tpu.protocol import codec
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import JobRecord, Record
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import JobIntent

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native layer unavailable: {native.build_error()}"
)


class TestRingBuffer:
    def test_fifo_roundtrip(self):
        rb = native.RingBuffer(1 << 12)
        msgs = [f"msg-{i}".encode() for i in range(10)]
        for m in msgs:
            assert rb.offer(m)
        assert rb.drain() == msgs
        rb.close()

    def test_wraparound_many_times(self):
        rb = native.RingBuffer(256)
        for i in range(10_000):
            m = f"x{i}".encode()
            assert rb.offer(m)
            assert rb.poll() == m
        rb.close()

    def test_backpressure_when_full(self):
        rb = native.RingBuffer(256)
        count = 0
        while rb.offer(b"0123456789abcdef"):
            count += 1
        assert 0 < count <= 256 // 24 + 1
        # consuming frees space
        assert rb.poll() is not None
        assert rb.offer(b"0123456789abcdef")
        rb.close()

    def test_fragment_too_large_rejected(self):
        rb = native.RingBuffer(256)
        with pytest.raises(ValueError):
            rb.offer(b"x" * 200)
        rb.close()

    def test_interleaved_offer_poll_preserves_order(self):
        rb = native.RingBuffer(1 << 10)
        out = []
        n = 0
        for round_ in range(200):
            for _ in range(3):
                rb.offer(f"m{n}".encode())
                n += 1
            out.extend(rb.drain())
        assert out == [f"m{i}".encode() for i in range(n)]
        rb.close()

    def test_concurrent_producers(self):
        """Many producer threads, one consumer: every message arrives exactly
        once (the dispatcher's many-producer contract)."""
        rb = native.RingBuffer(1 << 14)
        per_producer = 2_000
        nproducers = 4
        received = []
        done = threading.Event()

        def produce(pid):
            for i in range(per_producer):
                msg = f"{pid}:{i}".encode()
                while not rb.offer(msg):
                    pass  # backpressure: spin

        def consume():
            while len(received) < per_producer * nproducers:
                item = rb.poll()
                if item is not None:
                    received.append(item)
            done.set()

        threads = [threading.Thread(target=produce, args=(p,)) for p in range(nproducers)]
        consumer = threading.Thread(target=consume)
        consumer.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert done.wait(timeout=30)
        consumer.join()

        assert len(received) == per_producer * nproducers
        # per-producer FIFO order holds; cross-producer order is unspecified
        by_producer = {p: [] for p in range(nproducers)}
        for item in received:
            pid, i = item.split(b":")
            by_producer[int(pid)].append(int(i))
        for seq in by_producer.values():
            assert seq == list(range(per_producer))
        rb.close()


class TestNativeLogStorage:
    def test_append_read_roundtrip(self, tmp_path):
        ls = native.NativeLogStorage(str(tmp_path / "log"))
        a1 = ls.append(b"hello")
        a2 = ls.append(b"world!")
        assert ls.read(a1, 5) == b"hello"
        assert ls.read(a2, 6) == b"world!"
        ls.close()

    def test_segment_roll(self, tmp_path):
        ls = native.NativeLogStorage(str(tmp_path / "log"), segment_size=64)
        addrs = [ls.append(b"0123456789" * 3) for _ in range(5)]
        segs = {ls.segment_of(a) for a in addrs}
        assert len(segs) > 1
        for a in addrs:
            assert ls.read(a, 30) == b"0123456789" * 3
        ls.close()

    def test_reopen_recovers(self, tmp_path):
        path = str(tmp_path / "log")
        ls = native.NativeLogStorage(path, segment_size=64)
        addrs = [ls.append(f"block-{i}".encode()) for i in range(10)]
        ls.close()
        ls = native.NativeLogStorage(path, segment_size=64)
        for i, a in enumerate(addrs):
            assert ls.read(a, len(f"block-{i}")) == f"block-{i}".encode()
        # appends continue in the tail segment
        a = ls.append(b"after-reopen")
        assert ls.read(a, 12) == b"after-reopen"
        ls.close()

    def test_truncate(self, tmp_path):
        ls = native.NativeLogStorage(str(tmp_path / "log"), segment_size=64)
        keep = ls.append(b"keep")
        cut = ls.append(b"cut-me")
        later = [ls.append(b"0123456789" * 4) for _ in range(4)]
        ls.truncate(cut)
        assert ls.read(keep, 4) == b"keep"
        assert ls.read(cut, 6) == b""  # past EOF now
        # later segments were deleted from disk
        with pytest.raises(OSError):
            ls.read_segment(ls.segment_of(later[-1]))
        a = ls.append(b"fresh")
        assert a == cut  # reuses the truncated tail position
        ls.close()

    def test_disk_format_compatible_with_python_backend(self, tmp_path):
        """Blocks written by the native backend are read back by the Python
        backend and vice versa (same segment header + addressing)."""
        path = str(tmp_path / "log")
        nat = native.NativeLogStorage(path, segment_size=1024)
        a1 = nat.append(b"from-native")
        nat.close()

        py = SegmentedLogStorage(path, segment_size=1024)
        assert py.read(a1, 11) == b"from-native"
        a2 = py.append(b"from-python")
        py.close()

        nat = native.NativeLogStorage(path, segment_size=1024)
        assert nat.read(a2, 11) == b"from-python"
        blocks = list(nat.iter_blocks())
        assert len(blocks) == 1
        assert blocks[0][1] == b"from-native" + b"from-python"
        nat.close()


def _record(pos, key=7):
    return Record(
        position=pos,
        key=key,
        timestamp=1000 + pos,
        metadata=RecordMetadata(
            record_type=RecordType.COMMAND,
            value_type=ValueType.JOB,
            intent=int(JobIntent.CREATE),
        ),
        value=JobRecord(type="native-test", retries=3),
    )


class TestFrameScan:
    def test_scan_valid_frames(self):
        frames = b"".join(codec.encode_record(_record(p)) for p in range(5))
        offsets, valid = native.frame_scan(frames)
        assert len(offsets) == 5
        assert valid == len(frames)
        # offsets decode correctly with the python codec
        for i, off in enumerate(offsets):
            rec, _ = codec.decode_record(frames, off)
            assert rec.position == i

    def test_torn_tail_stops_scan(self):
        frames = b"".join(codec.encode_record(_record(p)) for p in range(3))
        torn = frames + codec.encode_record(_record(3))[:-10]
        offsets, valid = native.frame_scan(torn)
        assert len(offsets) == 3
        assert valid == len(frames)

    def test_corrupt_tail_stops_scan(self):
        good = codec.encode_record(_record(0))
        bad = bytearray(codec.encode_record(_record(1)))
        bad[20] ^= 0xFF  # flip a body byte: crc mismatch
        offsets, valid = native.frame_scan(bytes(good + bad))
        assert len(offsets) == 1
        assert valid == len(good)

    def test_crc32_matches_zlib(self):
        for data in (b"", b"a", b"hello world" * 100):
            assert native.crc32(data) == zlib.crc32(data)


class TestKvStore:
    def test_put_get_delete(self):
        kv = native.KvStore()
        kv.put(b"a", b"1")
        kv.put(b"b", b"22")
        assert kv.get(b"a") == b"1"
        assert kv.get(b"b") == b"22"
        assert kv.get(b"missing") is None
        assert len(kv) == 2
        assert kv.delete(b"a")
        assert not kv.delete(b"a")
        assert kv.get(b"a") is None
        assert len(kv) == 1
        kv.close()

    def test_overwrite(self):
        kv = native.KvStore()
        kv.put(b"k", b"v1")
        kv.put(b"k", b"v2-longer")
        assert kv.get(b"k") == b"v2-longer"
        assert len(kv) == 1
        kv.close()

    def test_many_keys_resize(self):
        kv = native.KvStore()
        n = 20_000
        for i in range(n):
            kv.put(f"key-{i}".encode(), f"value-{i}".encode())
        assert len(kv) == n
        for i in range(0, n, 997):
            assert kv.get(f"key-{i}".encode()) == f"value-{i}".encode()
        kv.close()

    def test_empty_value(self):
        kv = native.KvStore()
        kv.put(b"k", b"")
        assert kv.get(b"k") == b""
        kv.close()

    def test_items_iteration(self):
        kv = native.KvStore()
        expect = {}
        for i in range(100):
            k, v = f"k{i}".encode(), f"v{i}".encode()
            kv.put(k, v)
            expect[k] = v
        kv.delete(b"k50")
        del expect[b"k50"]
        assert dict(kv.items()) == expect
        kv.close()

    def test_checkpoint_restore(self, tmp_path):
        kv = native.KvStore()
        for i in range(1000):
            kv.put(f"key-{i}".encode(), (f"val-{i}" * 3).encode())
        kv.delete(b"key-500")
        path = str(tmp_path / "state.ckpt")
        kv.checkpoint(path)
        kv.close()

        restored = native.KvStore.restore(path)
        assert len(restored) == 999
        assert restored.get(b"key-1") == b"val-1" * 3
        assert restored.get(b"key-500") is None
        restored.close()

    def test_restore_corrupt_fails(self, tmp_path):
        kv = native.KvStore()
        kv.put(b"k", b"v")
        path = str(tmp_path / "state.ckpt")
        kv.checkpoint(path)
        kv.close()
        with open(path, "r+b") as f:
            f.seek(4)
            f.write(b"\xff")
        with pytest.raises(OSError):
            native.KvStore.restore(path)

    def test_restore_missing_fails(self, tmp_path):
        with pytest.raises(OSError):
            native.KvStore.restore(str(tmp_path / "nope.ckpt"))


class TestNativeStorageIntegration:
    """The native backend serving the RUNTIME (VERDICT round-2 item 7:
    integrated, not orphaned): a LogStream over the C++ storage, the
    documented ``SegmentedLogStorage(native=True)`` selector, and the
    cold record cache spilling to the kv store."""

    def test_logstream_over_native_storage(self, tmp_path):
        from zeebe_tpu.log.logstream import LogStream
        from zeebe_tpu.log.storage import SegmentedLogStorage

        from tests.test_raft import job_record

        storage = SegmentedLogStorage(
            str(tmp_path / "nlog"), segment_size=4096, native=True
        )
        assert type(storage).__name__ == "NativeLogStorage"
        log = LogStream(storage, partition_id=0)
        for i in range(300):
            log.append([job_record(i)])
        assert len(storage._segments) > 2
        # compaction is segment-aligned through the native delete path
        base = log.compact(200)
        assert 0 < base <= 200
        assert log.record_at(base) is not None
        assert log.record_at(base - 1) is None
        storage.close()

        # recovery reopens the same files (identical on-disk format)
        storage2 = SegmentedLogStorage(str(tmp_path / "nlog"), native=True)
        log2 = LogStream(storage2, partition_id=0)
        assert log2.next_position == 300
        assert log2.base_position == base
        storage2.close()

    def test_python_and_native_formats_interchange(self, tmp_path):
        from zeebe_tpu.log.logstream import LogStream
        from zeebe_tpu.log.storage import SegmentedLogStorage

        from tests.test_raft import job_record

        d = str(tmp_path / "mixed")
        py_storage = SegmentedLogStorage(d, segment_size=4096)
        log = LogStream(py_storage, partition_id=0)
        for i in range(50):
            log.append([job_record(i)])
        py_storage.close()
        # reopen the same directory with the native backend
        n_storage = SegmentedLogStorage(d, segment_size=4096, native=True)
        log2 = LogStream(n_storage, partition_id=0)
        assert log2.next_position == 50
        log2.append([job_record(50)])
        n_storage.close()
        # and back with the Python one
        py2 = SegmentedLogStorage(d, segment_size=4096)
        log3 = LogStream(py2, partition_id=0)
        assert log3.next_position == 51
        py2.close()

    def test_record_cache_spills_to_kvstore(self):
        from zeebe_tpu.engine.interpreter import RecordCache

        from tests.test_raft import job_record

        cache = RecordCache(hot_capacity=16)
        assert cache._kv is not None, "native layer should be available here"
        records = {}
        for i in range(200):
            r = job_record(i)
            r.position = i
            records[i] = r
            cache[i] = r
        assert len(cache._hot) == 16  # bounded heap
        # cold reads decode from the kv store, hot reads stay objects
        for i in (0, 5, 100, 199):
            got = cache.get(i)
            assert got is not None
            assert got.position == i
            assert got.key == records[i].key
        assert cache.get(9999) is None
        assert 150 in cache

    def test_native_storage_cluster_broker(self, tmp_path):
        from zeebe_tpu.runtime.cluster_broker import ClusterBroker
        from zeebe_tpu.runtime.config import BrokerCfg

        cfg = BrokerCfg()
        cfg.network.client_port = 0
        cfg.network.management_port = 0
        cfg.network.subscription_port = 0
        cfg.metrics.port = 0
        cfg.metrics.enabled = False
        cfg.cluster.node_id = "nat-0"
        cfg.data.native_storage = True
        broker = ClusterBroker(cfg, str(tmp_path / "nat"))
        try:
            broker.open_partition(0).join(10)
            broker.bootstrap_partition(0, {})
            import time as _t
            deadline = _t.time() + 20
            while _t.time() < deadline and not broker.partitions[0].is_leader:
                _t.sleep(0.02)
            assert broker.partitions[0].is_leader
            assert type(broker.partitions[0].storage).__name__ == "NativeLogStorage"

            from zeebe_tpu.gateway.cluster_client import ClusterClient
            from zeebe_tpu.models.bpmn.builder import Bpmn

            client = ClusterClient([broker.client_address])
            try:
                client.deploy_model(
                    Bpmn.create_process("np").start_event()
                    .service_task("t", type="svc").end_event().done()
                )
                done = []
                w = client.open_job_worker(
                    "svc", lambda pid, rec: done.append(rec.key) or {}
                )
                client.create_instance("np", {})
                deadline = _t.time() + 20
                while _t.time() < deadline and not done:
                    _t.sleep(0.02)
                assert done
                w.close()
            finally:
                client.close()
        finally:
            broker.close()
