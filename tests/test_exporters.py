"""Exporter plane: director dispatch, position acks, compaction gating,
built-in sinks, config, and crash-resume (single-broker level; the
cluster-level invariants live in tests/test_chaos.py)."""

import json
import os

import pytest

from zeebe_tpu.exporter import (
    Exporter,
    InMemoryExporter,
    JsonlExporter,
    MetricsExporter,
    build_exporter,
    read_audit_docs,
)
from zeebe_tpu.exporter.director import ExporterDirector, fold_tail_acks
from zeebe_tpu.exporter.jsonl import _recover_file_tail
from zeebe_tpu.gateway import JobWorker, ZeebeClient
from zeebe_tpu.log import LogStream, SegmentedLogStorage
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import ExporterIntent, JobIntent
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import ExporterPositionRecord, JobRecord, Record
from zeebe_tpu.runtime import Broker, ControlledClock
from zeebe_tpu.runtime.config import ExporterCfg, load_config
from zeebe_tpu.runtime.metrics import (
    GLOBAL_REGISTRY,
    MetricsRegistry,
    event_count,
    render_with_global,
)


@pytest.fixture(autouse=True)
def _reset_memory_sinks():
    InMemoryExporter.reset()
    yield
    InMemoryExporter.reset()


def job_record(i: int) -> Record:
    return Record(
        key=i,
        metadata=RecordMetadata(
            record_type=RecordType.EVENT,
            value_type=ValueType.JOB,
            intent=int(JobIntent.CREATED),
        ),
        value=JobRecord(type=f"t{i}"),
    )


def simple_model(pid="exp-proc"):
    return (
        Bpmn.create_process(pid)
        .start_event("s")
        .service_task("t", type="svc")
        .end_event("e")
        .done()
    )


def make_log(tmp_path, segment_size=512):
    storage = SegmentedLogStorage(str(tmp_path / "log"), segment_size=segment_size)
    return LogStream(storage)


def make_director(log, exporters, clock=None):
    director = ExporterDirector(
        0, log, exporters, append_fn=lambda recs: log.append(recs),
        clock=clock,
    )
    return director


# ---------------------------------------------------------------------------
# director core
# ---------------------------------------------------------------------------


class TestDirector:
    def test_dispatches_committed_records_in_order_and_acks(self, tmp_path):
        log = make_log(tmp_path)
        log.append([job_record(i) for i in range(10)])
        mem = InMemoryExporter()
        director = make_director(log, [("mem", mem)])
        director.open({})
        assert director.pump() is True
        assert mem.positions() == list(range(10))
        # the ack went into the log as a replicated EXPORTER record
        acks = [
            r for r in log.reader(0)
            if int(r.metadata.value_type) == int(ValueType.EXPORTER)
            and int(r.metadata.intent) == int(ExporterIntent.ACKNOWLEDGE)
        ]
        # registration ack (-1) + progress ack; the progress ack lands on
        # the last VISIBLE record (9) — never on the trailing hidden
        # registration record at 10, which the exporter never saw (a file
        # sink compares its recovered tail against the replicated ack on
        # open, and an ack on a hidden position would false-report an
        # audit hole after a restart)
        assert [a.value.position for a in acks] == [-1, 9]
        director.close()
        assert mem.closed

    def test_acks_never_self_feed(self, tmp_path):
        """Pumping to quiescence terminates: ack records are hidden from
        exporters and an admin-only batch writes no further ack."""
        log = make_log(tmp_path)
        log.append([job_record(0)])
        director = make_director(log, [("mem", InMemoryExporter())])
        director.open({})
        for _ in range(5):
            if not director.pump():
                break
        else:
            pytest.fail("director never reached quiescence")
        n_records = log.next_position
        director.pump()
        assert log.next_position == n_records, "idle pump appended records"

    def test_failing_exporter_is_isolated_and_retried(self, tmp_path):
        clock_ms = [1_000_000]
        log = make_log(tmp_path)
        log.append([job_record(i) for i in range(4)])
        ok, bad = InMemoryExporter(), InMemoryExporter()
        bad.fail = True
        director = make_director(
            log, [("ok", ok), ("bad", bad)], clock=lambda: clock_ms[0]
        )
        director.open({})
        director.pump()
        assert ok.positions() == [0, 1, 2, 3], "healthy exporter blocked"
        assert bad.positions() == []
        f = GLOBAL_REGISTRY.counter(
            "exporter_export_failures", exporter="bad", partition="0"
        )
        assert f.value >= 1
        # backoff: an immediate re-pump skips the failing exporter
        failures_before = f.value
        director.pump()
        assert f.value == failures_before
        # after the backoff window it retries; once fixed it catches up
        bad.fail = False
        clock_ms[0] += 60_000
        director.pump()
        assert bad.positions()[: 4] == [0, 1, 2, 3]
        director.close()

    def test_exporter_lag_gauge_tracks_commit_distance(self, tmp_path):
        log = make_log(tmp_path)
        log.append([job_record(i) for i in range(6)])
        stuck = InMemoryExporter()
        stuck.fail = True
        director = make_director(log, [("lagging", stuck)])
        director.open({})
        director.pump()
        gauge = GLOBAL_REGISTRY.gauge(
            "exporter_lag", exporter="lagging", partition="0"
        )
        # behind by every VISIBLE record — the registration ack is this
        # plane's own hidden traffic, not exportable lag (measured against
        # the raw commit position the gauge could never read 0)
        assert gauge.value == 6
        stuck.fail = False
        director.handles[0].retry_at_ms = 0
        director.pump()
        assert gauge.value == 0  # fully caught up reads zero at idle
        director.close()
        # the gauge renders into the merged /metrics text
        assert "exporter_lag" in render_with_global(MetricsRegistry())

    def test_broken_open_is_isolated(self, tmp_path):
        class Exploding(Exporter):
            def open(self, controller):
                raise RuntimeError("boom")

        log = make_log(tmp_path)
        log.append([job_record(0)])
        ok = InMemoryExporter()
        director = make_director(log, [("boom", Exploding()), ("ok", ok)])
        director.open({})
        director.pump()
        assert ok.positions() == [0]
        assert director.handles[0].broken is not None
        # one live exporter keeps the ack plane alive for tracing...
        assert director.can_ack()
        director.close()

    def test_all_broken_handles_cannot_ack(self, tmp_path):
        """Tracing probes can_ack() to decide whether the response/apply
        is a span's final stage: a director whose every exporter broke at
        open will never ack, and waiting on it would leak every span."""
        class Exploding(Exporter):
            def open(self, controller):
                raise RuntimeError("boom")

        log = make_log(tmp_path)
        log.append([job_record(0)])
        director = make_director(log, [("a", Exploding()), ("b", Exploding())])
        director.open({})
        assert not director.can_ack()
        director.close()

    def test_manual_ack_holds_position_until_confirmed(self, tmp_path):
        class AsyncSink(InMemoryExporter):
            MANUAL_ACK = True

        log = make_log(tmp_path)
        log.append([job_record(i) for i in range(3)])
        sink = AsyncSink()
        director = make_director(log, [("async", sink)])
        director.open({})
        director.pump()
        handle = director.handles[0]
        assert sink.positions() == [0, 1, 2]  # delivered...
        assert handle.position == -1          # ...but not acked
        assert director.compaction_floor() == 0
        sink.controller.update_position(2)
        director.pump()
        assert handle.position == 2
        assert director.compaction_floor() == 3
        director.close()

    def test_manual_ack_consuming_without_confirm_fires_stall(self, tmp_path):
        """A MANUAL_ACK sink that keeps accepting batches but never calls
        update_position is a stall: its position pins the floor even
        though its cursor runs ahead of the commit position."""
        class AsyncSink(InMemoryExporter):
            MANUAL_ACK = True

        clock_ms = [1_000_000]
        log = make_log(tmp_path)
        log.append([job_record(i) for i in range(3)])
        sink = AsyncSink()
        director = make_director(log, [("async", sink)], clock=lambda: clock_ms[0])
        director.open({})
        director.pump()
        assert sink.positions() == [0, 1, 2]  # consuming fine...
        assert director.compaction_floor() == 0  # ...but pinning
        s0 = event_count("exporter_floor_stalls")
        clock_ms[0] += ExporterDirector.STALL_AFTER_MS + 1
        director.pump()
        assert event_count("exporter_floor_stalls") - s0 == 1
        # confirming clears the stall episode
        sink.controller.update_position(2)
        director.pump()
        assert director.handles[0].stall_warned is False
        director.close()

    def test_manual_ack_confirming_everything_visible_is_not_a_stall(self, tmp_path):
        """A MANUAL_ACK sink acked at the last VISIBLE record is fully
        caught up — the trailing hidden ack records above it must not
        read as lag or fire a false stall warning."""
        class AsyncSink(InMemoryExporter):
            MANUAL_ACK = True

        clock_ms = [1_000_000]
        log = make_log(tmp_path)
        log.append([job_record(i) for i in range(3)])
        sink = AsyncSink()
        director = make_director(log, [("async", sink)], clock=lambda: clock_ms[0])
        director.open({})
        director.pump()
        sink.controller.update_position(2)  # confirm everything visible
        director.pump()
        s0 = event_count("exporter_floor_stalls")
        clock_ms[0] += ExporterDirector.STALL_AFTER_MS * 3
        director.pump()
        assert event_count("exporter_floor_stalls") == s0, "false stall"
        assert director.handles[0].stall_warned is False
        gauge = GLOBAL_REGISTRY.gauge(
            "exporter_lag", exporter="async", partition="0"
        )
        assert gauge.value == 0, "hidden ack records counted as lag"
        director.close()

    def test_fold_tail_acks_covers_unreplayed_tail(self, tmp_path):
        log = make_log(tmp_path)
        log.append([job_record(0)])
        log.append([
            Record(
                metadata=RecordMetadata(
                    record_type=RecordType.COMMAND,
                    value_type=ValueType.EXPORTER,
                    intent=int(ExporterIntent.ACKNOWLEDGE),
                ),
                value=ExporterPositionRecord(exporter_id="x", position=7),
            )
        ])
        assert fold_tail_acks({"x": 3}, log, 0) == {"x": 7}
        assert fold_tail_acks({}, log, 0) == {"x": 7}
        # monotonic: engine state ahead of the tail wins
        assert fold_tail_acks({"x": 11}, log, 0) == {"x": 11}


# ---------------------------------------------------------------------------
# compaction gating + stall warning
# ---------------------------------------------------------------------------


class TestCompactionGating:
    def _fill_segments(self, log, n=40):
        for i in range(n):
            log.append([job_record(i)])
        log.flush()

    def test_stuck_exporter_holds_the_floor_and_compact_refuses(self, tmp_path):
        clock_ms = [1_000_000]
        log = make_log(tmp_path, segment_size=256)
        self._fill_segments(log)
        stuck = InMemoryExporter()
        stuck.fail = True
        director = make_director(log, [("stuck", stuck)], clock=lambda: clock_ms[0])
        director.open({})
        director.pump()
        # the caller asks to compact everything; the floor provider refuses
        assert log.compact(log.next_position) == 0
        assert log.base_position == 0
        assert log.record_at(0) is not None, "unexported record dropped"
        # the stall warning fires once the exporter stays stuck
        s0 = event_count("exporter_floor_stalls")
        clock_ms[0] += ExporterDirector.STALL_AFTER_MS + 1
        director.pump()
        assert event_count("exporter_floor_stalls") - s0 == 1
        # ...once per episode, not per pump
        clock_ms[0] += ExporterDirector.STALL_AFTER_MS + 1
        director.pump()
        assert event_count("exporter_floor_stalls") - s0 == 1
        director.close()

    def test_acking_releases_the_floor(self, tmp_path):
        clock_ms = [1_000_000]
        log = make_log(tmp_path, segment_size=256)
        self._fill_segments(log)
        stuck = InMemoryExporter()
        stuck.fail = True
        director = make_director(log, [("stuck", stuck)], clock=lambda: clock_ms[0])
        director.open({})
        director.pump()
        assert log.compact(log.next_position) == 0
        stuck.fail = False
        clock_ms[0] += 60_000
        director.pump()
        new_base = log.compact(log.next_position)
        assert new_base > 0, "ack did not release the compaction floor"
        # at-least-once: everything the exporter saw is still in order
        positions = stuck.positions()
        assert positions == sorted(positions)
        director.close()

    def test_removed_provider_stops_gating(self, tmp_path):
        log = make_log(tmp_path, segment_size=256)
        self._fill_segments(log)
        stuck = InMemoryExporter()
        stuck.fail = True
        director = make_director(log, [("stuck", stuck)])
        director.open({})
        director.pump()
        assert log.compact(log.next_position) == 0
        director.close()  # deconfigured exporter no longer pins
        assert log.compact(log.next_position) > 0


# ---------------------------------------------------------------------------
# broker integration (engine state + snapshot + crash resume)
# ---------------------------------------------------------------------------


class TestBrokerIntegration:
    def _run_traffic(self, broker, n=5, pid="exp-proc"):
        client = ZeebeClient(broker)
        client.deploy_model(simple_model(pid))
        worker = JobWorker(broker, "svc", lambda ctx: {"done": True})
        for i in range(n):
            client.create_instance(pid, {"i": i})
        broker.run_until_idle()
        return worker

    def test_exports_every_committed_record_and_persists_positions(self, tmp_path):
        mem = InMemoryExporter()
        broker = Broker(data_dir=str(tmp_path), exporters=[("mem", mem)])
        self._run_traffic(broker)
        log = broker.partitions[0].log
        visible = [
            r.position for r in log.reader(0)
            if int(r.metadata.value_type) != int(ValueType.EXPORTER)
        ]
        assert mem.positions() == visible
        engine = broker.partitions[0].engine
        assert engine.exporter_positions["mem"] >= visible[-1]
        assert engine.compaction_floor() <= engine.exporter_positions["mem"] + 1
        broker.close()

    def test_shared_instance_pair_rejected_with_multiple_partitions(self, tmp_path):
        """One instance across partitions would interleave both streams
        into one sink (and the JSONL dedup tail would silently drop the
        lower partition's records) — fail boot instead."""
        with pytest.raises(ValueError, match="instance pairs"):
            Broker(
                num_partitions=2, data_dir=str(tmp_path),
                exporters=[("mem", InMemoryExporter())],
            )
        # cfg entries build a fresh instance per partition: fine
        broker = Broker(
            num_partitions=2, data_dir=str(tmp_path / "ok"),
            exporters=[ExporterCfg(id="mem", type="memory")],
        )
        directors = [p.exporter_director for p in broker.partitions]
        assert directors[0].handles[0].exporter is not directors[1].handles[0].exporter
        broker.close()

    def test_positions_survive_snapshot_restore(self, tmp_path):
        mem = InMemoryExporter()
        broker = Broker(data_dir=str(tmp_path), exporters=[("mem", mem)])
        self._run_traffic(broker)
        broker.snapshot()
        acked = broker.partitions[0].engine.exporter_positions["mem"]
        broker.close()

        mem2 = InMemoryExporter()
        restarted = Broker(data_dir=str(tmp_path), exporters=[("mem", mem2)])
        assert restarted.partitions[0].engine.exporter_positions["mem"] == acked
        # resume: nothing re-exported below the ack
        restarted.run_until_idle()
        assert all(p > acked for p in mem2.positions())
        restarted.close()

    def test_deconfigured_exporter_stops_pinning_the_floor(self, tmp_path):
        """Restarting without a previously configured exporter appends an
        EXPORTER REMOVE for its recovered entry: the stale position (here
        a -1 registration that never acked) no longer pins compaction."""
        never = InMemoryExporter()
        never.fail = True  # registers at -1, never acks
        mem = InMemoryExporter()
        broker = Broker(
            data_dir=str(tmp_path), exporters=[("mem", mem), ("gone", never)]
        )
        self._run_traffic(broker)
        engine = broker.partitions[0].engine
        assert engine.exporter_positions["gone"] == -1
        assert engine.compaction_floor() == 0  # pinned by "gone"
        broker.close()

        mem2 = InMemoryExporter()
        restarted = Broker(data_dir=str(tmp_path), exporters=[("mem", mem2)])
        restarted.run_until_idle()
        engine = restarted.partitions[0].engine
        assert "gone" not in engine.exporter_positions
        assert engine.compaction_floor() > 0
        restarted.close()

    def test_removing_the_last_exporter_sweeps_its_position(self, tmp_path):
        """Removing ALL exporters still sweeps the recovered entries: with
        no director installed at all, the boot path itself must append the
        REMOVEs or the last-removed exporter's stale position pins the
        compaction floor forever."""
        mem = InMemoryExporter()
        broker = Broker(data_dir=str(tmp_path), exporters=[("mem", mem)])
        self._run_traffic(broker)
        assert broker.partitions[0].engine.exporter_positions["mem"] >= 0
        broker.close()

        restarted = Broker(data_dir=str(tmp_path))  # no exporters at all
        restarted.run_until_idle()
        engine = restarted.partitions[0].engine
        assert engine.exporter_positions == {}
        restarted.close()

    def test_crash_resume_without_snapshot_reads_tail_acks(self, tmp_path):
        """No snapshot at all (crash before the first checkpoint): the
        director folds committed tail acks in and still resumes exactly."""
        mem = InMemoryExporter()
        broker = Broker(data_dir=str(tmp_path), exporters=[("mem", mem)])
        self._run_traffic(broker)
        broker.close()
        mem2 = InMemoryExporter()
        restarted = Broker(data_dir=str(tmp_path), exporters=[("mem", mem2)])
        restarted.run_until_idle()
        assert mem2.positions() == []
        restarted.close()


# ---------------------------------------------------------------------------
# JSONL exporter
# ---------------------------------------------------------------------------


class TestJsonlExporter:
    def _cfg(self, tmp_path, **extra):
        return ExporterCfg(
            id="audit", type="jsonl",
            args={"path": str(tmp_path / "audit"), **extra},
        )

    def test_audit_file_replays_to_the_log_sequence(self, tmp_path):
        broker = Broker(
            data_dir=str(tmp_path / "data"),
            exporters=[self._cfg(tmp_path)],
        )
        TestBrokerIntegration()._run_traffic(broker)
        log = broker.partitions[0].log
        expected = [
            (r.position, int(r.metadata.intent))
            for r in log.reader(0)
            if int(r.metadata.value_type) != int(ValueType.EXPORTER)
        ]
        broker.close()
        docs = read_audit_docs(str(tmp_path / "audit"))
        assert [d["position"] for d in docs] == [p for p, _ in expected]
        assert all("valueType" in d and "intent" in d for d in docs)

    def test_rotation_by_size(self, tmp_path):
        broker = Broker(
            data_dir=str(tmp_path / "data"),
            exporters=[self._cfg(tmp_path, rotate_bytes=2048)],
        )
        TestBrokerIntegration()._run_traffic(broker, n=10)
        broker.close()
        files = os.listdir(str(tmp_path / "audit"))
        assert len(files) > 1, "no rotation happened"
        docs = read_audit_docs(str(tmp_path / "audit"))
        positions = [d["position"] for d in docs]
        assert positions == sorted(positions)

    def test_torn_tail_line_is_truncated_and_redelivery_fills_the_gap(self, tmp_path):
        """Kernel-crash model: the last audit line is torn mid-write. A
        fresh exporter instance truncates it on open and the director's
        at-least-once re-delivery (export resumes at the last acked
        position, which trails the file tail) restores a gap-free,
        duplicate-free file."""
        from zeebe_tpu.exporter.base import ExporterContext

        audit = str(tmp_path / "audit")
        records = [job_record(i) for i in range(6)]
        for i, r in enumerate(records):
            r.position = i
            r.timestamp = 0

        def fresh():
            exporter = JsonlExporter()
            exporter.configure(ExporterContext("audit", {"path": audit}))
            exporter.open(None)
            return exporter

        first = fresh()
        first.export_batch(records)
        first.close()
        files = sorted(os.listdir(audit))
        path = os.path.join(audit, files[-1])
        # tear mid-line (crash mid-write of the final record)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 9)
        assert _recover_file_tail(path) == 4
        with open(path, "rb") as f:
            assert f.read().endswith(b"\n"), "torn line not truncated"
        # restart: re-delivery overlaps the surviving tail (positions 3..5)
        second = fresh()
        second.export_batch(records[3:])
        second.close()
        docs = read_audit_docs(audit)
        assert [d["position"] for d in docs] == [0, 1, 2, 3, 4, 5]

    def test_crash_after_rotation_recovers_tail_from_older_files(self, tmp_path):
        """A crash between rotation and the new file's first flush leaves
        the newest file EMPTY; open() must walk back to the older files
        for the dedup tail or re-delivery duplicates their records."""
        from zeebe_tpu.exporter.base import ExporterContext

        audit = str(tmp_path / "audit")
        records = [job_record(i) for i in range(4)]
        for i, r in enumerate(records):
            r.position = i
            r.timestamp = 0

        def fresh():
            exporter = JsonlExporter()
            exporter.configure(ExporterContext("audit", {"path": audit}))
            exporter.open(None)
            return exporter

        first = fresh()
        first.export_batch(records)
        first.close()
        # crash model: rotation created the next file but nothing reached it
        open(os.path.join(audit, "audit-p0-000000000004.jsonl"), "w").close()
        second = fresh()
        assert second._last_position == 3, "tail not recovered from older file"
        second.export_batch(records[2:])  # at-least-once re-delivery
        second.close()
        docs = read_audit_docs(audit)
        assert [d["position"] for d in docs] == [0, 1, 2, 3]

    def test_wiped_audit_directory_under_an_ack_reports_a_hole(self, tmp_path):
        """The ENTIRE audit directory lost (disk replaced, volume not
        mounted) while the acked position survives in replicated engine
        state: open() must report the hole exactly like a lost tail, not
        silently resume above the missing history."""
        import shutil

        from zeebe_tpu.exporter.base import ExporterContext, ExporterController

        audit = str(tmp_path / "audit")
        records = [job_record(i) for i in range(3)]
        for i, r in enumerate(records):
            r.position = i
            r.timestamp = 0
        first = JsonlExporter()
        first.configure(ExporterContext("audit", {"path": audit}))
        first.open(None)
        first.export_batch(records)
        first.close()

        shutil.rmtree(audit)
        holes = event_count("exporter_audit_holes")
        second = JsonlExporter()
        second.configure(ExporterContext("audit", {"path": audit}))
        second.open(ExporterController(
            lambda _p: None, lambda _d, _f: None, acked_position=2
        ))
        second.close()
        assert event_count("exporter_audit_holes") == holes + 1

    def test_recover_tail_preserves_lines_after_midfile_bitrot(self, tmp_path):
        """A corrupt line FOLLOWED by more content is bitrot, not a torn
        tail: recovery must preserve the intact lines after it as
        forensic evidence (replay raises on the corruption instead of
        silently losing it to truncation), and the dedup tail still
        comes from the valid lines beyond the corruption."""
        path = str(tmp_path / "audit-p0-000000000000.jsonl")
        content = '{"position": 1}\nGARBAGE\n{"position": 3}\n'
        with open(path, "w") as f:
            f.write(content)
        bitrot = event_count("exporter_audit_bitrot")
        assert _recover_file_tail(path) == 3
        with open(path) as f:
            assert f.read() == content, "bitrot evidence truncated"
        assert event_count("exporter_audit_bitrot") == bitrot + 1
        with pytest.raises(ValueError):
            read_audit_docs(str(tmp_path))
        # a trailing torn fragment after the bitrot is still cut — but
        # never the corruption or the valid lines around it
        with open(path, "a") as f:
            f.write('{"posi')
        assert _recover_file_tail(path) == 3
        with open(path) as f:
            assert f.read() == content

    def test_recover_tail_cuts_complete_but_non_dict_lines(self, tmp_path):
        """Bitrot can leave a COMPLETE line whose json is not a dict
        (`42\\n`): recovery must truncate it like any corrupt tail, not
        crash open() with a TypeError and brick the exporter."""
        path = str(tmp_path / "audit-p0-000000000000.jsonl")
        with open(path, "w") as f:
            f.write('{"position": 3}\n42\n')
        assert _recover_file_tail(path) == 3
        with open(path) as f:
            assert f.read() == '{"position": 3}\n'
        # same gap for a dict whose position is null
        with open(path, "a") as f:
            f.write('{"position": null}\n')
        assert _recover_file_tail(path) == 3

    def test_recover_tail_scans_backwards_in_chunks(self, tmp_path, monkeypatch):
        """A near-rotation-size audit file must not be slurped + parsed
        whole on every leadership install: the backwards scan reads only
        the tail window (widened until a valid line is found)."""
        import zeebe_tpu.exporter.jsonl as jsonl_mod

        monkeypatch.setattr(jsonl_mod, "_TAIL_CHUNK", 64)
        path = str(tmp_path / "audit-p0-000000000000.jsonl")
        with open(path, "w") as f:
            for i in range(100):
                f.write(json.dumps({"position": i, "pad": "x" * 40}) + "\n")
            f.write('{"position": 100, "torn...')  # crash mid-write
        assert _recover_file_tail(path) == 99
        with open(path, "rb") as f:
            data = f.read()
        assert data.endswith(b"\n") and b"torn" not in data
        # torn tail LONGER than the first window: widening still finds it
        with open(path, "a") as f:
            f.write('{"position": 100, ' + "y" * 500)
        assert _recover_file_tail(path) == 99

    def test_mid_file_corruption_raises_instead_of_silent_hole(self, tmp_path):
        """A corrupt line in a NON-newest file is bitrot, not a torn tail:
        replay must raise, not return a sequence missing records."""
        from zeebe_tpu.exporter.base import ExporterContext

        audit = str(tmp_path / "audit")
        records = [job_record(i) for i in range(3)]
        for i, r in enumerate(records):
            r.position = i
            r.timestamp = 0
        exporter = JsonlExporter()
        exporter.configure(ExporterContext("audit", {"path": audit}))
        exporter.open(None)
        exporter.export_batch(records)
        exporter.close()
        files = sorted(os.listdir(audit))
        older = os.path.join(audit, files[0])
        with open(older, "r+b") as f:
            f.seek(2)
            f.write(b"\x00\x00")  # bitrot mid-line (valid utf-8, broken json)
        open(os.path.join(audit, "audit-p0-000000000009.jsonl"), "w").close()
        with pytest.raises(ValueError, match="corrupt audit line"):
            read_audit_docs(audit)

    def test_missing_path_arg_fails_loudly(self, tmp_path):
        spec = ExporterCfg(id="audit", type="jsonl", args={})
        exporter_id, exporter = build_exporter(spec)
        with pytest.raises(ValueError, match="path"):
            from zeebe_tpu.exporter.base import ExporterContext

            exporter.configure(ExporterContext(exporter_id, {}))


# ---------------------------------------------------------------------------
# metrics exporter
# ---------------------------------------------------------------------------


class TestMetricsExporter:
    def test_per_value_type_counters_and_latency_histograms(self, tmp_path):
        registry = MetricsRegistry()
        clock = ControlledClock(start_ms=1_000_000)
        broker = Broker(
            data_dir=str(tmp_path), clock=clock,
            exporters=[("metrics", MetricsExporter(registry=registry))],
        )
        TestBrokerIntegration()._run_traffic(broker)
        text = registry.dump(now_ms=0)
        assert 'exported_records_total{' in text
        assert 'value_type="JOB"' in text
        assert 'value_type="WORKFLOW_INSTANCE"' in text
        assert 'intent="CREATED"' in text
        assert "export_latency_ms_bucket" in text
        assert "export_latency_ms_count" in text
        broker.close()

    def test_histogram_rendering_is_cumulative(self):
        registry = MetricsRegistry()
        hist = registry.histogram("h_test", buckets=(1, 10, 100))
        for v in (0.5, 5, 50, 500):
            hist.observe(v)
        text = registry.dump(now_ms=0)
        assert 'h_test_bucket{le="1"} 1' in text
        assert 'h_test_bucket{le="10"} 2' in text
        assert 'h_test_bucket{le="100"} 3' in text
        assert 'h_test_bucket{le="+Inf"} 4' in text
        assert "h_test_count 4" in text


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------


class TestExporterConfig:
    def test_exporters_section_parses(self):
        cfg = load_config(toml_text="""
[[exporters]]
id = "audit"
type = "jsonl"
args = { path = "/tmp/audit", rotate_bytes = 1024 }

[[exporters]]
id = "metrics"
type = "metrics"
""", env={})
        assert [e.id for e in cfg.exporters] == ["audit", "metrics"]
        assert cfg.exporters[0].args == {"path": "/tmp/audit", "rotate_bytes": 1024}

    def test_exporter_entry_requires_id_and_type(self):
        with pytest.raises(ValueError, match="id"):
            load_config(toml_text="""
[[exporters]]
type = "jsonl"
""", env={})

    def test_build_exporter_rejects_unknown_type(self):
        with pytest.raises(ValueError, match="unknown exporter type"):
            build_exporter(ExporterCfg(id="x", type="nope"))

    def test_build_exporter_dotted_path(self):
        _, exporter = build_exporter(
            ExporterCfg(id="x", type="zeebe_tpu.exporter.memory:InMemoryExporter")
        )
        assert isinstance(exporter, InMemoryExporter)

    def test_duplicate_exporter_ids_rejected_everywhere(self, tmp_path):
        """Two exporters on one id share one replicated position entry —
        the faster one's ack masks the slower one's gap after restart, so
        every boot path must refuse the config."""
        from zeebe_tpu.runtime.cluster_broker import ClusterBroker
        from zeebe_tpu.runtime.config import BrokerCfg

        with pytest.raises(ValueError, match="duplicate exporter id"):
            load_config(toml_text="""
[[exporters]]
id = "audit"
type = "jsonl"
args = { path = "/tmp/a" }

[[exporters]]
id = "audit"
type = "memory"
""", env={})
        with pytest.raises(ValueError, match="duplicate exporter id"):
            Broker(
                data_dir=str(tmp_path / "b"),
                exporters=[("mem", InMemoryExporter()),
                           ("mem", InMemoryExporter())],
            )
        cfg = BrokerCfg()
        cfg.exporters = [
            ExporterCfg(id="mem", type="memory"),
            ExporterCfg(id="mem", type="memory"),
        ]
        with pytest.raises(ValueError, match="duplicate exporter id"):
            ClusterBroker(cfg, str(tmp_path / "c"))

    def test_cluster_broker_rejects_bad_exporter_at_construction(self, tmp_path):
        """Cluster path must fail boot loudly like the in-process Broker —
        not surface the error inside the leadership-install actor job."""
        from zeebe_tpu.runtime.cluster_broker import ClusterBroker
        from zeebe_tpu.runtime.config import BrokerCfg

        cfg = BrokerCfg()
        cfg.exporters = [ExporterCfg(id="x", type="no-such-type")]
        with pytest.raises(ValueError, match="unknown exporter type"):
            ClusterBroker(cfg, str(tmp_path))


# ---------------------------------------------------------------------------
# protocol round-trip
# ---------------------------------------------------------------------------


class TestExporterRecords:
    def test_ack_record_codec_roundtrip(self):
        from zeebe_tpu.protocol import codec

        record = Record(
            position=5,
            metadata=RecordMetadata(
                record_type=RecordType.COMMAND,
                value_type=ValueType.EXPORTER,
                intent=int(ExporterIntent.ACKNOWLEDGE),
            ),
            value=ExporterPositionRecord(exporter_id="audit", position=41),
        )
        decoded, _ = codec.decode_record(codec.encode_record(record))
        assert decoded.value.exporter_id == "audit"
        assert decoded.value.position == 41
        assert int(decoded.metadata.value_type) == int(ValueType.EXPORTER)

    def test_engine_folds_acks_and_registration_pins_floor(self, tmp_path):
        broker = Broker(data_dir=str(tmp_path))
        engine = broker.partitions[0].engine
        from zeebe_tpu.protocol.records import ExporterPositionRecord as EPR

        def ack(exporter_id, pos):
            return Record(
                metadata=RecordMetadata(
                    record_type=RecordType.COMMAND,
                    value_type=ValueType.EXPORTER,
                    intent=int(ExporterIntent.ACKNOWLEDGE),
                ),
                value=EPR(exporter_id=exporter_id, position=pos),
            )

        broker.partitions[0].log.append([ack("a", -1)])
        broker.run_until_idle()
        assert engine.exporter_positions == {"a": -1}
        assert engine.compaction_floor() == 0  # registration pins everything
        broker.partitions[0].log.append([ack("a", 50), ack("a", 20)])
        broker.run_until_idle()
        assert engine.exporter_positions == {"a": 50}, "ack must be monotonic"
        broker.close()

    def test_exporter_positions_ride_state_serialization(self):
        from zeebe_tpu.engine.interpreter import PartitionEngine, WorkflowRepository
        from zeebe_tpu.log import stateser

        engine = PartitionEngine(
            partition_id=0, num_partitions=1,
            repository=WorkflowRepository(), clock=lambda: 0,
        )
        engine.exporter_positions = {"audit": 17, "mem": -1}
        restored = stateser.decode_state(
            stateser.encode_state(engine.snapshot_state())
        )
        assert restored["exporter_positions"] == {"audit": 17, "mem": -1}


# ---------------------------------------------------------------------------
# columnar egress (PR 7): batched JSONL writes, column-only sinks, and
# wave-vs-1 byte identity of the audit trail
# ---------------------------------------------------------------------------


class _WriteCountingFile:
    """Wraps a file object counting syscall-level ``write`` calls."""

    def __init__(self, inner):
        self._inner = inner
        self.writes = 0

    def write(self, data):
        self.writes += 1
        return self._inner.write(data)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestColumnarEgress:
    def test_jsonl_batch_is_one_write_and_flush_per_batch(self, tmp_path):
        """Satellite: the whole batch serializes into one buffer and
        issues ONE write (+flush) per batch instead of one per record."""
        counters = []

        class CountingJsonl(JsonlExporter):
            def _open_audit(self, path):
                f = _WriteCountingFile(super()._open_audit(path))
                counters.append(f)
                return f

        log = make_log(tmp_path, segment_size=1 << 20)
        log.append([job_record(i) for i in range(100)])
        jsonl = CountingJsonl()
        jsonl._cfg_args = {"path": str(tmp_path / "audit")}
        director = make_director(log, [("audit", jsonl)])
        director.open({})
        while director.pump():
            pass
        director.close()
        docs = read_audit_docs(str(tmp_path / "audit"))
        assert [d["position"] for d in docs] == list(range(100))
        # 100 records, ONE audit file, ONE batch → exactly one write
        assert len(counters) == 1
        assert counters[0].writes == 1

    def test_jsonl_batch_write_splits_at_rotation(self, tmp_path):
        counters = []

        class CountingJsonl(JsonlExporter):
            def _open_audit(self, path):
                f = _WriteCountingFile(super()._open_audit(path))
                counters.append(f)
                return f

        log = make_log(tmp_path, segment_size=1 << 20)
        log.append([job_record(i) for i in range(50)])
        jsonl = CountingJsonl()
        # tiny rotation: ~2 lines per file → many files, still one write
        # per (batch, file) pair and replay stays exact
        jsonl._cfg_args = {"path": str(tmp_path / "audit"), "rotate_bytes": 400}
        director = make_director(log, [("audit", jsonl)])
        director.open({})
        while director.pump():
            pass
        director.close()
        docs = read_audit_docs(str(tmp_path / "audit"))
        assert [d["position"] for d in docs] == list(range(50))
        assert len(counters) > 5  # rotation actually split files
        assert all(f.writes <= 2 for f in counters)

    def test_metrics_exporter_consumes_columns_never_rows(self, tmp_path):
        """The metrics sink reads only metadata columns — a columnar view
        batch must export with ZERO lazy row materializations."""
        from zeebe_tpu.protocol.columnar import (
            ColumnarBatch,
            RecordsView,
            rows_materialized_total,
        )

        records = [job_record(i) for i in range(20)]
        for i, r in enumerate(records):
            r.position = i
            r.timestamp = 100
        batch = ColumnarBatch(
            len(records),
            {
                "position": [r.position for r in records],
                "timestamp": [100] * len(records),
                "record_type": [int(r.metadata.record_type) for r in records],
                "value_type": [int(r.metadata.value_type) for r in records],
                "intent": [int(r.metadata.intent) for r in records],
            },
            materializer=lambda i: records[i],
        )
        view = RecordsView(batch.log_entries())
        registry = MetricsRegistry()
        metrics = MetricsExporter(registry=registry)
        metrics.clock = lambda: 150
        before = rows_materialized_total()
        metrics.export_batch(view)
        assert rows_materialized_total() == before
        text = registry.dump()
        assert "exported_records_total" in text

    def test_audit_bytes_identical_wave_vs_record_at_a_time(self, tmp_path):
        """The exporter plane's columnar dispatch must leave the audit
        trail BYTE-identical to record-at-a-time processing (wave size 1),
        for the whole broker pipeline."""

        def run(data_dir, wave_size):
            clock = ControlledClock(start_ms=1_000_000)
            audit_dir = os.path.join(data_dir, "audit")
            broker = Broker(
                num_partitions=1, data_dir=data_dir, clock=clock,
                exporters=[ExporterCfg(
                    id="audit", type="jsonl", args={"path": audit_dir},
                )],
            )
            broker.wave_size = wave_size
            try:
                client = ZeebeClient(broker)
                client.deploy_model(simple_model())
                JobWorker(broker, "svc", lambda ctx: {"ok": True})
                for i in range(12):
                    client.create_instance("exp-proc", {"i": i})
                clock.advance(1_000)
                broker.tick()
                broker.run_until_idle()
            finally:
                broker.close()
            names = sorted(os.listdir(audit_dir))
            return names, [
                open(os.path.join(audit_dir, n), "rb").read() for n in names
            ]

        names_wave, bytes_wave = run(str(tmp_path / "wave"), 256)
        names_one, bytes_one = run(str(tmp_path / "one"), 1)
        assert names_wave == names_one
        assert bytes_wave == bytes_one
        assert sum(len(b) for b in bytes_wave) > 1000
