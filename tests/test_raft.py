"""Raft cluster tests: elections, replication, commit, failover, recovery.

Reference parity: ``raft/src/test`` — RaftRule/RaftClusterRule run 1/2/3/5
real raft actors over loopback transport in one process
(``RaftFiveNodesTest``, leader change tests, log consistency; SURVEY.md §4).
"""

import os
import time

import pytest

from zeebe_tpu.cluster import Raft, RaftConfig, RaftState
from zeebe_tpu.log import LogStream, SegmentedLogStorage
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import JobIntent
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import JobRecord, Record
from zeebe_tpu.runtime.actors import ActorScheduler

FAST = RaftConfig(
    heartbeat_interval_ms=30,
    election_timeout_ms=150,
    election_jitter_ms=150,
)


def wait_until(predicate, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def append_with_retry(cluster, records, timeout=15):
    """Append via the current leader, retrying on leadership changes (what
    the reference client's topology-aware retry does)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leader = cluster.leader()
        if leader is None:
            time.sleep(0.05)
            continue
        try:
            return leader, leader.append(records).join(5)
        except RuntimeError:
            time.sleep(0.05)
    raise AssertionError("could not append within timeout")


def job_record(i):
    return Record(
        metadata=RecordMetadata(
            record_type=RecordType.COMMAND,
            value_type=ValueType.JOB,
            intent=int(JobIntent.CREATE),
        ),
        value=JobRecord(type=f"work-{i}", retries=3),
    )


class Cluster:
    def __init__(self, scheduler, tmp_path, n, config=FAST):
        self.scheduler = scheduler
        self.tmp_path = tmp_path
        self.config = config
        self.nodes = {}
        self.logs = {}
        for i in range(n):
            self._make_node(f"n{i}")
        members = {nid: node.address for nid, node in self.nodes.items()}
        for node in self.nodes.values():
            node.bootstrap(members)

    def _make_node(self, nid, port=0):
        storage = SegmentedLogStorage(os.path.join(str(self.tmp_path), f"log-{nid}-{time.monotonic_ns()}"))
        # raft mode: commit position is leader-driven, never recovered
        log = LogStream(storage, partition_id=0, recover_commit=False)
        raft = Raft(
            nid,
            log,
            self.scheduler,
            config=self.config,
            port=port,
            storage_path=os.path.join(str(self.tmp_path), f"raft-{nid}.meta"),
        )
        self.nodes[nid] = raft
        self.logs[nid] = log
        return raft

    def leader(self):
        leaders = [n for n in self.nodes.values() if n.state == RaftState.LEADER]
        return leaders[0] if len(leaders) == 1 else None

    def await_leader(self, timeout=15):
        assert wait_until(lambda: self.leader() is not None, timeout), {
            nid: n.state for nid, n in self.nodes.items()
        }
        return self.leader()

    def close(self):
        for node in self.nodes.values():
            node.close()


@pytest.fixture
def scheduler():
    s = ActorScheduler(cpu_threads=2, io_threads=2).start()
    yield s
    s.stop()


class TestElection:
    def test_single_node_becomes_leader(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 1)
        try:
            leader = cluster.await_leader()
            assert leader.term >= 1
            # initial event committed
            assert wait_until(lambda: cluster.logs[leader.node_id].commit_position >= 0)
        finally:
            cluster.close()

    def test_three_nodes_elect_exactly_one_leader(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            cluster.await_leader()
            time.sleep(0.5)  # stability: still exactly one leader
            assert len(
                [n for n in cluster.nodes.values() if n.state == RaftState.LEADER]
            ) == 1
        finally:
            cluster.close()

    def test_leader_failover(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            old = cluster.await_leader()
            old_id, old_term = old.node_id, old.term
            old.close()  # hard kill
            assert wait_until(
                lambda: any(
                    n.state == RaftState.LEADER and n.node_id != old_id
                    for n in cluster.nodes.values()
                ),
                timeout=15,
            ), {nid: n.state for nid, n in cluster.nodes.items()}
            new = [
                n
                for n in cluster.nodes.values()
                if n.state == RaftState.LEADER and n.node_id != old_id
            ][0]
            assert new.term > old_term
        finally:
            cluster.close()


class TestReplication:
    def test_appends_replicate_and_commit(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            cluster.await_leader()
            leader, last = append_with_retry(cluster, [job_record(i) for i in range(10)])
            assert wait_until(
                lambda: all(
                    log.commit_position >= last for log in cluster.logs.values()
                ),
                timeout=15,
            ), {nid: log.commit_position for nid, log in cluster.logs.items()}
            # every follower's log matches the leader's byte-for-byte content
            leader_log = cluster.logs[leader.node_id]
            for nid, log in cluster.logs.items():
                for pos in range(last + 1):
                    a, b = leader_log._records[pos], log._records[pos]
                    assert (a.position, a.raft_term, a.metadata.intent) == (
                        b.position,
                        b.raft_term,
                        b.metadata.intent,
                    ), (nid, pos)
        finally:
            cluster.close()

    def test_append_on_follower_rejected(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            # leadership can move between picking a follower and appending
            # (elections flap under load); retry until an append hit a node
            # that was still follower at that instant
            for _ in range(10):
                leader = cluster.await_leader()
                follower = next(
                    n for n in cluster.nodes.values() if n.node_id != leader.node_id
                )
                try:
                    follower.append([job_record(0)]).join(5)
                except RuntimeError as e:
                    assert "not leader" in str(e)
                    break
            else:
                pytest.fail("append never hit a follower")
        finally:
            cluster.close()

    def test_commit_requires_quorum(self, scheduler, tmp_path):
        """With both followers dead, the leader cannot advance the commit
        position (no quorum)."""
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            leader = cluster.await_leader()
            # wait for a stable committed state before killing followers
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= 0
            )
            for node in list(cluster.nodes.values()):
                if node.node_id != leader.node_id:
                    node.close()
            committed_before = cluster.logs[leader.node_id].commit_position
            # a dying follower's last election poll (term+1) may legally
            # depose the leader before the append lands — both outcomes
            # prove the safety property: nothing can COMMIT without quorum
            try:
                leader.append([job_record(0)]).join(5)
            except RuntimeError as e:
                assert "not leader" in str(e)
            time.sleep(0.5)
            assert cluster.logs[leader.node_id].commit_position == committed_before
        finally:
            cluster.close()

    def test_follower_catches_up_after_restart_gap(self, scheduler, tmp_path):
        """A follower that missed appends receives the backlog (nextIndex
        walk-back; reference MemberReplicateLogController catch-up)."""
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            leader = cluster.await_leader()
            slow_id = next(
                nid for nid in cluster.nodes if nid != leader.node_id
            )
            old_addr = cluster.nodes[slow_id].address
            cluster.nodes[slow_id].close()
            del cluster.nodes[slow_id]  # leader() must not see the corpse
            leader, last = append_with_retry(cluster, [job_record(i) for i in range(20)])
            # quorum of 2 still commits
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= last,
                timeout=15,
            )
            # resurrect the slow follower on the SAME address with its log
            log = cluster.logs[slow_id]
            raft = Raft(
                slow_id,
                log,
                scheduler,
                config=FAST,
                port=old_addr.port,
                storage_path=os.path.join(str(tmp_path), f"raft-{slow_id}.meta"),
            )
            members = {nid: n.address for nid, n in cluster.nodes.items() if nid != slow_id}
            members[slow_id] = raft.address
            raft.bootstrap(members)
            cluster.nodes[slow_id] = raft
            assert wait_until(
                lambda: log.commit_position >= last, timeout=15
            ), log.commit_position
        finally:
            cluster.close()


class TestDurabilityInvariants:
    def test_follower_restart_does_not_resurrect_commit(self, tmp_path):
        """A raft-mode log recovered from disk must NOT mark its tail
        committed — the leader decides (regression: _recover exposed a
        restarted follower's unreplicated tail as committed)."""
        path = os.path.join(str(tmp_path), "raftlog")
        storage = SegmentedLogStorage(path)
        log = LogStream(storage, recover_commit=False)
        log.append([job_record(0), job_record(1)], commit=False)
        log.flush()
        storage.close()

        storage = SegmentedLogStorage(path)
        recovered = LogStream(storage, recover_commit=False)
        assert recovered.next_position == 2
        assert recovered.commit_position == -1
        storage.close()

    def test_truncating_committed_records_is_refused_in_raft_mode(self, tmp_path):
        storage = SegmentedLogStorage(os.path.join(str(tmp_path), "raftlog"))
        log = LogStream(storage, recover_commit=False)
        log.append([job_record(0), job_record(1)], commit=False)
        log.set_commit_position(0)
        with pytest.raises(RuntimeError, match="commit is final"):
            log.truncate(0)
        log.truncate(1)  # uncommitted tail is fine
        assert log.next_position == 1
        storage.close()


class TestPersistence:
    def test_term_and_vote_survive_restart(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 1)
        try:
            leader = cluster.await_leader()
            term = leader.term
            assert term >= 1
            leader.close()
            from zeebe_tpu.cluster.raft import RaftPersistentStorage

            storage = RaftPersistentStorage(
                os.path.join(str(tmp_path), "raft-n0.meta")
            )
            assert storage.term == term
            assert storage.voted_for == "n0"
            assert "n0" in storage.members
        finally:
            cluster.close()
