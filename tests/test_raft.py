"""Raft cluster tests: elections, replication, commit, failover, recovery.

Reference parity: ``raft/src/test`` — RaftRule/RaftClusterRule run 1/2/3/5
real raft actors over loopback transport in one process
(``RaftFiveNodesTest``, leader change tests, log consistency; SURVEY.md §4).
"""

import os
import time

import pytest

from zeebe_tpu.cluster import Raft, RaftConfig, RaftState
from zeebe_tpu.log import LogStream, SegmentedLogStorage
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import JobIntent
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import JobRecord, Record
from zeebe_tpu.runtime.actors import ActorScheduler

FAST = RaftConfig(
    heartbeat_interval_ms=30,
    election_timeout_ms=150,
    election_jitter_ms=150,
)


def wait_until(predicate, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def append_with_retry(cluster, records, timeout=15):
    """Append via the current leader, retrying on leadership changes (what
    the reference client's topology-aware retry does). ``append`` acks at
    COMMIT: a deposed leader fails the future (records truncated by the
    new leader) and the retry lands on the real one; a slow quorum round
    under load surfaces as a join timeout and retries the same way."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leader = cluster.leader()
        if leader is None:
            time.sleep(0.05)
            continue
        try:
            return leader, leader.append(records).join(5)
        except (RuntimeError, TimeoutError):
            time.sleep(0.05)
    raise AssertionError("could not append within timeout")


def job_record(i):
    return Record(
        metadata=RecordMetadata(
            record_type=RecordType.COMMAND,
            value_type=ValueType.JOB,
            intent=int(JobIntent.CREATE),
        ),
        value=JobRecord(type=f"work-{i}", retries=3),
    )


class Cluster:
    def __init__(self, scheduler, tmp_path, n, config=FAST, segment_size=None):
        self.scheduler = scheduler
        self.tmp_path = tmp_path
        self.config = config
        self.segment_size = segment_size
        self.nodes = {}
        self.logs = {}
        for i in range(n):
            self._make_node(f"n{i}")
        members = {nid: node.address for nid, node in self.nodes.items()}
        for node in self.nodes.values():
            node.bootstrap(members)

    def _make_node(self, nid, port=0):
        kw = {"segment_size": self.segment_size} if self.segment_size else {}
        storage = SegmentedLogStorage(
            os.path.join(str(self.tmp_path), f"log-{nid}-{time.monotonic_ns()}"),
            **kw,
        )
        # raft mode: commit position is leader-driven, never recovered
        log = LogStream(storage, partition_id=0, recover_commit=False)
        raft = Raft(
            nid,
            log,
            self.scheduler,
            config=self.config,
            port=port,
            storage_path=os.path.join(str(self.tmp_path), f"raft-{nid}.meta"),
        )
        self.nodes[nid] = raft
        self.logs[nid] = log
        return raft

    def leader(self):
        leaders = [n for n in self.nodes.values() if n.state == RaftState.LEADER]
        return leaders[0] if len(leaders) == 1 else None

    def await_leader(self, timeout=15):
        assert wait_until(lambda: self.leader() is not None, timeout), {
            nid: n.state for nid, n in self.nodes.items()
        }
        return self.leader()

    def close(self):
        for node in self.nodes.values():
            node.close()


@pytest.fixture
def scheduler():
    s = ActorScheduler(cpu_threads=2, io_threads=2).start()
    yield s
    s.stop()


class TestElection:
    def test_single_node_becomes_leader(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 1)
        try:
            leader = cluster.await_leader()
            assert leader.term >= 1
            # initial event committed
            assert wait_until(lambda: cluster.logs[leader.node_id].commit_position >= 0)
        finally:
            cluster.close()

    def test_three_nodes_elect_exactly_one_leader(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            cluster.await_leader()
            time.sleep(0.5)  # stability: still exactly one leader
            assert len(
                [n for n in cluster.nodes.values() if n.state == RaftState.LEADER]
            ) == 1
        finally:
            cluster.close()

    def test_leader_failover(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            old = cluster.await_leader()
            old_id, old_term = old.node_id, old.term
            old.close()  # hard kill
            assert wait_until(
                lambda: any(
                    n.state == RaftState.LEADER and n.node_id != old_id
                    for n in cluster.nodes.values()
                ),
                timeout=15,
            ), {nid: n.state for nid, n in cluster.nodes.items()}
            new = [
                n
                for n in cluster.nodes.values()
                if n.state == RaftState.LEADER and n.node_id != old_id
            ][0]
            assert new.term > old_term
        finally:
            cluster.close()


class TestReplication:
    def test_appends_replicate_and_commit(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            cluster.await_leader()
            leader, last = append_with_retry(cluster, [job_record(i) for i in range(10)])
            assert wait_until(
                lambda: all(
                    log.commit_position >= last for log in cluster.logs.values()
                ),
                timeout=15,
            ), {nid: log.commit_position for nid, log in cluster.logs.items()}
            # every follower's log matches the leader's byte-for-byte content
            leader_log = cluster.logs[leader.node_id]
            for nid, log in cluster.logs.items():
                for pos in range(last + 1):
                    a, b = leader_log._records[pos], log._records[pos]
                    assert (a.position, a.raft_term, a.metadata.intent) == (
                        b.position,
                        b.raft_term,
                        b.metadata.intent,
                    ), (nid, pos)
        finally:
            cluster.close()

    def test_commit_stall_watchdog_rearms_on_progress(self):
        """Commit progress ends a stall episode even while newer pendings
        remain — under sustained load _pending_commits never drains to
        empty, and a once-armed watchdog would otherwise never warn or
        count again (the metrics doc tells operators to alert on
        sustained zb_raft_commit_stalls growth)."""
        import threading
        from types import SimpleNamespace

        from zeebe_tpu.cluster.raft import Raft
        from zeebe_tpu.runtime.actors import ActorFuture

        def stub(commit, pendings):
            return SimpleNamespace(
                _append_lock=threading.Lock(),
                _pending_commits=pendings,
                _commit_stall_warned=True,
                _traced_bound=set(),
                log=SimpleNamespace(commit_position=commit),
            )

        f1, f2 = ActorFuture(), ActorFuture()
        s = stub(1, [(0, 1, 0, f1), (2, 3, 0, f2)])
        Raft._resolve_pending_commits(s)
        assert f1.is_done() and not f2.is_done()
        assert s._pending_commits == [(2, 3, 0, f2)]
        assert s._commit_stall_warned is False  # progress re-armed it

        s = stub(-1, [(0, 1, 0, ActorFuture())])
        Raft._resolve_pending_commits(s)
        assert s._commit_stall_warned is True  # wedged: still one episode

    def test_follower_truncate_spares_other_brokers_spans(self):
        """The tracer is process-global: a follower truncating its own
        divergent suffix must not finish spans the in-process LEADER
        bound at the same positions — only the raft that bound a span
        (tracked in _traced_bound) may truncate-finish it."""
        import threading
        from types import SimpleNamespace

        from zeebe_tpu import tracing
        from zeebe_tpu.cluster.raft import Raft

        tracer = tracing.install(tracing.RecordTracer(sample_rate=1.0))
        try:
            span = tracer.maybe_sample(0)
            tracer.bind_request(span, 1, 0)
            assert tracer.bind_append(1, 0, 7) is True

            def stub(bound):
                s = SimpleNamespace(
                    _append_lock=threading.Lock(),
                    _pending_commits=[],
                    _commit_stall_warned=False,
                    _traced_bound=bound,
                    log=SimpleNamespace(partition_id=0),
                    node_id="nX",
                    persistent=SimpleNamespace(term=2),
                )
                return s

            Raft._fail_pending_from(stub(set()), 5, "follower truncate")
            assert not span.finished  # the leader's span survived

            Raft._fail_pending_from(stub({7}), 5, "leader truncate")
            assert span.finished
            assert "truncated" in span.stage_names()
        finally:
            tracing.install(None)

    def test_snapshot_fast_forward_fails_pending_appends(self):
        """Snapshot catch-up resets the log without going through
        set_commit_position, so a deposed leader's acked-means-committed
        futures would never resolve — the fast-forward hook must fail
        them so callers retry on the real leader."""
        import threading
        from types import SimpleNamespace

        from zeebe_tpu.cluster.raft import Raft
        from zeebe_tpu.runtime.actors import ActorFuture

        future = ActorFuture()
        stub = SimpleNamespace(
            _append_lock=threading.Lock(),
            _pending_commits=[(5, 9, 0, future)],
            _commit_stall_warned=True,
            _traced_bound=set(),
            log=SimpleNamespace(partition_id=0),
            node_id="n0",
            persistent=SimpleNamespace(term=3),
        )
        stub._fail_pending_from = Raft._fail_pending_from.__get__(stub)
        Raft.on_snapshot_fast_forward(stub)
        assert stub._pending_commits == []
        with pytest.raises(RuntimeError, match="fast-forward"):
            future.join(1)

    def test_append_racing_close_fails_fast(self, scheduler, tmp_path):
        """An append whose drain lands after close() must fail its future
        immediately — close() sweeps _pending_commits exactly once, so a
        drain registering entries after that sweep would leave the caller
        hanging with no replication and no resolver left (regression from
        the acked-means-committed change)."""
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            leader = cluster.await_leader()
            leader.close()  # transports dead, but the actor still runs
            future = leader.append([job_record(0)])
            with pytest.raises(RuntimeError, match="raft closed|not leader"):
                future.join(5)
        finally:
            cluster.close()

    def test_deposed_leader_append_resolves_and_cluster_stays_live(
        self, scheduler, tmp_path
    ):
        """Regression for the recorded replication flake (commit stuck at
        the no-op): an append landing on a leader that was already deposed
        — but had not yet heard the new term — used to ack on local
        durability, and the new leader then truncated the records, so a
        caller retrying only on failure waited forever for a commit that
        could never come. Acked-means-committed closes the window: the
        deposed leader's future RESOLVES (exceptionally when truncated)
        as soon as the new leader makes contact, and the retry commits on
        the real leader."""
        from zeebe_tpu.testing.chaos import FaultPlane

        cluster = Cluster(scheduler, tmp_path, 3)
        plane = FaultPlane(seed=7)
        try:
            for nid, node in cluster.nodes.items():
                plane.register_endpoint(nid, node.address)
                plane.install_client(node.client, nid)
                plane.install_server(node.server, nid)
            old = cluster.await_leader()
            assert wait_until(
                lambda: all(
                    log.commit_position >= 0 for log in cluster.logs.values()
                )
            )
            plane.isolate(old.node_id)
            assert wait_until(
                lambda: any(
                    n.state == RaftState.LEADER and n.node_id != old.node_id
                    for n in cluster.nodes.values()
                ),
                timeout=15,
            ), {nid: n.state for nid, n in cluster.nodes.items()}
            # the deposed-but-unaware leader accepts the append locally;
            # the future must NOT ack it (the records cannot commit)
            future = old.append([job_record(0)])
            plane.heal()
            try:
                last = future.join(15)
                # only legitimate if the record genuinely committed
                assert wait_until(
                    lambda: cluster.logs[old.node_id].commit_position >= last
                )
            except RuntimeError as e:
                assert "not leader" in str(e)
            # liveness: a retry commits cluster-wide (this is exactly the
            # wait the flaky test timed out on)
            leader, last = append_with_retry(cluster, [job_record(1)])
            assert wait_until(
                lambda: all(
                    log.commit_position >= last
                    for log in cluster.logs.values()
                ),
                timeout=15,
            ), {nid: log.commit_position for nid, log in cluster.logs.items()}
        finally:
            cluster.close()

    def test_append_on_follower_rejected(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            # leadership can move between picking a follower and appending
            # (elections flap under load); retry until an append hit a node
            # that was still follower at that instant
            for _ in range(10):
                leader = cluster.await_leader()
                follower = next(
                    n for n in cluster.nodes.values() if n.node_id != leader.node_id
                )
                try:
                    follower.append([job_record(0)]).join(5)
                except RuntimeError as e:
                    assert "not leader" in str(e)
                    break
            else:
                pytest.fail("append never hit a follower")
        finally:
            cluster.close()

    def test_commit_requires_quorum(self, scheduler, tmp_path):
        """With both followers dead, the leader cannot advance the commit
        position (no quorum)."""
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            leader = cluster.await_leader()
            # wait for a stable committed state before killing followers
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= 0
            )
            for node in list(cluster.nodes.values()):
                if node.node_id != leader.node_id:
                    node.close()
            committed_before = cluster.logs[leader.node_id].commit_position
            # acked-means-committed: without quorum the append future can
            # never complete successfully — it either times out (no
            # commit possible) or fails "not leader" (a dying follower's
            # last election poll legally deposed the leader first). A
            # successful ack here would BE the safety violation.
            try:
                leader.append([job_record(0)]).join(5)
                pytest.fail("append acked without a quorum to commit it")
            except TimeoutError:
                pass
            except RuntimeError as e:
                assert "not leader" in str(e)
            time.sleep(0.5)
            assert cluster.logs[leader.node_id].commit_position == committed_before
        finally:
            cluster.close()

    def test_follower_catches_up_after_restart_gap(self, scheduler, tmp_path):
        """A follower that missed appends receives the backlog (nextIndex
        walk-back; reference MemberReplicateLogController catch-up)."""
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            leader = cluster.await_leader()
            slow_id = next(
                nid for nid in cluster.nodes if nid != leader.node_id
            )
            old_addr = cluster.nodes[slow_id].address
            cluster.nodes[slow_id].close()
            del cluster.nodes[slow_id]  # leader() must not see the corpse
            leader, last = append_with_retry(cluster, [job_record(i) for i in range(20)])
            # quorum of 2 still commits
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= last,
                timeout=15,
            )
            # resurrect the slow follower on the SAME address with its log
            log = cluster.logs[slow_id]
            raft = Raft(
                slow_id,
                log,
                scheduler,
                config=FAST,
                port=old_addr.port,
                storage_path=os.path.join(str(tmp_path), f"raft-{slow_id}.meta"),
            )
            members = {nid: n.address for nid, n in cluster.nodes.items() if nid != slow_id}
            members[slow_id] = raft.address
            raft.bootstrap(members)
            cluster.nodes[slow_id] = raft
            assert wait_until(
                lambda: log.commit_position >= last, timeout=15
            ), log.commit_position
        finally:
            cluster.close()


class TestDurabilityInvariants:
    def test_follower_restart_does_not_resurrect_commit(self, tmp_path):
        """A raft-mode log recovered from disk must NOT mark its tail
        committed — the leader decides (regression: _recover exposed a
        restarted follower's unreplicated tail as committed)."""
        path = os.path.join(str(tmp_path), "raftlog")
        storage = SegmentedLogStorage(path)
        log = LogStream(storage, recover_commit=False)
        log.append([job_record(0), job_record(1)], commit=False)
        log.flush()
        storage.close()

        storage = SegmentedLogStorage(path)
        recovered = LogStream(storage, recover_commit=False)
        assert recovered.next_position == 2
        assert recovered.commit_position == -1
        storage.close()

    def test_truncating_committed_records_is_refused_in_raft_mode(self, tmp_path):
        storage = SegmentedLogStorage(os.path.join(str(tmp_path), "raftlog"))
        log = LogStream(storage, recover_commit=False)
        log.append([job_record(0), job_record(1)], commit=False)
        log.set_commit_position(0)
        with pytest.raises(RuntimeError, match="commit is final"):
            log.truncate(0)
        log.truncate(1)  # uncommitted tail is fine
        assert log.next_position == 1
        storage.close()


class TestPersistence:
    def test_term_and_vote_survive_restart(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 1)
        try:
            leader = cluster.await_leader()
            term = leader.term
            assert term >= 1
            leader.close()
            from zeebe_tpu.cluster.raft import RaftPersistentStorage

            storage = RaftPersistentStorage(
                os.path.join(str(tmp_path), "raft-n0.meta")
            )
            assert storage.term == term
            assert storage.voted_for == "n0"
            assert "n0" in storage.members
        finally:
            cluster.close()


class TestMembershipChange:
    """Single-step configuration change via entries on the replicated log
    (reference ``raft/.../event/RaftConfigurationEvent.java`` +
    ``RaftJoinService``; the configuration takes effect on APPEND, raft
    dissertation §4.1)."""

    def test_add_member_live(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            leader = cluster.await_leader()
            leader, last = append_with_retry(cluster, [job_record(i) for i in range(5)])
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= last
            )
            # bring up a 4th node knowing the current members + itself
            new = cluster._make_node("n3")
            members = {nid: n.address for nid, n in cluster.nodes.items()}
            new.bootstrap(members)
            leader.add_member("n3", new.address).join(5)
            assert "n3" in leader.persistent.members
            # the new member catches up on the existing log + config entry
            assert wait_until(
                lambda: cluster.logs["n3"].commit_position >= last, timeout=15
            ), cluster.logs["n3"].next_position
            # and its replicated config entry teaches IT the membership
            assert wait_until(
                lambda: set(new.persistent.members) == set(members) | {"n3"},
                timeout=10,
            ), new.persistent.members
            # the new member counts toward commit
            leader2, last2 = append_with_retry(cluster, [job_record(99)])
            assert wait_until(
                lambda: cluster.logs["n3"].commit_position >= last2, timeout=15
            )
        finally:
            cluster.close()

    def test_remove_member_adjusts_quorum(self, scheduler, tmp_path):
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            leader = cluster.await_leader()
            gone = next(nid for nid in cluster.nodes if nid != leader.node_id)
            leader.remove_member(gone).join(5)
            assert gone not in leader.persistent.members
            cluster.nodes[gone].close()
            del cluster.nodes[gone]
            # 2-node cluster: quorum 2 still commits without the removed one
            leader2, last = append_with_retry(cluster, [job_record(1)])
            assert wait_until(
                lambda: cluster.logs[leader2.node_id].commit_position >= last,
                timeout=15,
            )
        finally:
            cluster.close()

    def test_config_survives_in_log_replication(self, scheduler, tmp_path):
        """The config entry is an ordinary replicated record: followers
        apply it from the append stream."""
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            leader = cluster.await_leader()
            new = cluster._make_node("n3")
            members = {nid: n.address for nid, n in cluster.nodes.items()}
            new.bootstrap(members)
            leader.add_member("n3", new.address).join(5)
            followers = [
                n for nid, n in cluster.nodes.items()
                if nid not in (leader.node_id, "n3")
            ]
            assert wait_until(
                lambda: all("n3" in f.persistent.members for f in followers),
                timeout=10,
            ), [f.persistent.members for f in followers]
        finally:
            cluster.close()


class TestMembershipChurnUnderFaults:
    """Membership change while the leader is partitioned away must either
    complete (forwarded to the new leader, finishing after heal) or roll
    back cleanly via ``_rollback_config`` when the deposed leader's
    uncommitted config entry is truncated."""

    def test_change_on_partitioned_leader_rolls_back_after_heal(
        self, scheduler, tmp_path
    ):
        from zeebe_tpu.testing.chaos import FaultPlane

        plane = FaultPlane(seed=7)
        cluster = Cluster(scheduler, tmp_path, 3)
        extra = None
        try:
            for nid, node in cluster.nodes.items():
                plane.register_endpoint(nid, node.address)
                plane.install_client(node.client, nid)
            leader = cluster.await_leader()
            lid = leader.node_id
            assert wait_until(lambda: cluster.logs[lid].commit_position >= 0)

            # cut the leader off completely, then have it accept an
            # add_member it can never commit (applies on append). Under
            # CI load a heartbeat hiccup can depose the just-observed
            # leader (higher-term election) right around the isolation,
            # voiding the premise — the cut-off node then neither accepts
            # nor forwards the op and the test dies on its 10s deadline.
            # So: isolate, let in-flight higher-term messages drain, and
            # only proceed if the isolated node still leads (isolated,
            # nothing can depose it anymore); else heal and re-acquire.
            isolated_leader = False
            for _ in range(5):
                plane.isolate(lid)
                time.sleep(0.3)
                if leader.state == RaftState.LEADER:
                    isolated_leader = True
                    break
                plane.heal(lid)
                leader = cluster.await_leader()
                lid = leader.node_id
            # must record that the BREAK path was taken: after a failed
            # final attempt `leader` is a freshly-healed, connected leader
            # whose state check would pass vacuously
            assert isolated_leader, "no stable leader to isolate"
            original_members = set(leader.persistent.members)
            followers = [n for n in cluster.nodes if n != lid]
            extra = cluster._make_node("n3")
            del cluster.nodes["n3"]  # keep leader() blind to the bystander
            # join margin > MEMBERSHIP_TIMEOUT_MS (10s): the op's own
            # deadline raises a far more diagnostic error than a bare
            # join TimeoutError would
            leader.add_member("n3", extra.address).join(15)
            assert wait_until(lambda: "n3" in leader.persistent.members)

            # the connected majority elects a successor that never saw the
            # config entry
            assert wait_until(
                lambda: any(
                    cluster.nodes[f].state == RaftState.LEADER for f in followers
                ),
                timeout=15,
            ), {nid: n.state for nid, n in cluster.nodes.items()}

            # heal: the deposed leader's conflicting suffix is truncated and
            # the configuration rolls back to the one in force before it
            plane.heal(lid)
            assert wait_until(
                lambda: leader.state != RaftState.LEADER, timeout=15
            )
            assert wait_until(
                lambda: set(leader.persistent.members) == original_members,
                timeout=15,
            ), leader.persistent.members
            for f in followers:
                assert set(cluster.nodes[f].persistent.members) == original_members
        finally:
            if extra is not None:
                extra.close()
            cluster.close()

    def test_change_forwarded_during_partition_completes_after_failover(
        self, scheduler, tmp_path
    ):
        from zeebe_tpu.testing.chaos import FaultPlane

        plane = FaultPlane(seed=8)
        cluster = Cluster(scheduler, tmp_path, 3)
        try:
            for nid, node in cluster.nodes.items():
                plane.register_endpoint(nid, node.address)
                plane.install_client(node.client, nid)
            leader = cluster.await_leader()
            lid = leader.node_id
            followers = [n for n in cluster.nodes if n != lid]
            assert wait_until(lambda: cluster.logs[lid].commit_position >= 0)

            plane.isolate(lid)
            # a follower takes the op while the old leader is unreachable:
            # it forwards/retries across the leadership flap until the NEW
            # leader accepts (reference RaftJoinService retry semantics)
            new = cluster._make_node("n4")
            members = {nid: n.address for nid, n in cluster.nodes.items()}
            new.bootstrap(members)
            position = cluster.nodes[followers[0]].add_member(
                "n4", new.address
            ).join(15)
            assert position >= 0
            new_leader = next(
                cluster.nodes[f] for f in followers
                if cluster.nodes[f].state == RaftState.LEADER
            )
            assert "n4" in new_leader.persistent.members

            # after heal the deposed leader converges onto the new config
            plane.heal(lid)
            old = cluster.nodes[lid]
            assert wait_until(
                lambda: "n4" in old.persistent.members, timeout=15
            ), old.persistent.members
        finally:
            cluster.close()


class TestRpcBackoff:
    def test_backoff_ramps_per_window_and_clears_on_inbound(self):
        """One outage fails every in-flight request at once — the burst
        must count as ONE failure (ramp 1x, 2x, ... per retry round, not
        straight to the max), and inbound traffic from the peer (a healed
        follower's poll) clears the backoff instead of sitting it out."""
        import random
        import types

        r = Raft.__new__(Raft)
        r.config = RaftConfig(rpc_backoff_base_ms=50, rpc_backoff_max_ms=2000)
        r.rng = random.Random(0)
        now = [0]
        r.scheduler = types.SimpleNamespace(now_ms=lambda: now[0])
        r._peer_backoff = {}

        for _ in range(10):  # 10 in-flight failures from the same outage
            r._note_peer_failure("p")
        assert r._peer_backoff["p"][0] == 1  # counted once, not ten times
        assert r._peer_backed_off("p")

        # window expired + another failure: NOW it escalates
        now[0] = r._peer_backoff["p"][1]
        r._note_peer_failure("p")
        assert r._peer_backoff["p"][0] == 2
        first_window = r._peer_backoff["p"][1]
        assert first_window > now[0]

        # inbound traffic from the peer clears everything immediately
        r._note_peer_ok("p")
        assert not r._peer_backed_off("p")
        assert "p" not in r._peer_backoff


class TestCompaction:
    def test_compaction_is_segment_aligned_and_survives_restart(
        self, scheduler, tmp_path
    ):
        import dataclasses as dc

        from zeebe_tpu.log.storage import SegmentedLogStorage

        d = str(tmp_path / "compact-log")
        storage = SegmentedLogStorage(d, segment_size=4096)
        log = LogStream(storage, partition_id=0)
        for i in range(400):
            log.append([job_record(i)])
        assert len(storage._segments) > 3
        segments_before = list(storage._segments)
        base = log.compact(300)
        assert 0 < base <= 300
        assert log.record_at(base - 1) is None
        assert log.record_at(base).position == base
        assert len(storage._segments) < len(segments_before)
        # readers start at the floor
        positions = [r.position for r in log.reader(0)]
        assert positions[0] == base and positions[-1] == 399
        storage.flush()
        storage.close()

        # restart: recovery rebuilds EXACTLY the compacted view
        storage2 = SegmentedLogStorage(d, segment_size=4096)
        log2 = LogStream(storage2, partition_id=0)
        assert log2.base_position == base
        assert [r.position for r in log2.reader(0)] == positions
        storage2.close()

    def test_follower_rejoins_after_compaction_via_snapshot(
        self, scheduler, tmp_path
    ):
        """A follower that slept through compaction cannot be served the
        deleted records; it installs the leader's snapshot (fast_forward)
        and replication resumes from the snapshot boundary — the raft-level
        contract behind SnapshotReplicationService catch-up."""
        cluster = Cluster(scheduler, tmp_path, 3, segment_size=8192)
        try:
            leader = cluster.await_leader()
            slow_id = next(nid for nid in cluster.nodes if nid != leader.node_id)
            cluster.nodes[slow_id].close()
            slow_log = cluster.logs[slow_id]
            del cluster.nodes[slow_id]

            # many small batches so storage segments actually roll (one
            # giant batch would land in a single oversized segment and
            # leave nothing compactable)
            for i in range(0, 600, 20):
                leader, last = append_with_retry(
                    cluster, [job_record(j) for j in range(i, i + 20)]
                )
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= last,
                timeout=20,
            )
            # snapshot taken at the commit point; compact the whole prefix
            leader_log = cluster.logs[leader.node_id]
            base = leader_log.compact(leader_log.commit_position)
            assert base > 0

            # the rejoining follower is below the floor: simulate its
            # snapshot install (the cluster broker's replication service
            # does the fetch), then rejoin
            slow_log.fast_forward(base, term=leader_log.term_at(base - 1))
            raft = Raft(
                slow_id,
                slow_log,
                scheduler,
                config=FAST,
                storage_path=os.path.join(str(tmp_path), f"raft-{slow_id}.meta"),
            )
            cluster.nodes[slow_id] = raft
            members = {nid: n.address for nid, n in cluster.nodes.items()}
            for node in cluster.nodes.values():
                node.bootstrap(members)
            # the follower catches up from the snapshot boundary onward
            leader2, last2 = append_with_retry(cluster, [job_record(777)])
            assert wait_until(
                lambda: slow_log.commit_position >= last2, timeout=20
            ), (slow_log.next_position, slow_log.base_position)
            assert slow_log.base_position >= base
        finally:
            cluster.close()
