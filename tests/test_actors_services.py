"""Actor scheduler + service container tests.

Reference parity: ``util/src/test/.../sched`` (actor scheduling, timers,
conditions, futures, single-writer serialization; ActorSchedulerRule /
ControlledActorSchedulerRule) and ``service-container/src/test`` (dependency
start ordering, injection, groups, stop cascades; 2,309 LoC).
"""

import threading
import time

import pytest

from zeebe_tpu.runtime.actors import (
    Actor,
    ActorFuture,
    ActorScheduler,
    ControlledActorScheduler,
)
from zeebe_tpu.runtime.clock import ControlledClock
from zeebe_tpu.runtime.services import Service, ServiceContainer


@pytest.fixture
def scheduler():
    s = ActorScheduler(cpu_threads=2, io_threads=1).start()
    yield s
    s.stop()


@pytest.fixture
def controlled():
    clock = ControlledClock(start_ms=0)
    s = ControlledActorScheduler(clock=clock).start()
    return s, clock


class Recorder(Actor):
    def __init__(self):
        super().__init__()
        self.events = []
        self.started = threading.Event()

    def on_actor_started(self):
        self.events.append("started")
        self.started.set()


class TestActorScheduler:
    def test_submit_and_run(self, scheduler):
        actor = Recorder()
        scheduler.submit_actor(actor).join(5)
        assert actor.events == ["started"]
        done = ActorFuture()
        actor.actor.run(lambda: (actor.events.append("ran"), done.complete())[-1])
        done.join(5)
        assert actor.events == ["started", "ran"]

    def test_call_returns_value(self, scheduler):
        actor = Recorder()
        scheduler.submit_actor(actor).join(5)
        assert actor.actor.call(lambda: 41 + 1).join(5) == 42

    def test_call_propagates_exception(self, scheduler):
        actor = Recorder()
        scheduler.submit_actor(actor).join(5)

        def boom():
            raise ValueError("nope")

        with pytest.raises(ValueError):
            actor.actor.call(boom).join(5)

    def test_single_writer_serialization(self, scheduler):
        """Jobs from many threads interleave but never run concurrently on
        one actor (the core single-writer guarantee)."""
        actor = Recorder()
        scheduler.submit_actor(actor).join(5)
        counter = {"v": 0, "max_in_flight": 0}
        in_flight = {"n": 0}
        total = 2000
        done = ActorFuture()

        def job():
            in_flight["n"] += 1
            counter["max_in_flight"] = max(counter["max_in_flight"], in_flight["n"])
            v = counter["v"]
            counter["v"] = v + 1  # racy unless serialized
            in_flight["n"] -= 1
            if counter["v"] == total:
                done.complete()

        def submit_many():
            for _ in range(total // 4):
                actor.actor.run(job)

        threads = [threading.Thread(target=submit_many) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        done.join(10)
        assert counter["v"] == total
        assert counter["max_in_flight"] == 1

    def test_run_delayed(self, scheduler):
        actor = Recorder()
        scheduler.submit_actor(actor).join(5)
        fired = ActorFuture()
        t0 = time.monotonic()
        actor.actor.run_delayed(50, lambda: fired.complete(time.monotonic() - t0))
        elapsed = fired.join(5)
        assert elapsed >= 0.045

    def test_run_at_fixed_rate_and_cancel(self, scheduler):
        actor = Recorder()
        scheduler.submit_actor(actor).join(5)
        hits = []
        enough = ActorFuture()

        def tick():
            hits.append(1)
            if len(hits) == 3:
                enough.complete()

        timer = actor.actor.run_at_fixed_rate(10, tick)
        enough.join(5)
        timer.cancel()
        n = len(hits)
        time.sleep(0.1)
        assert len(hits) <= n + 1  # at most one in-flight tick after cancel

    def test_condition_signal(self, scheduler):
        actor = Recorder()
        scheduler.submit_actor(actor).join(5)
        fired = ActorFuture()
        cond = actor.actor.on_condition("data-ready", lambda: fired.complete("ok"))
        cond.signal()
        assert fired.join(5) == "ok"

    def test_run_on_completion(self, scheduler):
        a, b = Recorder(), Recorder()
        scheduler.submit_actor(a).join(5)
        scheduler.submit_actor(b).join(5)
        chained = ActorFuture()
        f = a.actor.call(lambda: "payload")
        b.actor.run_on_completion(f, lambda fut: chained.complete(fut.join(0)))
        assert chained.join(5) == "payload"

    def test_close_actor_stops_jobs(self, scheduler):
        actor = Recorder()
        scheduler.submit_actor(actor).join(5)
        scheduler.close_actor(actor).join(5)
        actor.actor.run(lambda: actor.events.append("after-close"))
        time.sleep(0.05)
        assert "after-close" not in actor.events


class TestControlledScheduler:
    def test_deterministic_draining(self, controlled):
        scheduler, _clock = controlled
        actor = Recorder()
        scheduler.submit_actor(actor)
        assert actor.events == []  # nothing runs until work_until_done
        scheduler.work_until_done()
        assert actor.events == ["started"]

    def test_timers_fire_on_clock_advance(self, controlled):
        scheduler, clock = controlled
        actor = Recorder()
        scheduler.submit_actor(actor)
        scheduler.work_until_done()
        actor.actor.run_delayed(1000, lambda: actor.events.append("late"))
        scheduler.work_until_done()
        assert "late" not in actor.events
        clock.advance(999)
        scheduler.work_until_done()
        assert "late" not in actor.events
        clock.advance(1)
        scheduler.work_until_done()
        assert "late" in actor.events

    def test_fixed_rate_fires_per_period(self, controlled):
        scheduler, clock = controlled
        actor = Recorder()
        scheduler.submit_actor(actor)
        scheduler.work_until_done()
        hits = []
        actor.actor.run_at_fixed_rate(100, lambda: hits.append(scheduler.now_ms()))
        for _ in range(3):
            clock.advance(100)
            scheduler.work_until_done()
        assert hits == [100, 200, 300]

    def test_job_exception_does_not_wedge_actor(self, controlled):
        """A raising job must not leave the actor permanently unschedulable
        (regression: _running stayed True after an uncaught exception)."""
        scheduler, _clock = controlled
        actor = Recorder()
        scheduler.submit_actor(actor)
        scheduler.work_until_done()

        def boom():
            raise RuntimeError("job failed")

        actor.actor.run(boom)
        scheduler.work_until_done()
        actor.actor.run(lambda: actor.events.append("alive"))
        scheduler.work_until_done()
        assert "alive" in actor.events


class Tracked(Service):
    def __init__(self, log, name):
        self.log = log
        self.name = name
        self.injected = {}

    def start(self, ctx):
        self.log.append(("start", self.name))

    def stop(self, ctx):
        self.log.append(("stop", self.name))


class TestServiceContainer:
    @pytest.fixture
    def container(self, controlled):
        scheduler, _ = controlled
        c = ServiceContainer(scheduler)
        scheduler.work_until_done()
        return c, scheduler

    def test_start_ordering_follows_dependencies(self, container):
        c, s = container
        log = []
        # install dependent FIRST: must wait for its dependency
        c.create_service("b", Tracked(log, "b")).dependency("a").install()
        s.work_until_done()
        assert log == []
        c.create_service("a", Tracked(log, "a")).install()
        s.work_until_done()
        assert log == [("start", "a"), ("start", "b")]

    def test_injection(self, container):
        c, s = container
        log = []
        a = Tracked(log, "a")
        b = Tracked(log, "b")
        c.create_service("a", a).install()
        c.create_service("b", b).dependency(
            "a", lambda svc: b.injected.__setitem__("a", svc)
        ).install()
        s.work_until_done()
        assert b.injected["a"] is a

    def test_double_install_fails(self, container):
        c, s = container
        log = []
        f1 = c.create_service("x", Tracked(log, "x1")).install()
        f2 = c.create_service("x", Tracked(log, "x2")).install()
        s.work_until_done()
        assert f1.join(0)
        with pytest.raises(ValueError):
            f2.join(0)

    def test_remove_cascades_to_dependents(self, container):
        c, s = container
        log = []
        c.create_service("a", Tracked(log, "a")).install()
        c.create_service("b", Tracked(log, "b")).dependency("a").install()
        c.create_service("c", Tracked(log, "c")).dependency("b").install()
        s.work_until_done()
        log.clear()
        c.remove_service("a")
        s.work_until_done()
        assert log == [("stop", "c"), ("stop", "b"), ("stop", "a")]
        assert not c.has_service("a")

    def test_groups_join_leave_listeners(self, container):
        c, s = container
        log = []
        joins, leaves = [], []
        c.on_group_change(
            "partitions",
            on_join=lambda n, svc: joins.append(n),
            on_leave=lambda n, svc: leaves.append(n),
        )
        c.create_service("p-0", Tracked(log, "p-0")).group("partitions").install()
        s.work_until_done()
        assert joins == ["p-0"]
        # late listener sees existing members
        late_joins = []
        c.on_group_change("partitions", on_join=lambda n, svc: late_joins.append(n))
        s.work_until_done()
        assert late_joins == ["p-0"]
        c.remove_service("p-0")
        s.work_until_done()
        assert leaves == ["p-0"]
        assert c.group_members("partitions") == []

    def test_composite_install(self, container):
        c, s = container
        log = []
        comp = c.composite()
        comp.create_service("x", Tracked(log, "x"))
        comp.create_service("y", Tracked(log, "y")).dependency("x")
        done = comp.install()
        s.work_until_done()
        assert done.is_done()
        assert ("start", "x") in log and ("start", "y") in log

    def test_circular_dependency_rejected(self, container):
        c, s = container
        log = []
        c.create_service("a", Tracked(log, "a")).dependency("b").install()
        f = c.create_service("b", Tracked(log, "b")).dependency("a").install()
        s.work_until_done()
        with pytest.raises(ValueError, match="circular"):
            f.join(0)

    def test_composite_install_failure_propagates(self, container):
        c, s = container

        class Failing(Service):
            def start(self, ctx):
                raise RuntimeError("bad service")

        comp = c.composite()
        comp.create_service("ok", Tracked([], "ok"))
        comp.create_service("bad", Failing())
        done = comp.install()
        s.work_until_done()
        with pytest.raises(RuntimeError, match="bad service"):
            done.join(0)

    def test_remove_pending_service_unblocks_installer(self, container):
        """Removing a never-started registration must not call stop() and
        must fail the pending install future (regression)."""
        c, s = container
        log = []
        f = c.create_service("waiting", Tracked(log, "waiting")).dependency("never").install()
        removed = c.remove_service("waiting")
        s.work_until_done()
        assert removed.is_done()
        with pytest.raises(ValueError, match="removed before start"):
            f.join(0)
        assert log == []  # neither start nor stop ran

    def test_concurrent_remove_completes_after_stop(self, container):
        c, s = container
        log = []
        c.create_service("x", Tracked(log, "x")).install()
        s.work_until_done()
        f1 = c.remove_service("x")
        f2 = c.remove_service("x")
        s.work_until_done()
        assert f1.is_done() and f2.is_done()
        assert log.count(("stop", "x")) == 1

    def test_close_stops_everything(self, container):
        c, s = container
        log = []
        c.create_service("a", Tracked(log, "a")).install()
        c.create_service("b", Tracked(log, "b")).dependency("a").install()
        s.work_until_done()
        log.clear()
        c.close()
        s.work_until_done()
        assert ("stop", "a") in log and ("stop", "b") in log
        assert log.index(("stop", "b")) < log.index(("stop", "a"))


class TestActorFailureEscalation:
    """Actor job exceptions are counted and surfaced, never silently
    swallowed (reference: ActorTask failure handling escalates through the
    actor lifecycle). Round-4 lesson: a NameError in the broker tick
    survived 468 green tests because _drain only printed the traceback."""

    def test_failures_are_counted_and_listeners_fire(self):
        from zeebe_tpu.runtime.actors import Actor, ControlledActorScheduler

        s = ControlledActorScheduler().start()
        seen = []
        s.on_actor_failure(lambda actor, exc: seen.append((actor.name, type(exc))))
        a = Actor("bad-actor")
        s.submit_actor(a)
        s.work_until_done()

        def boom():
            raise NameError("_undefined_symbol")

        for _ in range(3):
            a.actor.run(boom)
        s.work_until_done()
        assert s.actor_failures == 3
        assert a._failure_count == 3
        assert [t for _, t in seen] == [NameError] * 3
        assert all(name == "bad-actor" for name, _ in seen)
        assert len(s.last_failures) == 3
        assert "_undefined_symbol" in s.last_failures[-1][1]

    def test_threaded_drain_counts_failures(self):
        import time as _time

        from zeebe_tpu.runtime.actors import Actor, ActorScheduler

        s = ActorScheduler(cpu_threads=1, io_threads=0).start()
        try:
            a = Actor("bad-threaded")
            s.submit_actor(a).join(5)
            a.actor.run(lambda: (_ for _ in ()).throw(RuntimeError("x")))
            deadline = _time.monotonic() + 5
            while s.actor_failures < 1 and _time.monotonic() < deadline:
                _time.sleep(0.01)
            assert s.actor_failures == 1
        finally:
            s.stop()

    def test_cluster_broker_health_flips_on_repeated_failures(self, tmp_path):
        from zeebe_tpu.runtime.cluster_broker import ClusterBroker
        from zeebe_tpu.runtime.config import BrokerCfg

        cfg = BrokerCfg()
        cfg.network.client_port = 0
        cfg.network.management_port = 0
        cfg.network.subscription_port = 0
        cfg.metrics.enabled = False
        broker = ClusterBroker(cfg, str(tmp_path / "b0"))
        try:
            assert broker.healthy()
            bad = object.__new__(type("X", (), {}))
            bad.name = "broken-tick"
            for _ in range(3):
                broker._on_actor_failure(bad, NameError("_due_probe_jit"))
            assert not broker.healthy()
            assert broker.metrics_actor_failures.value == 3
        finally:
            broker.close()
