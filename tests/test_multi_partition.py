"""Multi-partition behaviors: job routing, credits, subscription cleanup.

These cover the cross-partition seams the reference exercises in
qa/integration-tests (ClusteringRule): instances sharded over partitions,
jobs completed on the right partition, message subscriptions closed after
correlation.
"""

import pytest

from zeebe_tpu.gateway import JobWorker, ZeebeClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.protocol.enums import ValueType
from zeebe_tpu.protocol.intents import MessageSubscriptionIntent, WorkflowInstanceIntent as WI
from zeebe_tpu.runtime import Broker, ControlledClock


@pytest.fixture
def broker(tmp_path):
    b = Broker(num_partitions=4, data_dir=str(tmp_path / "mp"), clock=ControlledClock())
    yield b
    b.close()


@pytest.fixture
def client(broker):
    return ZeebeClient(broker)


def order_model():
    return (
        Bpmn.create_process("order")
        .start_event()
        .service_task("work", type="t")
        .end_event()
        .done()
    )


def test_jobs_complete_on_their_own_partition(broker, client):
    """Job keys collide across partitions (each partition has its own strided
    generator); completion must route to the partition that pushed the job."""
    client.deploy_model(order_model())
    worker = JobWorker(broker, "t", lambda ctx: {"done": ctx.partition_id})
    # one instance on every partition → same job key on each partition
    for pid in range(4):
        client.create_instance("order", {"p": pid}, partition_id=pid)
    broker.run_until_idle()
    assert len(worker.handled) == 4
    # every instance completed on its own partition
    for pid in range(4):
        completed = [
            r
            for r in broker.records(pid)
            if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
            and r.metadata.intent == WI.ELEMENT_COMPLETED
            and r.value.activity_id == "order"
        ]
        assert len(completed) == 1, f"partition {pid} did not complete"
        assert completed[0].value.payload["done"] == pid
        assert broker.partitions[pid].engine.jobs == {}


def test_credits_do_not_inflate_across_partitions(broker, client):
    client.deploy_model(order_model())
    worker = JobWorker(broker, "t", lambda ctx: None, credits=8)
    for pid in range(4):
        for _ in range(3):
            client.create_instance("order", partition_id=pid)
    broker.run_until_idle()
    assert len(worker.handled) == 12
    # every partition's credit counter returned exactly to its initial value
    for partition in broker.partitions:
        subs = [
            s
            for s in partition.engine.job_subscriptions
            if s.subscriber_key == worker.subscriber_key
        ]
        assert len(subs) == 1
        assert subs[0].credits == worker.initial_credits


def test_message_subscription_closed_after_correlation(broker, client):
    model = (
        Bpmn.create_process("msg")
        .start_event()
        .message_catch_event("wait", message_name="m", correlation_key="$.cid")
        .end_event()
        .done()
    )
    client.deploy_model(model)
    client.create_instance("msg", {"cid": "abc"}, partition_id=1)
    broker.run_until_idle()
    msg_pid = broker.partition_for_correlation_key("abc")
    assert len(broker.partitions[msg_pid].engine.message_subscriptions) == 1
    client.publish_message("m", "abc", {"got": 1})
    broker.run_until_idle()
    # instance completed AND the subscription store is clean again
    assert broker.partitions[msg_pid].engine.message_subscriptions == []
    closed = [
        r
        for r in broker.records(msg_pid)
        if r.metadata.value_type == ValueType.MESSAGE_SUBSCRIPTION
        and r.metadata.intent == MessageSubscriptionIntent.CLOSED
    ]
    assert len(closed) == 1


def test_terminated_catch_event_closes_subscription(broker, client):
    model = (
        Bpmn.create_process("msg2")
        .start_event()
        .message_catch_event("wait", message_name="m2", correlation_key="$.cid")
        .end_event()
        .done()
    )
    client.deploy_model(model)
    instance = client.create_instance("msg2", {"cid": "xyz"}, partition_id=2)
    broker.run_until_idle()
    msg_pid = broker.partition_for_correlation_key("xyz")
    assert len(broker.partitions[msg_pid].engine.message_subscriptions) == 1
    client.cancel_instance(instance.workflow_instance_key, partition_id=2)
    broker.run_until_idle()
    assert broker.partitions[msg_pid].engine.message_subscriptions == []
