"""Standalone launcher + deployment-asset tests.

Boots real ``python -m zeebe_tpu`` subprocesses with the EXACT argument
vector the Dockerfile CMD passes and the EXACT env names the compose file
sets, so the shipped deployment assets are exercised, not approximated
(reference: StandaloneBroker.main + docker/compose).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIST_CFG = os.path.join(REPO, "dist", "zeebe.cfg.toml")


def _free_port_block(n=3):
    """A port offset whose 26500..26504+off*10 and 9600+off*10 blocks are
    free for ``n`` consecutive offsets."""
    for off in range(100, 900, n):
        ok = True
        for i in range(n):
            shift = (off + i) * 10
            for base in (26500, 26501, 26502, 26503, 26504, 9600):
                with socket.socket() as s:
                    try:
                        s.bind(("127.0.0.1", base + shift))
                    except OSError:
                        ok = False
                        break
            if not ok:
                break
        if ok:
            return off
    pytest.skip("no free port block")


def _spawn_broker(tmp_path, node_id, port_offset, extra_env=None, args=None):
    env = dict(os.environ)
    env.update(
        {
            # compose env surface (docker/compose/docker-compose.yml)
            "ZEEBE_NODE_ID": node_id,
            "ZEEBE_HOST": "127.0.0.1",
            "ZEEBE_PORT_OFFSET": str(port_offset),
        }
    )
    env.update(extra_env or {})
    # exact Dockerfile CMD argument vector (config path swapped for the
    # repo's dist file — the image COPYs the same file to /opt/zeebe-tpu)
    argv = args if args is not None else [
        "--config", DIST_CFG, "--data-dir", str(tmp_path / node_id)
    ]
    proc = subprocess.Popen(
        [sys.executable, "-m", "zeebe_tpu", *argv],
        cwd=REPO,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    # a reader thread drains stdout into a list: selecting on the raw fd
    # under a buffered TextIO misses lines the wrapper already holds, and
    # a blocking readline would defeat _await_line's deadline
    proc.captured_lines = []

    def _drain():
        for line in proc.stdout:
            proc.captured_lines.append(line)

    import threading

    threading.Thread(target=_drain, daemon=True).start()
    return proc


def _await_line(proc, needle, timeout=60):
    deadline = time.time() + timeout
    scanned = 0
    while time.time() < deadline:
        lines = proc.captured_lines
        while scanned < len(lines):
            line = lines[scanned]
            scanned += 1
            if needle in line:
                return line
        if proc.poll() is not None:
            # give the drain thread a beat, then scan whatever arrived
            time.sleep(0.2)
            if any(needle in line for line in proc.captured_lines[scanned:]):
                return needle
            raise AssertionError(
                f"broker exited rc={proc.returncode}:\n"
                f"{''.join(proc.captured_lines)}"
            )
        time.sleep(0.05)
    raise AssertionError(
        f"timeout waiting for {needle!r}:\n{''.join(proc.captured_lines)}"
    )


def _stop(procs):
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


class TestDockerCmdBoot:
    def test_dockerfile_cmd_and_compose_env_boot_a_cluster(self, tmp_path):
        """3 brokers launched with the Dockerfile CMD argv + compose env
        names gossip-join, bootstrap, and serve gRPC + /metrics."""
        off = _free_port_block(3)
        contact = f"127.0.0.1:{26502 + off * 10}"
        procs = []
        try:
            procs.append(
                _spawn_broker(
                    tmp_path, "broker-0", off,
                    {"ZEEBE_BOOTSTRAP_EXPECT": "3"},
                )
            )
            for i in (1, 2):
                procs.append(
                    _spawn_broker(
                        tmp_path, f"broker-{i}", off + i,
                        {
                            "ZEEBE_BOOTSTRAP_EXPECT": "3",
                            # exact compose env name
                            "ZEEBE_CONTACT_POINTS": contact,
                        },
                    )
                )
            for proc in procs:
                _await_line(proc, "gRPC gateway on")

            # the cluster self-bootstraps; the gateway serves topology
            import grpc

            from zeebe_tpu.gateway.grpc_gateway import GrpcGatewayClient

            stub = GrpcGatewayClient("127.0.0.1", 26500 + off * 10)
            try:
                deadline = time.time() + 60
                brokers = []
                while time.time() < deadline:
                    try:
                        brokers = list(stub.health_check().brokers)
                        if brokers:
                            break
                    except grpc.RpcError:
                        pass
                    time.sleep(0.5)
                assert brokers, "gateway never served topology"
            finally:
                stub.close()

            # prometheus target: the broker serves /metrics itself
            with urllib.request.urlopen(
                f"http://127.0.0.1:{9600 + off * 10}/metrics", timeout=5
            ) as rsp:
                text = rsp.read().decode()
            assert "zb_" in text
        finally:
            _stop(procs)

    def test_missing_config_file_is_an_error(self, tmp_path):
        proc = _spawn_broker(
            tmp_path, "broker-x", 0,
            args=["--config", str(tmp_path / "nope.toml")],
        )
        try:
            rc = proc.wait(timeout=30)
            time.sleep(0.2)  # let the drain thread catch the tail
            out = "".join(proc.captured_lines)
            assert rc != 0
            assert "not found" in out
        finally:
            _stop([proc])


class TestTpuEngineLauncher:
    def test_engine_tpu_serves_order_process_over_grpc(self, tmp_path):
        """A broker launched with [engine] type="tpu" serves deploy →
        create → job-complete → instance-complete end to end (VERDICT
        round-2 item 2: the flagship engine must be reachable in the
        shipped product, not only in tests)."""
        off = _free_port_block(1)
        cfg_path = tmp_path / "zeebe.cfg.toml"
        cfg_path.write_text(
            "[network]\n"
            'host = "127.0.0.1"\n'
            "[engine]\n"
            'type = "tpu"\n'
            "capacity = 512\n"
            "[metrics]\n"
            "port = 0\n"
        )
        proc = _spawn_broker(
            tmp_path, "tpu-0", off,
            # tests run the device kernel on CPU (conftest contract);
            # the subprocess must do the same, with the shared compile
            # cache so the kernel compile doesn't dominate the test
            {
                "JAX_PLATFORMS": "cpu",
                "ZEEBE_JAX_CACHE_DIR": os.path.join(REPO, ".jax_cache"),
            },
            args=["--config", str(cfg_path), "--data-dir", str(tmp_path / "d")],
        )
        try:
            line = _await_line(proc, "zeebe-tpu broker")
            assert "engine=tpu" in line
            _await_line(proc, "gRPC gateway on")

            from zeebe_tpu.gateway.cluster_client import ClusterClient
            from zeebe_tpu.models.bpmn.builder import Bpmn
            from zeebe_tpu.transport import RemoteAddress

            client = ClusterClient(
                [RemoteAddress("127.0.0.1", 26501 + off * 10)],
                num_partitions=1,
                # the first CREATE triggers the kernel jit compile; the
                # command response waits behind it
                request_timeout_ms=180_000,
            )
            try:
                deadline = time.time() + 90
                while time.time() < deadline:
                    if client.refresh_topology():
                        break
                    time.sleep(0.5)
                model = (
                    Bpmn.create_process("order-process")
                    .start_event()
                    .service_task("collect-money", type="payment-service")
                    .end_event()
                    .done()
                )
                client.deploy_model(model)
                done = []
                worker = client.open_job_worker(
                    "payment-service",
                    lambda pid, rec: done.append(rec.key) or {"paid": True},
                )
                client.create_instance("order-process", payload={"total": 100.0})
                # cold compile cache: the activation wave is a second
                # kernel shape and can take minutes on CPU
                deadline = time.time() + 240
                while time.time() < deadline and not done:
                    time.sleep(0.2)
                assert done, "job was never pushed to the worker"
                worker.close()
            finally:
                client.close()
        finally:
            _stop([proc])


class TestNativeStorageLauncher:
    def test_native_storage_broker_serves_end_to_end(self, tmp_path):
        """`[data] nativeStorage = true` (the container config surface —
        the Docker image builds native/ at image build time) boots, serves
        an instance end to end, and leaves native-format segments in the
        data dir (VERDICT round-3 #9: the configured native layer must
        work where the image enables it)."""
        import pytest as _pytest

        from zeebe_tpu import native as native_mod

        if not native_mod.available():
            _pytest.skip("native toolchain unavailable")
        off = _free_port_block(1)
        proc = _spawn_broker(
            tmp_path, "native-0", off,
            {"ZEEBE_NATIVE_STORAGE": "true", "JAX_PLATFORMS": "cpu"},
        )
        try:
            # the broker must actually select the native backend — a broker
            # that silently fell back would boot with storage=python
            line = _await_line(proc, "zeebe-tpu broker")
            assert "storage=native" in line, line
            _await_line(proc, "gRPC gateway on")
            from zeebe_tpu.gateway.cluster_client import ClusterClient
            from zeebe_tpu.models.bpmn.builder import Bpmn
            from zeebe_tpu.transport import RemoteAddress

            client = ClusterClient(
                [RemoteAddress("127.0.0.1", 26501 + off * 10)],
                num_partitions=1,
                request_timeout_ms=60_000,
            )
            try:
                deadline = time.time() + 60
                while time.time() < deadline:
                    if client.refresh_topology():
                        break
                    time.sleep(0.5)
                model = (
                    Bpmn.create_process("native-proc")
                    .start_event()
                    .service_task("work", type="io-service")
                    .end_event()
                    .done()
                )
                client.deploy_model(model)
                done = []
                worker = client.open_job_worker(
                    "io-service", lambda pid, rec: done.append(rec.key) or {}
                )
                client.create_instance("native-proc", payload={"n": 1})
                deadline = time.time() + 60
                while time.time() < deadline and not done:
                    time.sleep(0.2)
                assert done, "job was never pushed to the worker"
                worker.close()
            finally:
                client.close()
        finally:
            _stop([proc])
