"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path; real TPU hardware is only used by bench.py), mirroring the
reference's strategy of running multi-node tests in one JVM
(SURVEY.md §4: ClusteringRule runs 3 real brokers in-process).
"""

import os

# Must be set before jax is imported anywhere. Forced (not setdefault): the
# runner environment pre-sets JAX_PLATFORMS=axon (the tunneled TPU), but
# tests must run on the virtual CPU mesh — the real chip is bench-only.
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
# Persistent compile cache: the step kernel is a large jit program; caching
# makes repeat test runs fast.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import pytest  # noqa: E402


@pytest.fixture
def tmp_log_dir(tmp_path):
    return str(tmp_path / "log")
