"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path; real TPU hardware is only used by bench.py), mirroring the
reference's strategy of running multi-node tests in one JVM
(SURVEY.md §4: ClusteringRule runs 3 real brokers in-process).
"""

import os

# Must be set before jax is imported anywhere. Forced (not setdefault): the
# runner environment pre-sets JAX_PLATFORMS=axon (the tunneled TPU), but
# tests must run on the virtual CPU mesh — the real chip is bench-only.
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

# The env var alone is NOT enough: the runner's sitecustomize re-injects the
# axon platform, silently routing every test op through the TPU tunnel
# (orders of magnitude slower). The config update below wins as long as it
# happens before the backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache: the step kernel is a large jit program; caching
# makes repeat test runs fast. (Must be config.update, not env vars — this
# jax build never reads the JAX_COMPILATION_CACHE_DIR env var.)
# The cache dir is keyed by a machine fingerprint: the repo (incl. ignored
# files) persists across build rounds that may land on DIFFERENT machines,
# and XLA:CPU AOT executables compiled for another machine's CPU features
# fail to load (or risk SIGILL) — a stale cross-machine cache turned the
# whole suite into a compile storm in round 4.
import hashlib
import platform


def _machine_fingerprint() -> str:
    # the fingerprint must cover the COMPILER, not just the CPU: XLA:CPU
    # AOT entries written by a different jax/jaxlib build carry target
    # configs the current loader only warns about ("machine feature
    # +prefer-no-scatter is not supported ... could lead to SIGILL") and
    # executing them can kill broker threads mid-test — observed as the
    # round-4 "leader connection refused" flake class
    import jaxlib

    try:
        with open("/proc/cpuinfo") as f:
            flags = next(
                (line for line in f if line.startswith("flags")), platform.machine()
            )
    except OSError:
        flags = platform.machine()
    tag = f"{flags}|jax={jax.__version__}|jaxlib={jaxlib.__version__}"
    return hashlib.sha256(tag.encode()).hexdigest()[:12]


jax.config.update(
    "jax_compilation_cache_dir",
    os.path.abspath(
        os.path.join(
            os.path.dirname(__file__), "..", ".jax_cache", _machine_fingerprint()
        )
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
# Round 6: the cache's WRITE path is itself a crash source on this jaxlib
# CPU build — `_compile_and_write_cache` (executable serialization for the
# disk entry) dies with SIGABRT/SIGSEGV nondeterministically (~50%
# observed on the post-restore step-program compile in
# test_tpu_parity.py::test_mixed_deployment_survives_snapshot_restore,
# REGARDLESS of kernel version — one crash aborts the whole pytest
# process). Warm caches masked it: reads are safe, so a populated dir
# never re-enters the writer. Tests always run on the CPU mesh (forced
# above) where compiles are seconds, so the persistent cache is disabled
# here outright; bench.py keeps its own cache for the TPU path, where
# compiles are minutes and the CPU serializer is not involved.
jax.config.update("jax_enable_compilation_cache", False)

import pytest  # noqa: E402


def pytest_configure(config):
    # tier gating: ci.sh and the tier-1 verify run `-m "not slow"`; the
    # marker must be registered or pytest treats it as unknown (warning
    # noise, and a typo'd mark silently drops a suite out of its tier)
    config.addinivalue_line(
        "markers",
        "slow: tier-2 suites (volume pins, randomized sweeps, device-engine "
        "clusters) excluded from tier-1; run with `pytest -m slow`",
    )


@pytest.fixture
def tmp_log_dir(tmp_path):
    return str(tmp_path / "log")


def make_tpu_broker(data_dir=None, clock=None, num_partitions=1):
    """A single-node Broker whose partitions run the TPU device engine
    (shared helper for the device-engine test classes)."""
    from zeebe_tpu.engine.interpreter import WorkflowRepository
    from zeebe_tpu.runtime import Broker, ControlledClock
    from zeebe_tpu.tpu import TpuPartitionEngine

    clock = clock or ControlledClock(start_ms=1_000_000)
    repo = WorkflowRepository()
    return Broker(
        num_partitions=num_partitions,
        data_dir=data_dir,
        clock=clock,
        engine_factory=lambda pid: TpuPartitionEngine(
            pid, num_partitions, repository=repo, clock=clock
        ),
    )
