"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the driver separately dry-runs the
multi-chip path; real TPU hardware is only used by bench.py), mirroring the
reference's strategy of running multi-node tests in one JVM
(SURVEY.md §4: ClusteringRule runs 3 real brokers in-process).
"""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_log_dir(tmp_path):
    return str(tmp_path / "log")
