"""Full wire-path volume test: client → TCP → log append → raft commit →
partition engine → worker push → job complete → responses, at four-digit
instance counts in CI (VERDICT round-4 item 4; reference:
``ClientApiMessageHandler.java:90-165`` → processors → responders, driven
by the qa integration suites at volume).

The serving path was round 4's least-tested surface — its bench config
could not even bring up a cluster. These tests pin (a) deterministic
single-node bring-up, (b) a 10k-instance create/complete run with the
pipelined worker, and (c) that the engine really serves from the device
path when configured so.
"""

import tempfile
import threading
import time

import pytest

# Volume + device-engine wire tests: on a shared-CPU container the cold
# XLA compiles and the 10k-instance run exceed tier-1's wall budget (this
# module alone ran >550s there), so the whole module is tier-2 — run it
# with `pytest -m slow`.
pytestmark = pytest.mark.slow

from zeebe_tpu.gateway.cluster_client import ClusterClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.runtime.cluster_broker import ClusterBroker
from zeebe_tpu.runtime.config import BrokerCfg


def make_broker(tmp_dir, engine="host", capacity=4096):
    cfg = BrokerCfg()
    cfg.network.client_port = 0
    cfg.network.management_port = 0
    cfg.network.subscription_port = 0
    cfg.metrics.enabled = False
    cfg.engine.type = engine
    cfg.engine.capacity = capacity
    from zeebe_tpu.runtime.engines import engine_factory_from_config

    broker = ClusterBroker(
        cfg, tmp_dir, engine_factory=engine_factory_from_config(cfg)
    )
    broker.open_partition(0).join(120)
    broker.bootstrap_partition(0, {})
    deadline = time.time() + 120
    while time.time() < deadline and not broker.partitions[0].is_leader:
        time.sleep(0.01)
    assert broker.partitions[0].is_leader, "single-node bring-up failed"
    return broker


MODEL = (
    Bpmn.create_process("serve")
    .start_event()
    .service_task("work", type="serve-svc")
    .end_event()
    .done()
)


class TestServingPathVolume:
    def test_10k_instances_complete_through_the_wire(self, tmp_path):
        """≥10k instances created over real sockets, every job pushed and
        completed, every response delivered. The completion wait budget is
        generous: CI machines vary, but the run must CONVERGE."""
        broker = make_broker(str(tmp_path), engine="host", capacity=32768)
        try:
            client = ClusterClient([broker.client_address], num_partitions=1)
            try:
                client.deploy_model(MODEL)
                done = []
                worker = client.open_job_worker(
                    "serve-svc", lambda pid, rec: done.append(rec.key) or {},
                    credits=512,
                )
                n, threads = 10_240, 32
                errors = []

                def pump(k):
                    for i in range(n // threads):
                        try:
                            client.create_instance("serve", {"k": k, "i": i})
                        except Exception as e:  # noqa: BLE001
                            errors.append(repr(e)[:200])
                            return

                ts = [
                    threading.Thread(target=pump, args=(k,), daemon=True)
                    for k in range(threads)
                ]
                t0 = time.perf_counter()
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(300)
                assert not errors, errors[:3]
                deadline = time.time() + 300
                while time.time() < deadline and len(done) < n:
                    time.sleep(0.1)
                elapsed = time.perf_counter() - t0
                assert len(done) == n, (len(done), n)
                # every job completed exactly once (no double pushes on the
                # happy path; at-least-once only applies across failovers)
                assert len(set(done)) == n
                print(
                    f"serving path: {n} instances in {elapsed:.1f}s "
                    f"({n / elapsed:.0f} inst/s)"
                )
                worker.close()
            finally:
                client.close()
        finally:
            broker.close()

    def test_device_engine_serves_the_wire_path(self, tmp_path):
        """The TPU engine behind the same wire path: 256 instances, every
        one served from the DEVICE table (asserted via the engine's
        residency counters, not inferred)."""
        broker = make_broker(str(tmp_path), engine="tpu", capacity=4096)
        try:
            client = ClusterClient([broker.client_address], num_partitions=1)
            try:
                client.deploy_model(MODEL)
                done = []
                worker = client.open_job_worker(
                    "serve-svc", lambda pid, rec: done.append(rec.key) or {},
                    credits=128,
                )
                n = 256
                for i in range(n):
                    client.create_instance("serve", {"i": i})
                deadline = time.time() + 180
                while time.time() < deadline and len(done) < n:
                    time.sleep(0.05)
                assert len(done) == n, (len(done), n)
                engine = broker.partitions[0].engine
                assert engine.device_records_processed > 0
                worker.close()
            finally:
                client.close()
        finally:
            broker.close()
