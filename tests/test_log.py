"""Log storage + log stream tests (reference: logstreams module tests)."""

from zeebe_tpu.log import LogStream, LogStreamReader, SegmentedLogStorage
from zeebe_tpu.protocol import RecordType, ValueType, WorkflowInstanceIntent
from zeebe_tpu.protocol.metadata import RecordMetadata
from zeebe_tpu.protocol.records import Record, WorkflowInstanceRecord
from zeebe_tpu.testing import DiskFaults


def wi_record(key=1, activity="start", intent=WorkflowInstanceIntent.ELEMENT_READY):
    return Record(
        key=key,
        metadata=RecordMetadata(
            record_type=RecordType.EVENT,
            value_type=ValueType.WORKFLOW_INSTANCE,
            intent=int(intent),
        ),
        value=WorkflowInstanceRecord(activity_id=activity, workflow_instance_key=key),
    )


def test_append_assigns_dense_positions(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    log.append([wi_record(), wi_record()])
    last = log.append([wi_record()])
    assert last == 2
    assert log.next_position == 3
    assert log.commit_position == 2


def test_reader_iterates_in_order(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    for i in range(10):
        log.append([wi_record(key=i, activity=f"a{i}")])
    records = list(log.reader(0))
    assert [r.position for r in records] == list(range(10))
    assert [r.value.activity_id for r in records] == [f"a{i}" for i in range(10)]


def test_reader_seek(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    for i in range(10):
        log.append([wi_record(key=i)])
    reader = log.reader(7)
    assert [r.position for r in reader] == [7, 8, 9]


def test_recovery_after_reopen(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    for i in range(5):
        log.append([wi_record(key=i)])
    log.flush()
    log.storage.close()

    reopened = LogStream(SegmentedLogStorage(tmp_log_dir))
    assert reopened.next_position == 5
    assert reopened.commit_position == 4
    assert [r.position for r in reopened.reader(0)] == list(range(5))
    # appends continue from the recovered position
    assert reopened.append([wi_record(key=99)]) == 5


def test_append_after_close_reopens_current_segment(tmp_log_dir):
    """Regression (BENCH_r05 tail): an append arriving after close() —
    broker shutdown racing a late drain — crashed with ``AttributeError:
    'NoneType' object has no attribute 'seek'``. The storage must reopen
    the current segment and keep the address sequence intact."""
    storage = SegmentedLogStorage(tmp_log_dir)
    a0 = storage.append(b"block-0")
    storage.close()
    a1 = storage.append(b"block-1")  # must reopen, not crash
    assert storage.segment_of(a1) == storage.segment_of(a0)
    assert storage.offset_of(a1) == storage.offset_of(a0) + len(b"block-0")
    assert storage.read(a0, 7) == b"block-0"
    assert storage.read(a1, 7) == b"block-1"
    # close/reset interplay: reset on a closed storage must not crash
    storage.close()
    storage.reset()
    assert storage.append(b"fresh") > 0


def test_log_append_after_storage_close(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    log.append([wi_record(key=1)])
    log.storage.close()
    # the stream keeps accepting appends after its storage was closed
    assert log.append([wi_record(key=2)]) == 1
    assert [r.key for r in log.reader(0)] == [1, 2]


def test_segment_rolling(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir, segment_size=1024))
    for i in range(50):
        log.append([wi_record(key=i, activity="activity-with-a-longer-name")])
    assert len(log.storage._segments) > 1
    assert [r.position for r in log.reader(0)] == list(range(50))


def test_truncate(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    for i in range(10):
        log.append([wi_record(key=i)])
    log.truncate(6)
    assert [r.position for r in log.reader(0)] == list(range(6))
    assert log.next_position == 6
    # positions are re-assigned after truncation
    assert log.append([wi_record(key=100)]) == 6


def test_commit_listener(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    seen = []
    log.on_commit(seen.append)
    log.append([wi_record()], commit=False)
    assert seen == []
    log.set_commit_position(0)
    assert seen == [0]


def test_read_committed_stops_at_commit_position(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    log.append([wi_record(key=1)], commit=True)
    log.append([wi_record(key=2)], commit=False)
    reader = LogStreamReader(log, 0)
    records = reader.read_committed()
    assert [r.position for r in records] == [0]


def test_torn_tail_truncated_on_reopen_and_appends_resume(tmp_log_dir):
    """Acceptance regression: a segment truncated mid-record is detected
    via CRC on reopen, cut back to the last whole record, and appends
    RESUME from there — before this, the torn bytes stayed in the file and
    every post-restart append landed after them, unreachable to replay."""
    from zeebe_tpu.runtime.metrics import event_count

    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    for i in range(5):
        log.append([wi_record(key=i)])
    log.flush()
    log.storage.close()
    DiskFaults.tear_log_tail(tmp_log_dir, nbytes=13)

    t0 = event_count("log_torn_tail_truncations")
    reopened = LogStream(SegmentedLogStorage(tmp_log_dir))
    assert event_count("log_torn_tail_truncations") - t0 == 1
    assert reopened.next_position == 4  # last record discarded
    assert reopened.append([wi_record(key=99)]) == 4
    reopened.flush()
    reopened.storage.close()

    # the resumed append is durable and replay sees a contiguous log
    final = LogStream(SegmentedLogStorage(tmp_log_dir))
    assert [r.position for r in final.reader(0)] == [0, 1, 2, 3, 4]
    assert final.record_at(4).key == 99
    final.storage.close()


def test_torn_first_record_recovers_to_empty_log(tmp_log_dir):
    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    log.append([wi_record(key=1)])
    log.flush()
    log.storage.close()
    DiskFaults.tear_log_tail(tmp_log_dir, nbytes=5)

    reopened = LogStream(SegmentedLogStorage(tmp_log_dir))
    assert reopened.next_position == 0
    assert reopened.append([wi_record(key=7)]) == 0
    reopened.flush()
    reopened.storage.close()
    final = LogStream(SegmentedLogStorage(tmp_log_dir))
    assert [r.key for r in final.reader(0)] == [7]
    final.storage.close()


def test_midfile_corruption_flagged_distinctly(tmp_log_dir):
    """A CRC failure with intact frames AFTER it is bitrot, not a torn
    append (a crash leaves at most one partial frame, at the tail). The
    suffix is still discarded — records are positionally sequential, so
    replay cannot skip past the bad one, and raft re-replicates it — but
    the distinct counter + error log tell the operator intact acked data
    was dropped, unlike the benign torn-tail path."""
    import os
    import struct

    from zeebe_tpu.runtime.metrics import event_count

    log = LogStream(SegmentedLogStorage(tmp_log_dir))
    for i in range(5):
        log.append([wi_record(key=i)])
    log.flush()
    log.storage.close()
    segments = sorted(
        n for n in os.listdir(tmp_log_dir)
        if n.startswith("segment-") and n.endswith(".log")
    )
    path = os.path.join(tmp_log_dir, segments[-1])
    with open(path, "r+b") as f:
        data = f.read()
        first_len = struct.unpack_from("<i", data, 16)[0]
        pos = 16 + first_len + 8 + 2  # inside the SECOND record's body
        f.seek(pos)
        f.write(bytes([data[pos] ^ 0xFF]))

    m0 = event_count("log_midfile_corruption")
    t0 = event_count("log_torn_tail_truncations")
    reopened = LogStream(SegmentedLogStorage(tmp_log_dir))
    assert event_count("log_midfile_corruption") - m0 == 1
    assert event_count("log_torn_tail_truncations") - t0 == 1
    # everything from the corrupt record on is discarded; appends resume
    assert reopened.next_position == 1
    assert reopened.append([wi_record(key=99)]) == 1
    reopened.storage.close()


def test_opaque_blocks_survive_reopen_unvalidated(tmp_log_dir):
    """The crc tail scan must never truncate content it cannot parse:
    raw-block users (native-format compat tests write arbitrary bytes)
    reopen with their data intact."""
    storage = SegmentedLogStorage(tmp_log_dir)
    a = storage.append(b"opaque-not-a-frame")
    storage.close()
    reopened = SegmentedLogStorage(tmp_log_dir)
    assert reopened.read(a, 18) == b"opaque-not-a-frame"
    reopened.close()
