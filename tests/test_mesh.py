"""Mesh-sharded serving plane (ISSUE 9): partition→device placement,
rebalance on leadership change, dead-device fallback, the all_to_all
frame exchange, and the hard contract — per-partition logs BIT-IDENTICAL
(frames and raw segment bytes) whether the engines are spread across the
mesh or pinned to one device. Placement is a WHERE change, never a WHAT
change."""

import itertools
import os
import tempfile
import time

import jax
import pytest

from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY, event_count
from zeebe_tpu.scheduler import PartitionFeed, WaveScheduler
from zeebe_tpu.scheduler.placement import DevicePlan, MeshExchange


# ---------------------------------------------------------------------------
# DevicePlan
# ---------------------------------------------------------------------------


class TestDevicePlan:
    def test_round_robin_assignment(self):
        plan = DevicePlan(devices=list("abcd"))
        assert [plan.assign(p) for p in range(8)] == [0, 1, 2, 3, 0, 1, 2, 3]

    def test_assignment_is_sticky(self):
        plan = DevicePlan(devices=list("abcd"))
        idx = plan.assign(7)
        for _ in range(3):
            assert plan.assign(7) == idx
        assert plan.device_for(7) == "abcd"[idx]

    def test_release_rebalances_next_install(self):
        """A leadership flap (release + assign) lands the next install on
        the emptiest device — the freed one."""
        plan = DevicePlan(devices=list("abcd"))
        for p in range(4):
            plan.assign(p)
        plan.release(2)
        assert plan.assign(99) == 2  # the freed slot is the emptiest
        # and the flapped partition itself re-places onto a least-loaded
        plan.release(0)
        assert plan.assign(0) == 0

    def test_least_loaded_wins(self):
        plan = DevicePlan(devices=list("ab"))
        assert plan.assign(0) == 0
        assert plan.assign(1) == 1
        assert plan.assign(2) == 0
        plan.release(0)
        plan.release(2)  # device 0 now empty, device 1 holds partition 1
        assert plan.assign(3) == 0

    def test_exclude_moves_partitions_to_remaining(self):
        plan = DevicePlan(devices=list("abcd"))
        for p in range(8):
            plan.assign(p)
        moves = plan.exclude(1)
        assert set(moves) == {1, 5}  # partitions that lived on device 1
        assert all(idx != 1 for idx in moves.values())
        assert all(idx != 1 for idx in plan.assignments().values())
        # new placements stay balanced over the healthy devices
        load = plan.load()
        assert load[1] == 0
        assert max(load[i] for i in (0, 2, 3)) <= 3

    def test_excluded_device_not_assigned_and_readmit(self):
        plan = DevicePlan(devices=list("ab"))
        plan.exclude(0)
        assert all(plan.assign(p) == 1 for p in range(3))
        plan.readmit(0)
        assert plan.assign(100) == 0  # emptiest again

    def test_all_excluded_raises(self):
        plan = DevicePlan(devices=list("ab"))
        plan.exclude(0)
        plan.exclude(1)
        with pytest.raises(RuntimeError, match="every device is excluded"):
            plan.assign(0)

    def test_load_gauges_published(self):
        plan = DevicePlan(devices=list("ab"))
        plan.assign(0)
        plan.assign(1)
        plan.assign(2)
        g = GLOBAL_REGISTRY.gauge("mesh_device_partitions", device="0")
        assert g.value == 2
        assert GLOBAL_REGISTRY.gauge("mesh_devices_healthy").value >= 2


# ---------------------------------------------------------------------------
# MeshExchange (the all_to_all frame hop)
# ---------------------------------------------------------------------------


class TestMeshExchange:
    def test_frames_round_trip_in_order(self):
        ex = MeshExchange(jax.devices()[:4], slots=4, frame_bytes=64)
        assert ex.queue(0, 2, 7, b"one")
        assert ex.queue(0, 2, 7, b"two")
        assert ex.queue(3, 2, 9, b"three")
        assert ex.queue(2, 0, 1, b"home")
        got = []
        delivered = ex.flush(lambda pid, frame: got.append((pid, frame)))
        assert delivered == 4
        # per destination: source-device order, then slot (queue) order
        assert got == [
            (1, b"home"),            # → device 0
            (7, b"one"), (7, b"two"),  # → device 2 from device 0
            (9, b"three"),           # → device 2 from device 3
        ]
        assert ex.pending() == 0

    def test_oversize_frame_refused_and_counted(self):
        ex = MeshExchange(jax.devices()[:2], slots=2, frame_bytes=16)
        before = event_count("mesh_exchange_fallbacks")
        assert not ex.queue(0, 1, 0, b"x" * 17)
        assert event_count("mesh_exchange_fallbacks") == before + 1

    def test_slot_overflow_refused(self):
        ex = MeshExchange(jax.devices()[:2], slots=2, frame_bytes=16)
        assert ex.queue(0, 1, 0, b"a")
        assert ex.queue(0, 1, 0, b"b")
        assert not ex.queue(0, 1, 0, b"c")  # pair budget exhausted
        assert ex.queue(1, 0, 0, b"d")  # other pairs unaffected

    def test_flush_with_nothing_queued_is_noop(self):
        ex = MeshExchange(jax.devices()[:2], slots=2, frame_bytes=16)
        assert ex.flush(lambda *_: pytest.fail("nothing to deliver")) == 0

    def test_failing_collective_still_delivers_frames(self):
        """The mesh hop is an optimization, never a durability boundary:
        when the collective raises, the round's frames (still in host
        memory) deliver directly — a dropped subscription OPEN would
        wedge its instance forever."""
        ex = MeshExchange(jax.devices()[:2], slots=4, frame_bytes=32)
        assert ex.queue(0, 1, 3, b"alpha")
        assert ex.queue(0, 1, 3, b"beta")
        assert ex.queue(1, 0, 0, b"gamma")

        def boom(*_a, **_k):
            raise RuntimeError("device lost mid-collective")

        ex._step = boom
        before = event_count("mesh_exchange_flush_failures")
        got = []
        delivered = ex.flush(lambda pid, frame: got.append((pid, frame)))
        assert delivered == 3
        # per-(src,dst) order preserved in the fallback
        assert got == [(3, b"alpha"), (3, b"beta"), (0, b"gamma")]
        assert event_count("mesh_exchange_flush_failures") > before
        assert ex.pending() == 0


# ---------------------------------------------------------------------------
# scheduler-level: shared waves span devices; flap keeps in-flight waves
# ---------------------------------------------------------------------------


class _Rec:
    __slots__ = ("position",)

    def __init__(self, position):
        self.position = position


class PlacedFeed(PartitionFeed):
    """Queue-backed pipelined feed tagged with a plan device (the shape
    PartitionServer presents to the scheduler in mesh mode)."""

    def __init__(self, pid, n, device_index, fail_dispatch=False):
        self.partition_id = pid
        self.device_index = device_index
        self.cursor = 0
        self.limit_n = n
        self.fail_dispatch = fail_dispatch
        self.dispatched = []
        self.collected = []

    def backlog(self):
        return self.limit_n - self.cursor

    def take(self, limit):
        take = min(limit, self.limit_n - self.cursor)
        if take <= 0:
            return []
        out = [_Rec(self.cursor + i) for i in range(take)]
        self.cursor += take
        return out

    def dispatch(self, records):
        if self.fail_dispatch:
            raise RuntimeError("device lost")
        self.dispatched.append(list(records))
        return list(records), 0.0, 0.0

    def collect(self, pending):
        self.collected.append(list(pending))
        return 0.0, 0.0

    def rewind(self, position):
        self.cursor = min(self.cursor, position)


class TestMeshWaves:
    def test_shared_wave_spans_devices(self):
        """One scheduling round's wave carries segments for SEVERAL
        devices — the '>1 device active per round' acceptance metric."""
        ws = WaveScheduler(wave_size=512)
        plan = DevicePlan(devices=list("abcd"))
        feeds = [PlacedFeed(p, 16, plan.assign(p)) for p in range(4)]
        for f in feeds:
            ws.register(f)
        devs_total0 = GLOBAL_REGISTRY.counter(
            "scheduler_wave_devices_total"
        ).value
        shared0 = GLOBAL_REGISTRY.counter(
            "scheduler_shared_waves_total"
        ).value
        ws.drain()
        d_shared = (
            GLOBAL_REGISTRY.counter("scheduler_shared_waves_total").value
            - shared0
        )
        mean_devices = (
            GLOBAL_REGISTRY.counter("scheduler_wave_devices_total").value
            - devs_total0
        ) / max(d_shared, 1)
        assert mean_devices > 1.0
        assert GLOBAL_REGISTRY.gauge("serving_wave_devices").value == 4
        for f in feeds:
            waves = GLOBAL_REGISTRY.counter(
                "serving_device_waves_total", device=str(f.device_index)
            )
            assert waves.value > 0

    def test_flap_rebalance_keeps_inflight_waves(self):
        """A dispatch failure mid-shared-wave (the device/leadership
        flap): the failing partition's segment REWINDS (records re-drain,
        nothing lost), every other device's in-flight segment still
        collects, and the flapped partition re-places onto the emptiest
        device."""
        ws = WaveScheduler(wave_size=64, quantum=16)
        plan = DevicePlan(devices=list("abc"))
        ok_a = PlacedFeed(0, 32, plan.assign(0))
        flappy = PlacedFeed(1, 32, plan.assign(1))
        ok_b = PlacedFeed(2, 32, plan.assign(2))
        flappy.fail_dispatch = True
        for f in (ok_a, flappy, ok_b):
            ws.register(f)
        with pytest.raises(RuntimeError, match="device lost"):
            ws.drain()
        # nothing lost: the flapped feed's cursor rewound to its segment
        # start, the other feeds' dispatched records were all collected
        assert flappy.cursor == 0
        for f in (ok_a, ok_b):
            assert sum(len(c) for c in f.collected) == sum(
                len(d) for d in f.dispatched
            )
        # leadership flap: release + re-assign lands on the emptiest
        # device (its own freed slot here)
        old = flappy.device_index
        plan.release(1)
        assert plan.assign(1) == old
        # after the flap the feed drains to completion
        flappy.fail_dispatch = False
        ws.drain()
        assert flappy.cursor == 32
        assert sum(len(c) for c in flappy.collected) == 32


# ---------------------------------------------------------------------------
# engine placement: committed state, migration, serving parity
# ---------------------------------------------------------------------------


def _mesh_workload(data_dir, devices, partitions=4, exchange=None):
    """Deterministic multi-partition device-engine workload; returns
    (per-partition frames, per-partition raw segment bytes). ``devices``
    is a list of per-partition jax devices (None = default placement)."""
    from zeebe_tpu.engine.interpreter import WorkflowRepository
    from zeebe_tpu.gateway import JobWorker, ZeebeClient
    from zeebe_tpu.gateway import workers as workers_mod
    from zeebe_tpu.models.bpmn.builder import Bpmn
    from zeebe_tpu.protocol import codec
    from zeebe_tpu.protocol.intents import WorkflowInstanceIntent
    from zeebe_tpu.protocol.records import WorkflowInstanceRecord
    from zeebe_tpu.runtime import Broker, ControlledClock
    from zeebe_tpu.tpu import TpuPartitionEngine

    workers_mod._subscriber_keys = itertools.count(1)
    clock = ControlledClock(start_ms=1_000_000)
    repo = WorkflowRepository()

    def factory(pid):
        dev = devices[pid] if devices is not None else None
        return TpuPartitionEngine(
            pid, partitions, repository=repo, clock=clock,
            device=dev, device_index=pid if dev is not None else -1,
        )

    broker = Broker(
        num_partitions=partitions, data_dir=data_dir, clock=clock,
        engine_factory=factory,
    )
    broker.wave_size = 256
    if exchange is not None:
        broker.mesh_exchange = exchange
    try:
        client = ZeebeClient(broker)
        client.deploy_model(
            Bpmn.create_process("mesh-par")
            .start_event("s")
            .service_task("w", type="mesh-par-svc")
            .end_event("e")
            .done()
        )
        JobWorker(broker, "mesh-par-svc", lambda ctx: {"ok": True})
        for burst in range(2):
            for i in range(4 * partitions):
                broker.write_command(
                    i % partitions,
                    WorkflowInstanceRecord(
                        bpmn_process_id="mesh-par",
                        payload={"b": burst, "i": i},
                    ),
                    WorkflowInstanceIntent.CREATE,
                )
            broker.run_until_idle()
        frames = [
            [codec.encode_record(r) for r in broker.records(pid)]
            for pid in range(partitions)
        ]
    finally:
        broker.close()
    raw = []
    for pid in range(partitions):
        pdir = os.path.join(data_dir, f"partition-{pid}")
        blobs = []
        for name in sorted(os.listdir(pdir)):
            if name.startswith("segment-") and name.endswith(".log"):
                with open(os.path.join(pdir, name), "rb") as f:
                    blobs.append(f.read())
        raw.append(blobs)
    return frames, raw


class TestEnginePlacement:
    def test_state_commits_to_the_assigned_device(self):
        from zeebe_tpu.tpu import TpuPartitionEngine

        dev = jax.devices()[3]
        engine = TpuPartitionEngine(0, 1, device=dev, device_index=3)
        assert engine.state.ei_i32.devices() == {dev}
        assert engine.device_index == 3

    def test_place_on_migrates_live_state(self):
        """Dead-device fallback at the engine level: a served engine moves
        to another device mid-life and keeps serving with its state
        intact."""
        from zeebe_tpu.engine.interpreter import WorkflowRepository
        from zeebe_tpu.gateway import JobWorker, ZeebeClient
        from zeebe_tpu.gateway import workers as workers_mod
        from zeebe_tpu.models.bpmn.builder import Bpmn
        from zeebe_tpu.runtime import Broker, ControlledClock
        from zeebe_tpu.tpu import TpuPartitionEngine

        workers_mod._subscriber_keys = itertools.count(1)
        clock = ControlledClock(start_ms=1_000_000)
        repo = WorkflowRepository()
        devs = jax.devices()
        engine_box = []

        def factory(pid):
            engine = TpuPartitionEngine(
                pid, 1, repository=repo, clock=clock,
                device=devs[1], device_index=1,
            )
            engine_box.append(engine)
            return engine

        with tempfile.TemporaryDirectory() as data_dir:
            broker = Broker(
                num_partitions=1, data_dir=data_dir, clock=clock,
                engine_factory=factory,
            )
            try:
                client = ZeebeClient(broker)
                client.deploy_model(
                    Bpmn.create_process("mig")
                    .start_event("s")
                    .service_task("w", type="mig-svc")
                    .end_event("e")
                    .done()
                )
                done = []
                JobWorker(broker, "mig-svc", lambda ctx: done.append(1) or {})
                client.create_instance("mig", {"i": 0})
                broker.run_until_idle()
                assert len(done) == 1
                # device 1 died: fall back to device 2 with live state
                engine = engine_box[0]
                engine.place_on(devs[2], 2)
                assert engine.state.ei_i32.devices() == {devs[2]}
                client.create_instance("mig", {"i": 1})
                broker.run_until_idle()
                assert len(done) == 2
            finally:
                broker.close()

    def test_mesh_vs_single_device_logs_bit_identical(self, tmp_path):
        """THE parity pin: frames AND raw on-disk segment bytes are
        identical whether partitions spread over the mesh or share the
        default device."""
        devs = jax.devices()[:4]
        frames_mesh, raw_mesh = _mesh_workload(
            str(tmp_path / "m"), list(devs)
        )
        frames_single, raw_single = _mesh_workload(str(tmp_path / "s"), None)
        assert sum(len(f) for f in frames_mesh) > 100
        for pid, (a, b) in enumerate(zip(frames_mesh, frames_single)):
            assert a == b, f"partition {pid} frames diverged under mesh"
        for pid, (a, b) in enumerate(zip(raw_mesh, raw_single)):
            assert a and a == b, f"partition {pid} raw bytes diverged"


# ---------------------------------------------------------------------------
# exchange-routed correlation: same log bytes as the direct hop
# ---------------------------------------------------------------------------


def _correlation_workload(data_dir, exchange):
    from zeebe_tpu.engine.interpreter import WorkflowRepository
    from zeebe_tpu.gateway import ZeebeClient
    from zeebe_tpu.gateway import workers as workers_mod
    from zeebe_tpu.models.bpmn.builder import Bpmn
    from zeebe_tpu.protocol import codec
    from zeebe_tpu.runtime import Broker, ControlledClock
    from zeebe_tpu.tpu import TpuPartitionEngine

    workers_mod._subscriber_keys = itertools.count(1)
    clock = ControlledClock(start_ms=1_000_000)
    repo = WorkflowRepository()
    devs = jax.devices()

    def factory(pid):
        return TpuPartitionEngine(
            pid, 2, repository=repo, clock=clock,
            device=devs[pid], device_index=pid,
        )

    broker = Broker(
        num_partitions=2, data_dir=data_dir, clock=clock,
        engine_factory=factory,
    )
    if exchange:
        broker.mesh_exchange = MeshExchange(
            devs[:2], slots=8, frame_bytes=2048
        )
    try:
        client = ZeebeClient(broker)
        client.deploy_model(
            Bpmn.create_process("xcorr")
            .start_event("s")
            .receive_task("wait", message_name="paid",
                          correlation_key="$.oid")
            .end_event("e")
            .done()
        )
        for i in range(6):
            # the key "k-i" hashes to partition i % 2 — creating the
            # instance on the OTHER partition forces every subscription
            # OPEN/CORRELATE across partitions (and across devices)
            client.create_instance(
                "xcorr", {"oid": f"k-{i}"}, partition_id=(i + 1) % 2
            )
        broker.run_until_idle()
        for i in range(6):
            client.publish_message("paid", f"k-{i}")
        broker.run_until_idle()
        return [
            [codec.encode_record(r) for r in broker.records(pid)]
            for pid in range(2)
        ]
    finally:
        broker.close()


class TestExchangeRouting:
    def test_exchange_routed_correlation_bit_identical(self, tmp_path):
        """Cross-partition subscription commands riding the all_to_all
        frame exchange produce EXACTLY the logs the direct (transport-
        analog) hop produces — the frames ARE the wire bytes — and the
        mesh counter proves they actually rode the mesh."""
        before = event_count("mesh_exchange_frames")
        frames_x = _correlation_workload(str(tmp_path / "x"), True)
        rode_mesh = event_count("mesh_exchange_frames") - before
        frames_d = _correlation_workload(str(tmp_path / "d"), False)
        assert rode_mesh > 0, "no frames rode the mesh exchange"
        for pid, (a, b) in enumerate(zip(frames_x, frames_d)):
            assert a == b, f"partition {pid} diverged (exchange vs direct)"


# ---------------------------------------------------------------------------
# cluster broker: plan wiring, leadership flap, dead-device fallback
# ---------------------------------------------------------------------------


def _boot_mesh_cluster(tmp_path, partitions=2):
    from zeebe_tpu.runtime.cluster_broker import ClusterBroker
    from zeebe_tpu.runtime.config import BrokerCfg
    from zeebe_tpu.runtime.engines import engine_factory_from_config

    cfg = BrokerCfg()
    cfg.network.client_port = 0
    cfg.network.management_port = 0
    cfg.network.subscription_port = 0
    cfg.metrics.port = 0
    cfg.metrics.enabled = False
    cfg.cluster.partitions = partitions
    cfg.engine.type = "tpu"
    cfg.engine.capacity = 1 << 10
    broker = ClusterBroker(
        cfg, os.path.join(str(tmp_path), "b0"),
        engine_factory=engine_factory_from_config(cfg),
    )
    for pid in range(partitions):
        broker.open_partition(pid).join(60)
        broker.bootstrap_partition(pid, {})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not all(
        broker.partitions[pid].is_leader for pid in range(partitions)
    ):
        time.sleep(0.02)
    assert all(
        broker.partitions[pid].is_leader for pid in range(partitions)
    )
    return broker


@pytest.mark.slow
class TestClusterMesh:
    """Device-engine cluster legs (slow tier with the other TPU cluster
    suites: per-device kernel compiles dominate on the CPU container)."""

    def test_partitions_placed_across_devices_and_flap_rebalances(
        self, tmp_path
    ):
        from zeebe_tpu.gateway.cluster_client import ClusterClient
        from zeebe_tpu.models.bpmn.builder import Bpmn

        broker = _boot_mesh_cluster(tmp_path, partitions=2)
        client = None
        try:
            plan = broker.device_plan
            assert plan is not None
            placed = plan.assignments()
            assert len(placed) == 2
            assert placed[0] != placed[1], "partitions share a device"
            client = ClusterClient(
                [broker.client_address], num_partitions=2,
                request_timeout_ms=120_000,
            )
            client.deploy_model(
                Bpmn.create_process("cm").start_event("s").end_event("e")
                .done()
            )
            for pid in (0, 1):
                rsp = client.create_instance("cm", partition_id=pid)
                assert rsp.value.workflow_instance_key > 0

            # leadership flap on partition 1: uninstall + reinstall (raft
            # stays leader; the serving install re-runs) — the plan frees
            # the slot and re-places, and serving continues with no
            # records lost
            server = broker.partitions[1]
            term = server.raft.term
            broker.actor.call(server._uninstall_leader).join(10)
            assert plan.device_index(1) == -1
            broker.actor.call(lambda: server._install_leader(term)).join(60)
            assert plan.device_index(1) >= 0
            rsp = client.create_instance("cm", partition_id=1)
            assert rsp.value.workflow_instance_key > 0
        finally:
            if client is not None:
                client.close()
            broker.close()

    def test_excluded_device_falls_back_to_remaining(self, tmp_path):
        from zeebe_tpu.gateway.cluster_client import ClusterClient
        from zeebe_tpu.models.bpmn.builder import Bpmn

        broker = _boot_mesh_cluster(tmp_path, partitions=2)
        client = None
        try:
            plan = broker.device_plan
            victim = plan.device_index(0)
            client = ClusterClient(
                [broker.client_address], num_partitions=2,
                request_timeout_ms=120_000,
            )
            client.deploy_model(
                Bpmn.create_process("cx").start_event("s").end_event("e")
                .done()
            )
            client.create_instance("cx", partition_id=0)
            moves = broker.exclude_device(victim).join(60)
            assert moves.get(0, victim) != victim
            new_idx = plan.device_index(0)
            assert new_idx >= 0 and new_idx != victim
            engine = broker.partitions[0].engine
            assert engine.state.ei_i32.devices() == {
                plan.devices[new_idx]
            }
            # the partition keeps serving from the fallback device
            rsp = client.create_instance("cx", partition_id=0)
            assert rsp.value.workflow_instance_key > 0
        finally:
            if client is not None:
                client.close()
            broker.close()
