"""JSONPath tokenizer / compiled queries / msgpack traverser.

Reference parity: ``json-path/src/test/`` — the compiler test suite
(token positions in errors), query evaluation over documents, and the
msgpack traverser that skips non-matching subtrees
(``MsgPackTraverser``)."""

import pytest

from zeebe_tpu.protocol import msgpack
from zeebe_tpu.protocol.jsonpath import (
    JsonPathError,
    TokenKind,
    compile_query,
    tokenize,
    traverse,
)


DOC = {
    "order": {
        "id": "o-1",
        "items": [
            {"sku": "a", "qty": 2, "price": 10.5},
            {"sku": "b", "qty": 1, "price": 99.0},
        ],
        "totals": {"net": 120.0, "tax": 20.0},
    },
    "tags": ["x", "y"],
    "n": 5,
}


class TestTokenizer:
    def test_token_kinds_and_positions(self):
        tokens = tokenize("$.order.items[0]['sku']")
        kinds = [t.kind for t in tokens]
        assert kinds == [
            TokenKind.ROOT, TokenKind.NAME, TokenKind.NAME,
            TokenKind.INDEX, TokenKind.NAME,
        ]
        assert [t.value for t in tokens[1:]] == ["order", "items", 0, "sku"]
        assert tokens[1].position == 2

    def test_wildcards(self):
        assert [t.kind for t in tokenize("$.items[*]")][-1] == TokenKind.WILDCARD
        assert [t.kind for t in tokenize("$.*")][-1] == TokenKind.WILDCARD

    @pytest.mark.parametrize("bad", [
        "order.id", "$.", "$.a[", "$.a['x", "$.a[1x]", "$.a[*", "$x",
    ])
    def test_errors_carry_position(self, bad):
        with pytest.raises(JsonPathError):
            tokenize(bad)


class TestQueries:
    @pytest.mark.parametrize("path,expected", [
        ("$", DOC),
        ("$.n", 5),
        ("$.order.id", "o-1"),
        ("$.order.items[0].sku", "a"),
        ("$.order.items[1]['price']", 99.0),
        ("$.order.totals.tax", 20.0),
        ("$.tags[-1]", "y"),
    ])
    def test_single_match(self, path, expected):
        found, value = compile_query(path).evaluate_one(DOC)
        assert found and value == expected

    @pytest.mark.parametrize("path", ["$.nope", "$.order.items[9]", "$.n.x"])
    def test_miss(self, path):
        found, _ = compile_query(path).evaluate_one(DOC)
        assert not found

    def test_wildcard_fanout(self):
        assert compile_query("$.order.items[*].sku").evaluate(DOC) == ["a", "b"]
        assert sorted(compile_query("$.order.totals.*").evaluate(DOC)) == [20.0, 120.0]

    def test_wildcard_over_array_then_filter_by_field(self):
        assert compile_query("$.order.items[*].qty").evaluate(DOC) == [2, 1]


class TestMsgpackTraverser:
    @pytest.mark.parametrize("path", [
        "$", "$.n", "$.order.id", "$.order.items[0].sku",
        "$.order.items[1]['price']", "$.order.totals.tax",
        "$.order.items[*].sku", "$.nope", "$.order.items[9]",
    ])
    def test_matches_document_evaluation(self, path):
        packed = msgpack.pack(DOC)
        query = compile_query(path)
        t_found, t_value = traverse(packed, query)
        d_found, d_value = query.evaluate_one(DOC)
        assert t_found == d_found
        if d_found:
            assert t_value == d_value

    def test_traverses_without_decoding_siblings(self):
        # a huge sibling subtree the query never touches: the traverser
        # must skip it structurally (this is the MsgPackTraverser point);
        # correctness check — the value comes back right even when the
        # sibling dwarfs the match
        doc = {"big": {"blob": "x" * 100_000, "list": list(range(1000))},
               "small": {"v": 7}}
        packed = msgpack.pack(doc)
        found, value = traverse(packed, compile_query("$.small.v"))
        assert found and value == 7

    def test_correlation_key_extraction_shape(self):
        # the engine's hot use: extract a correlation key from a packed
        # payload (SubscribeMessageHandler semantics)
        packed = msgpack.pack({"oid": "o-77", "rest": [1, 2, 3]})
        found, value = traverse(packed, compile_query("$.oid"))
        assert found and value == "o-77"
