"""Mesh-sharded partition state (ISSUE 19): one partition's
instance/job/timer/message tables block-shard over a mesh span, the step
gathers them per wave and keeps local row blocks on write — and the hard
contract is the same as mesh placement (test_mesh.py): sharding is a
WHERE change, never a WHAT change. Logs (frames AND raw segment bytes)
are bit-identical to the single-device engine, key-hash routing is
deterministic and host/device-agreed, snapshots round-trip across shard
counts, and a fixed-seed crash-stop replays to the identical log."""

import dataclasses
import itertools
import os
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI
from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY, event_count
from zeebe_tpu.scheduler import PartitionFeed, WaveScheduler
from zeebe_tpu.scheduler.placement import DevicePlan
from zeebe_tpu.tpu import shard
from zeebe_tpu.tpu import state as state_mod

SEED = 0x5A4DED


# ---------------------------------------------------------------------------
# key-hash routing: deterministic, host == device
# ---------------------------------------------------------------------------


def _key_corpus():
    rng = np.random.default_rng(SEED)
    keys = np.concatenate([
        np.arange(0, 256, dtype=np.int64),
        rng.integers(1, 1 << 62, size=256, dtype=np.int64),
        np.array([0, 1, (1 << 62) - 1, np.iinfo(np.int64).max], np.int64),
    ])
    return keys


class TestKeyHashRouting:
    def test_host_and_device_hash_agree(self):
        """shard_of_key (device) and shard_of_key_host (wave staging) are
        the same function — the routing plane has ONE hash."""
        keys = _key_corpus()
        for ns in (2, 3, 4, 8):
            dev = np.asarray(shard.shard_of_key(jnp.asarray(keys), ns))
            host = shard.shard_of_key_host(keys, ns)
            np.testing.assert_array_equal(dev, host)
            assert host.min() >= 0 and host.max() < ns

    def test_routing_is_deterministic_and_key_only(self):
        """Same key → same shard, independent of position in the wave or
        of any other key in it."""
        keys = _key_corpus()
        a = shard.shard_of_key_host(keys, 8)
        b = shard.shard_of_key_host(keys, 8)
        np.testing.assert_array_equal(a, b)
        perm = np.random.default_rng(SEED + 1).permutation(len(keys))
        np.testing.assert_array_equal(
            shard.shard_of_key_host(keys[perm], 8), a[perm]
        )

    def test_row_counts_match_host_and_respect_valid(self):
        keys = _key_corpus()
        valid = np.random.default_rng(SEED + 2).random(len(keys)) < 0.7
        for ns in (2, 8):
            dev = np.asarray(
                shard.shard_row_counts(jnp.asarray(keys), jnp.asarray(valid), ns)
            )
            host = shard.shard_row_counts_host(keys, valid, ns)
            np.testing.assert_array_equal(dev, host)
            assert host.sum() == valid.sum()

    def test_hash_spreads_sequential_keys(self):
        """Entity keys are near-sequential (per-partition counters); the
        Fibonacci hash must still spread them instead of striping."""
        counts = shard.shard_row_counts_host(
            np.arange(1, 4097, dtype=np.int64), np.ones(4096, bool), 8
        )
        assert counts.min() > 0
        assert counts.max() < 2 * counts.mean()


# ---------------------------------------------------------------------------
# spec tree + exchange model
# ---------------------------------------------------------------------------


class TestStateShardingSpecs:
    def _state(self):
        return state_mod.make_state(
            capacity=256, num_vars=8, job_capacity=256, sub_capacity=8
        )

    def _zipped(self, state, ns):
        specs = shard.state_partition_specs(state, ns)
        leaves = jax.tree_util.tree_flatten_with_path(state)[0]
        spec_leaves = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        assert len(leaves) == len(spec_leaves)
        return [
            (jax.tree_util.keystr(path), leaf, s)
            for (path, leaf), s in zip(leaves, spec_leaves)
        ]

    def test_row_tables_shard_and_scalars_replicate(self):
        state = self._state()
        sharded = {
            name for name, _, s in self._zipped(state, 8)
            if tuple(s) == (shard.STATE_AXIS,)
        }
        # the big row-table families are sharded...
        for fam in ("ei_i32", "job_i32", "timer_key", "ei_pay"):
            assert any(fam in n for n in sharded), f"{fam} not sharded"
        # ...and every scalar/rank-0 leaf stays replicated
        for name, leaf, s in self._zipped(state, 8):
            if np.ndim(leaf) == 0:
                assert tuple(s) == (), f"scalar {name} got spec {s}"

    def test_sharded_leaves_divide_evenly(self):
        state = self._state()
        for name, leaf, s in self._zipped(state, 8):
            if tuple(s) == (shard.STATE_AXIS,):
                assert leaf.shape[0] % 8 == 0, name

    def test_non_divisible_tables_fall_back_replicated(self):
        """num_shards that doesn't divide a table's rows must NOT shard it
        (correctness never depends on which leaves shard)."""
        state = self._state()
        for name, leaf, s in self._zipped(state, 7):
            if tuple(s) == (shard.STATE_AXIS,):
                assert leaf.shape[0] % 7 == 0, name

    def test_exchange_bytes_scale_with_span(self):
        """One wave's gather volume is sharded_bytes * (D-1): zero on a
        single device, linear in the span beyond it."""
        state = self._state()
        assert shard.state_exchange_bytes(state, 1) == 0
        eb2 = shard.state_exchange_bytes(state, 2)
        eb8 = shard.state_exchange_bytes(state, 8)
        assert eb2 > 0
        assert eb8 == 7 * eb2


# ---------------------------------------------------------------------------
# DevicePlan spans
# ---------------------------------------------------------------------------


class TestDevicePlanSpans:
    def test_span_assignment_sticky_and_sorted(self):
        plan = DevicePlan(devices=list("abcdefgh"))
        got = plan.assign_span(0, 4)
        assert got == sorted(got) and len(got) == 4
        assert plan.assign_span(0, 4) == got  # sticky
        assert plan.device_indices(0) == got
        assert plan.devices_for(0) == [plan.devices[i] for i in got]
        assert plan.device_index(0) == got[0]  # primary

    def test_spans_balance_across_the_mesh(self):
        plan = DevicePlan(devices=list("abcdefgh"))
        s0 = plan.assign_span(0, 4)
        s1 = plan.assign_span(1, 4)
        assert not set(s0) & set(s1), "second span landed on loaded devices"
        load = plan.load()
        assert all(load[i] == 1 for i in range(8))

    def test_span_of_one_degenerates_to_assign(self):
        plan = DevicePlan(devices=list("ab"))
        assert plan.assign_span(3, 1) == [plan.device_index(3)]
        assert plan.device_indices(3) == [plan.device_index(3)]

    def test_release_frees_the_whole_span(self):
        plan = DevicePlan(devices=list("abcd"))
        plan.assign_span(0, 4)
        plan.release(0)
        assert plan.device_indices(0) == []
        assert all(v == 0 for v in plan.load().values())

    def test_exclude_respans_sharded_victims(self):
        plan = DevicePlan(devices=list("abcdefgh"))
        span = plan.assign_span(0, 4)
        victim = span[1]
        moves = plan.exclude(victim)
        assert 0 in moves
        new_span = plan.device_indices(0)
        assert len(new_span) == 4
        assert victim not in new_span
        assert moves[0] == new_span[0]

    def test_span_larger_than_healthy_mesh_raises(self):
        plan = DevicePlan(devices=list("ab"))
        plan.exclude(0)
        with pytest.raises(RuntimeError, match="exceeds the 1 healthy"):
            plan.assign_span(0, 2)


# ---------------------------------------------------------------------------
# scheduler: a sharded segment's wave counts its WHOLE span active
# ---------------------------------------------------------------------------


class _Rec:
    __slots__ = ("position",)

    def __init__(self, position):
        self.position = position


class _SpanFeed(PartitionFeed):
    def __init__(self, pid, n, span):
        self.partition_id = pid
        self.device_index = span[0]
        self.device_indices = tuple(span)
        self.cursor = 0
        self.limit_n = n

    def backlog(self):
        return self.limit_n - self.cursor

    def take(self, limit):
        take = min(limit, self.limit_n - self.cursor)
        out = [_Rec(self.cursor + i) for i in range(take)]
        self.cursor += take
        return out

    def dispatch(self, records):
        return list(records), 0.0, 0.0

    def collect(self, pending):
        return 0.0, 0.0

    def rewind(self, position):
        self.cursor = min(self.cursor, position)


class TestSchedulerSpanAccounting:
    def test_wave_devices_gauge_counts_the_span(self):
        ws = WaveScheduler(wave_size=64)
        ws.register(_SpanFeed(0, 16, (0, 2, 5)))
        ws.drain()
        assert GLOBAL_REGISTRY.gauge("serving_wave_devices").value == 3


# ---------------------------------------------------------------------------
# engine guards
# ---------------------------------------------------------------------------


class TestShardedEngineGuards:
    def test_pinned_device_conflicts_with_sharding(self):
        from zeebe_tpu.tpu import TpuPartitionEngine

        with pytest.raises(ValueError, match="cannot also be pinned"):
            TpuPartitionEngine(
                0, 1, state_shards=2, device=jax.devices()[0], device_index=0
            )

    def test_span_larger_than_devices_raises(self):
        from zeebe_tpu.tpu import TpuPartitionEngine

        with pytest.raises(ValueError, match="needs that many devices"):
            TpuPartitionEngine(0, 1, state_shards=64)

    def test_sharded_engine_refuses_live_migration(self):
        """place_on is the single-device fallback path; a sharded engine
        is pinned to its span and rebuilds via snapshot → restore."""
        from zeebe_tpu.tpu import TpuPartitionEngine

        engine = TpuPartitionEngine(0, 1, capacity=256, state_shards=2)
        assert engine.device_indices == [0, 1]
        assert engine._shard_exchange_bytes > 0
        with pytest.raises(RuntimeError, match="pinned to its mesh span"):
            engine.place_on(jax.devices()[0], 0)


# ---------------------------------------------------------------------------
# serving parity: sharded tables, identical logs
# ---------------------------------------------------------------------------


def _sharded_workload(data_dir, state_shards, engine_box=None, **engine_kw):
    """Single-partition device-engine workload (service task + timer —
    instance, job AND timer tables all see traffic); returns
    (frames, raw segment bytes). ``engine_kw`` forwards to the engine
    ctor (``routing="resident"``, ``routed_lane_slots=...``)."""
    from zeebe_tpu.engine.interpreter import WorkflowRepository
    from zeebe_tpu.gateway import JobWorker, ZeebeClient
    from zeebe_tpu.gateway import workers as workers_mod
    from zeebe_tpu.models.bpmn.builder import Bpmn
    from zeebe_tpu.protocol import codec
    from zeebe_tpu.protocol.intents import WorkflowInstanceIntent
    from zeebe_tpu.protocol.records import WorkflowInstanceRecord
    from zeebe_tpu.runtime import Broker, ControlledClock
    from zeebe_tpu.tpu import TpuPartitionEngine

    workers_mod._subscriber_keys = itertools.count(1)
    clock = ControlledClock(start_ms=1_000_000)
    repo = WorkflowRepository()

    def factory(pid):
        engine = TpuPartitionEngine(
            pid, 1, repository=repo, clock=clock, capacity=1 << 10,
            state_shards=state_shards, **engine_kw,
        )
        if engine_box is not None:
            engine_box.append(engine)
        return engine

    broker = Broker(
        num_partitions=1, data_dir=data_dir, clock=clock,
        engine_factory=factory,
    )
    broker.wave_size = 128
    try:
        client = ZeebeClient(broker)
        client.deploy_model(
            Bpmn.create_process("shst")
            .start_event("s")
            .service_task("w", type="shst-svc")
            .timer_catch_event("cool", duration_ms=5_000)
            .end_event("e")
            .done()
        )
        JobWorker(broker, "shst-svc", lambda ctx: {"ok": True})
        for burst in range(2):
            for i in range(16):
                broker.write_command(
                    0,
                    WorkflowInstanceRecord(
                        bpmn_process_id="shst", payload={"b": burst, "i": i}
                    ),
                    WorkflowInstanceIntent.CREATE,
                )
            broker.run_until_idle()
            clock.advance(10_000)
            broker.tick()
            broker.run_until_idle()
        frames = [codec.encode_record(r) for r in broker.records(0)]
    finally:
        broker.close()
    blobs = []
    pdir = os.path.join(data_dir, "partition-0")
    for name in sorted(os.listdir(pdir)):
        if name.startswith("segment-") and name.endswith(".log"):
            with open(os.path.join(pdir, name), "rb") as f:
                blobs.append(f.read())
    return frames, blobs


@pytest.fixture(scope="module")
def single_device_baseline(tmp_path_factory):
    """The single-device drain of THE workload, run once per module: the
    deterministic oracle every parity test compares against (same seeds,
    same clock schedule — bit-identical across runs by construction, so
    sharing it is sound and saves three full drains of tier-1 wall)."""
    return _sharded_workload(str(tmp_path_factory.mktemp("un")), 1)


class TestShardedServingParity:
    def test_sharded_vs_single_device_logs_bit_identical(
        self, tmp_path, single_device_baseline
    ):
        """THE parity pin (acceptance): frames AND raw on-disk segment
        bytes identical with the tables sharded over all 8 devices — and
        the waves actually rode the sharded step (metrics prove it)."""
        waves0 = GLOBAL_REGISTRY.counter("serving_sharded_waves_total").value
        bytes0 = GLOBAL_REGISTRY.counter("mesh_shard_exchange_bytes_total").value
        box = []
        frames_sh, raw_sh = _sharded_workload(
            str(tmp_path / "sh"), 8, engine_box=box
        )
        d_waves = (
            GLOBAL_REGISTRY.counter("serving_sharded_waves_total").value - waves0
        )
        d_bytes = (
            GLOBAL_REGISTRY.counter("mesh_shard_exchange_bytes_total").value
            - bytes0
        )
        frames_un, raw_un = single_device_baseline
        assert len(frames_sh) > 100
        assert frames_sh == frames_un, "frames diverged under sharding"
        assert raw_sh and raw_sh == raw_un, "raw segment bytes diverged"
        # the sharded run really ran sharded
        engine = box[0]
        assert engine.device_indices == list(range(8))
        assert engine.sharded_waves > 0
        assert d_waves >= engine.sharded_waves
        assert d_bytes >= engine.sharded_waves * engine._shard_exchange_bytes
        # per-shard routing gauges populated for the whole span
        for d in range(8):
            assert (
                GLOBAL_REGISTRY.gauge("mesh_shard_rows", device=str(d)).value
                >= 0
            )


# ---------------------------------------------------------------------------
# sharded-state v2 (ISSUE 20): residency-routed staging
# ---------------------------------------------------------------------------


class TestRoutedServingParity:
    """Resident routing is a HOW change, never a WHAT change: the routed
    lane program, the overflow fallback, and the v1 gathered step must
    all drain the same workload to bit-identical logs."""

    def _routed_run(self, data_dir, shards, **kw):
        box = []
        frames, raw = _sharded_workload(
            data_dir, shards, engine_box=box, routing="resident", **kw
        )
        return frames, raw, box[0]

    def test_routed_vs_single_device_logs_bit_identical(
        self, tmp_path, monkeypatch, single_device_baseline
    ):
        """THE v2 parity pin (acceptance): 8-shard resident routing vs
        the single-device engine, frames AND raw segment bytes — the
        routed lane program actually carried waves, every routed wave's
        staged split landed on ONE lane (flagged single-lane for the
        skew gauge), and every residency entry sits on the
        host/device-agreed hash shard of its instance key (shard_of_key
        parity ON the routed staging plane)."""
        from zeebe_tpu.runtime import metrics as metrics_mod

        observed = []
        real = metrics_mod.observe_sharded_wave

        def spy(split, xb, single_lane=False):
            observed.append((list(int(x) for x in split), single_lane))
            real(split, xb, single_lane=single_lane)

        monkeypatch.setattr(metrics_mod, "observe_sharded_wave", spy)
        frames_rt, raw_rt, engine = self._routed_run(
            str(tmp_path / "rt"), 8
        )
        resident = dict(engine._resident)
        frames_un, raw_un = single_device_baseline
        assert len(frames_rt) > 100
        assert frames_rt == frames_un, "frames diverged under routing"
        assert raw_rt and raw_rt == raw_un, "raw segment bytes diverged"
        assert engine.routing == "resident"
        assert engine.routed_waves > 0, "no wave took the routed program"
        assert engine.routed_overflows == 0, (
            "default lanes overflowed on a 32-instance workload"
        )
        # completed instances demote; re-learned entries may remain from
        # in-flight timers — either way the invariant holds for all
        for ik, owner in resident.items():
            assert owner == shard.shard_of_key_host(ik, 8), ik
        if resident:
            keys = np.fromiter(resident, dtype=np.int64)
            np.testing.assert_array_equal(
                np.asarray(shard.shard_of_key(jnp.asarray(keys), 8)),
                np.asarray([resident[int(k)] for k in keys]),
            )
        routed = [s for s, single in observed if single and sum(s)]
        assert len(routed) == engine.routed_waves > 0
        for fill in routed:
            assert len(fill) == 8
            assert sum(1 for v in fill if v) == 1, fill

    @pytest.mark.slow
    def test_routed_vs_gathered_bit_identity_small_spans(self, tmp_path):
        """Routed-vs-gathered across the remaining shard counts (8 is
        pinned above against single-device, which gathered parity
        already equals; slow tier with the other heavy parity legs)."""
        for shards in (2, 4):
            frames_rt, raw_rt, engine = self._routed_run(
                str(tmp_path / f"rt{shards}"), shards
            )
            frames_g, raw_g = _sharded_workload(
                str(tmp_path / f"g{shards}"), shards
            )
            assert engine.routed_waves > 0
            assert frames_rt == frames_g, f"{shards}-shard logs diverged"
            assert raw_rt == raw_g, f"{shards}-shard raw bytes diverged"

    def test_undersized_lanes_overflow_to_fallback_losslessly(
        self, tmp_path, monkeypatch, single_device_baseline
    ):
        """Overflow-fallback parity: 2-slot lanes force every multi-row
        wave through the gathered fallback — counted, demoted from
        residency, and STILL bit-identical. Any wave that DOES route
        lands on exactly one lane; fallback waves keep the advisory
        key-hash split (never flagged single-lane)."""
        from zeebe_tpu.runtime import metrics as metrics_mod

        observed = []
        real = metrics_mod.observe_sharded_wave

        def spy(split, xb, single_lane=False):
            observed.append((list(int(x) for x in split), single_lane))
            real(split, xb, single_lane=single_lane)

        monkeypatch.setattr(metrics_mod, "observe_sharded_wave", spy)
        frames_rt, raw_rt, engine = self._routed_run(
            str(tmp_path / "rt"), 4, routed_lane_slots=2
        )
        frames_un, raw_un = single_device_baseline
        assert frames_rt == frames_un, "overflow fallback diverged"
        assert raw_rt == raw_un
        assert engine.routed_overflows > 0, "lanes never overflowed"
        assert engine.fallback_waves > 0, "overflow never took fallback"
        routed = [s for s, single in observed if single and sum(s)]
        assert len(routed) == engine.routed_waves
        for fill in routed:
            assert sum(1 for v in fill if v) == 1, fill
        fallbacks = [s for s, single in observed if not single and sum(s)]
        assert len(fallbacks) == engine.fallback_waves > 0

    def test_message_graphs_refuse_routing(self, tmp_path):
        """Message-correlation state is cross-instance by nature; a
        resident engine serving a message graph routes NOTHING (all
        waves fall back) and stays bit-identical — pinned by the slow
        correlation suite; here we pin the guard itself."""
        from zeebe_tpu.tpu import TpuPartitionEngine

        engine = TpuPartitionEngine(
            0, 1, capacity=256, state_shards=2, routing="resident"
        )
        assert engine._routing_active() is False  # no graph yet

    def test_unknown_routing_mode_raises(self):
        from zeebe_tpu.tpu import TpuPartitionEngine

        with pytest.raises(ValueError, match="routing"):
            TpuPartitionEngine(
                0, 1, capacity=256, state_shards=2, routing="telepathic"
            )


def _emission_stub(instance_keys, vtypes=None, intents=None, keys=None):
    """Minimal emission-batch stand-in for residency bookkeeping tests:
    just the columns _note_residency / _pop_residency_fallback read."""
    import types

    n = len(instance_keys)
    return types.SimpleNamespace(
        valid=np.ones(n, bool),
        instance_key=np.asarray(instance_keys, np.int64),
        vtype=np.asarray(vtypes if vtypes is not None else [0] * n, np.int32),
        intent=np.asarray(
            intents if intents is not None else [0] * n, np.int32
        ),
        key=np.asarray(keys if keys is not None else [-1] * n, np.int64),
    )


class TestResidencyInvalidation:
    """The residency map must never trust stale knowledge. A gathered
    fallback allocates at GLOBAL free slots, so (a) its collect retires
    every instance its EMISSIONS name — including the ones whose key the
    host could not prove at dispatch, exactly the rows that forced the
    fallback — (b) a routed segment dispatched BEFORE the pop cannot
    note such a key back in when its pipelined collect runs later, and
    (c) while a fallback with host-unprovable rows is in flight, routing
    holds off entirely (any entry might be stale until the emissions
    resolve the keys)."""

    def _engine(self):
        import types

        from zeebe_tpu.tpu import TpuPartitionEngine

        engine = TpuPartitionEngine(
            0, 1, capacity=256, state_shards=2, routing="resident"
        )
        engine.graph = types.SimpleNamespace(has_messages=False)
        assert engine._routing_active()
        return engine

    def test_fallback_collect_retires_emission_instances(self):
        engine = self._engine()
        engine._resident = {11: 1, 22: 0}
        engine._pop_residency_fallback(_emission_stub([11, 11, -1]), seq=7)
        assert engine._resident == {22: 0}
        assert engine._residency_invalid[11] == 7

    def test_stale_note_cannot_reinstate_popped_residency(self):
        engine = self._engine()
        o = _emission_stub([33], vtypes=[int(ValueType.JOB)], keys=[99])
        engine._residency_invalid = {33: 5}
        # dispatched before the fallback that invalidated at seq 5:
        # its collect arrives late (pipelining) and must be ignored
        engine._note_residency(o, owner=1, seq=4)
        assert 33 not in engine._resident
        # a segment dispatched AFTER the invalidation carries newer
        # knowledge and may note again
        engine._note_residency(o, owner=1, seq=6)
        assert engine._resident[33] == 1

    def test_blind_fallback_inflight_gates_routing(self):
        import types

        engine = self._engine()
        engine._resident = {44: 1}
        entry = types.SimpleNamespace(
            value=types.SimpleNamespace(
                headers=types.SimpleNamespace(workflow_instance_key=44)
            )
        )
        args = (entry, False, int(ValueType.JOB), int(RecordType.COMMAND), 0)
        assert engine._wave_route_class(*args) == ("ik", 1)
        engine._blind_fb_inflight = 1
        assert engine._wave_route_class(*args) == ("fb",)
        # CREATEs stay routable through the gate: their root key is
        # freshly allocated, so no residency entry can be stale for them
        create = (
            None, False, int(ValueType.WORKFLOW_INSTANCE),
            int(RecordType.COMMAND), int(WI.CREATE),
        )
        assert engine._wave_route_class(*create) == ("create",)
        engine._blind_fb_inflight = 0
        assert engine._wave_route_class(*args) == ("ik", 1)


class TestRoutedLoweringCensus:
    def test_routed_lowers_without_all_gather_fallback_keeps_it(self):
        """THE op-census acceptance pin: the routed program's lowering
        contains ZERO all_gathers — its only collectives are the
        boundary psums (all_reduce) — while the fallback's lowering
        keeps the row-table gathers (also proving the census string
        actually detects the prim)."""
        import dataclasses as dc

        import bench
        from jax.sharding import Mesh
        from zeebe_tpu.tpu import batch as rb
        from zeebe_tpu.tpu import state as state_mod

        graph, _meta = bench.build_graph()
        nv = max(graph.num_vars, 8)
        graph = dc.replace(graph, num_vars=nv)
        mesh = Mesh(np.asarray(jax.devices()), (shard.STATE_AXIS,))
        state_sds = jax.eval_shape(
            lambda: state_mod.make_state(
                capacity=256, num_vars=nv, job_capacity=256, sub_capacity=8
            )
        )
        now = jax.ShapeDtypeStruct((), jnp.int64)
        pid = jax.ShapeDtypeStruct((), jnp.int32)
        batch_sds = jax.eval_shape(lambda: rb.empty(16, nv))
        lanes_sds = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct((8,) + tuple(a.shape), a.dtype),
            batch_sds,
        )
        routed = shard.build_state_step_routed(mesh, state_sds)
        text = routed.lower(graph, state_sds, lanes_sds, now, pid).as_text()
        assert "all_gather" not in text, "routed lowering gained a gather"
        assert "all_reduce" in text, "boundary psums missing"
        fallback = shard.build_state_step_fallback(mesh, state_sds)
        ftext = fallback.lower(
            graph, state_sds, batch_sds, now, pid
        ).as_text()
        assert "all_gather" in ftext, "census string detects nothing"


class TestShardSkewGauge:
    def test_skew_ratio_and_warn_counter(self):
        from zeebe_tpu.runtime import metrics as metrics_mod

        g = GLOBAL_REGISTRY.gauge("mesh_shard_skew_ratio")
        skewed0 = GLOBAL_REGISTRY.counter(
            "mesh_shard_skewed_waves_total"
        ).value
        # balanced wave: ratio 1.0, no warn
        metrics_mod.observe_sharded_wave(np.array([8, 8, 8, 8]), 0)
        assert g.value == pytest.approx(1.0)
        # one shard takes everything at meaningful fill: ratio = nshards
        metrics_mod.observe_sharded_wave(np.array([32, 0, 0, 0]), 0)
        assert g.value == pytest.approx(4.0)
        # 4x is the warn threshold boundary (strictly-above fires)
        metrics_mod.observe_sharded_wave(np.array([33, 0, 0, 0, 0]), 0)
        assert g.value > 4.0
        assert GLOBAL_REGISTRY.counter(
            "mesh_shard_skewed_waves_total"
        ).value > skewed0
        # empty waves leave the gauge untouched
        before = g.value
        metrics_mod.observe_sharded_wave(np.array([0, 0, 0, 0]), 0)
        assert g.value == before
        # resident-ROUTED waves are one-lane BY DESIGN: no skew score
        skewed1 = GLOBAL_REGISTRY.counter(
            "mesh_shard_skewed_waves_total"
        ).value
        metrics_mod.observe_sharded_wave(
            np.array([0, 40, 0, 0, 0]), 0, single_lane=True
        )
        assert g.value == before
        assert GLOBAL_REGISTRY.counter(
            "mesh_shard_skewed_waves_total"
        ).value == skewed1


# ---------------------------------------------------------------------------
# cross-shard correlation: sharded partition, same wire bytes
# ---------------------------------------------------------------------------


def _correlation_workload(data_dir, sharded):
    """Two partitions, every subscription OPEN/CORRELATE forced across
    them; partition 0 optionally shards its tables over 4 devices."""
    from zeebe_tpu.engine.interpreter import WorkflowRepository
    from zeebe_tpu.gateway import ZeebeClient
    from zeebe_tpu.gateway import workers as workers_mod
    from zeebe_tpu.models.bpmn.builder import Bpmn
    from zeebe_tpu.protocol import codec
    from zeebe_tpu.runtime import Broker, ControlledClock
    from zeebe_tpu.tpu import TpuPartitionEngine

    workers_mod._subscriber_keys = itertools.count(1)
    clock = ControlledClock(start_ms=1_000_000)
    repo = WorkflowRepository()

    def factory(pid):
        if sharded and pid == 0:
            return TpuPartitionEngine(
                pid, 2, repository=repo, clock=clock, capacity=1 << 10,
                state_shards=4, shard_devices=jax.devices()[:4],
            )
        return TpuPartitionEngine(
            pid, 2, repository=repo, clock=clock, capacity=1 << 10
        )

    broker = Broker(
        num_partitions=2, data_dir=data_dir, clock=clock,
        engine_factory=factory,
    )
    try:
        client = ZeebeClient(broker)
        client.deploy_model(
            Bpmn.create_process("xshard")
            .start_event("s")
            .receive_task("wait", message_name="paid",
                          correlation_key="$.oid")
            .end_event("e")
            .done()
        )
        for i in range(6):
            # "k-i" hashes to partition i % 2; creating on the OTHER
            # partition forces the subscription hop across partitions —
            # for even i the subscription lands IN the sharded tables
            client.create_instance(
                "xshard", {"oid": f"k-{i}"}, partition_id=(i + 1) % 2
            )
        broker.run_until_idle()
        for i in range(6):
            client.publish_message("paid", f"k-{i}")
        broker.run_until_idle()
        return [
            [codec.encode_record(r) for r in broker.records(pid)]
            for pid in range(2)
        ]
    finally:
        broker.close()


@pytest.mark.slow
class TestCrossShardCorrelation:
    def test_correlation_parity_with_sharded_partition(self, tmp_path):
        """Cross-partition message correlation with one side's tables
        mesh-sharded produces EXACTLY the transport path's logs — the
        budgeted cross-shard gathers never change a correlation."""
        frames_sh = _correlation_workload(str(tmp_path / "sh"), True)
        frames_un = _correlation_workload(str(tmp_path / "un"), False)
        assert sum(len(f) for f in frames_sh) > 50
        for pid, (a, b) in enumerate(zip(frames_sh, frames_un)):
            assert a == b, f"partition {pid} diverged (sharded vs plain)"


# ---------------------------------------------------------------------------
# snapshot / restore across shard counts
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestShardedSnapshotRestore:
    # lookup structures re-derive from live rows at restore
    DERIVED = {
        "ei_map", "ei_index", "job_map", "job_index",
        "free_ei", "free_ei_pop", "free_ei_push",
        "free_job", "free_job_pop", "free_job_push",
    }

    def _assert_states_equal(self, ea, eb):
        norm_a = state_mod.rebuild_lookup_state(ea.state)
        norm_b = state_mod.rebuild_lookup_state(eb.state)
        for f in dataclasses.fields(ea.state):
            if f.name.startswith("sub_"):
                continue  # transient worker subscriptions drop on restore
            src_a = norm_a if f.name in self.DERIVED else ea.state
            src_b = norm_b if f.name in self.DERIVED else eb.state
            a, b = getattr(src_a, f.name), getattr(src_b, f.name)
            if hasattr(a, "keys"):
                np.testing.assert_array_equal(
                    np.asarray(a.keys), np.asarray(b.keys), err_msg=f.name
                )
                np.testing.assert_array_equal(
                    np.asarray(a.vals), np.asarray(b.vals), err_msg=f.name
                )
            else:
                np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b), err_msg=f.name
                )

    def test_round_trip_across_shard_counts(self, tmp_path):
        """A snapshot taken from an 8-way sharded engine restores
        bit-exactly into a 4-way sharded engine AND into a plain
        single-device engine: the snapshot is shard-layout-free."""
        from zeebe_tpu.engine.interpreter import WorkflowRepository
        from zeebe_tpu.runtime import ControlledClock
        from zeebe_tpu.tpu import TpuPartitionEngine

        box = []
        _sharded_workload(str(tmp_path / "w"), 8, engine_box=box)
        engine = box[0]
        snap = engine.snapshot_state()

        clock = ControlledClock(start_ms=1_000_000)
        for shards in (4, 1):
            restored = TpuPartitionEngine(
                0, 1, repository=WorkflowRepository(), clock=clock,
                capacity=1 << 10, state_shards=shards,
            )
            restored.restore_state(snap)
            self._assert_states_equal(engine, restored)
            if shards > 1:
                # the restored engine is still sharded end to end
                assert restored._mesh is not None
                assert restored._state_step is not None
                assert restored._shard_exchange_bytes > 0
                assert len(restored.state.ei_i32.devices()) == shards


# ---------------------------------------------------------------------------
# fixed-seed chaos: crash-stop replay + (slow) leader flap on a span
# ---------------------------------------------------------------------------


def _chaos_run(data_dir, state_shards, crash, routing="gathered"):
    """Seeded two-burst workload with an optional crash-stop between the
    bursts (close + reopen from the same log dir: replay rebuilds the
    sharded tables). Returns the final frame list."""
    from zeebe_tpu.engine.interpreter import WorkflowRepository
    from zeebe_tpu.gateway import JobWorker, ZeebeClient
    from zeebe_tpu.gateway import workers as workers_mod
    from zeebe_tpu.models.bpmn.builder import Bpmn
    from zeebe_tpu.protocol import codec
    from zeebe_tpu.protocol.intents import WorkflowInstanceIntent
    from zeebe_tpu.protocol.records import WorkflowInstanceRecord
    from zeebe_tpu.runtime import Broker, ControlledClock
    from zeebe_tpu.tpu import TpuPartitionEngine

    rnd = random.Random(SEED)
    clock = ControlledClock(start_ms=1_000_000)

    def boot():
        workers_mod._subscriber_keys = itertools.count(1)
        repo = WorkflowRepository()
        broker = Broker(
            num_partitions=1, data_dir=data_dir, clock=clock,
            engine_factory=lambda pid: TpuPartitionEngine(
                pid, 1, repository=repo, clock=clock, capacity=1 << 10,
                state_shards=state_shards,
                routing=routing if state_shards > 1 else "gathered",
            ),
        )
        broker.wave_size = 128
        JobWorker(broker, "chaos-svc", lambda ctx: {"ok": True})
        return broker

    def burst(broker, b):
        for i in range(12):
            broker.write_command(
                0,
                WorkflowInstanceRecord(
                    bpmn_process_id="chaos",
                    payload={"b": b, "i": i, "r": rnd.randrange(1_000_000)},
                ),
                WorkflowInstanceIntent.CREATE,
            )
        broker.run_until_idle()

    broker = boot()
    try:
        ZeebeClient(broker).deploy_model(
            Bpmn.create_process("chaos")
            .start_event("s")
            .service_task("w", type="chaos-svc")
            .end_event("e")
            .done()
        )
        burst(broker, 0)
        if crash:
            broker.close()
            broker = boot()
            # replay alone must rebuild the state: running to quiescence
            # appends NOTHING new (no duplicated side effects)
            n_records = len(broker.records(0))
            broker.run_until_idle()
            assert len(broker.records(0)) == n_records
        burst(broker, 1)
        return [codec.encode_record(r) for r in broker.records(0)]
    finally:
        broker.close()


@pytest.mark.slow
class TestShardedChaos:
    def test_fixed_seed_crash_stop_replays_identically(self, tmp_path):
        """Acceptance chaos leg: a crash-stop mid-run on a 4-way sharded
        partition replays from the log and finishes with EXACTLY the
        frames of a single-device run under the SAME seeded fault
        schedule (same-schedule control isolates the sharding variable;
        transient gateway request ids reset on ANY restart, sharded or
        not, so a no-crash oracle can never be byte-identical)."""
        frames_sharded = _chaos_run(str(tmp_path / "c"), 4, crash=True)
        frames_single = _chaos_run(str(tmp_path / "u"), 1, crash=True)
        assert len(frames_sharded) > 100
        assert frames_sharded == frames_single

    def test_fixed_seed_crash_stop_replays_identically_routed(
        self, tmp_path
    ):
        """Same chaos leg under resident routing: the crash drops the
        host residency dict with everything else; replay re-learns it
        (or falls back) and the frames stay byte-identical to the
        single-device run under the same seeded schedule."""
        frames_routed = _chaos_run(
            str(tmp_path / "r"), 4, crash=True, routing="resident"
        )
        frames_single = _chaos_run(str(tmp_path / "u"), 1, crash=True)
        assert len(frames_routed) > 100
        assert frames_routed == frames_single


@pytest.mark.slow
class TestShardedClusterFlap:
    """Cluster-level leader flap with a sharded span (slow tier with the
    other device-engine cluster suites)."""

    def test_leader_flap_releases_and_respans(self, tmp_path):
        import time

        from zeebe_tpu.gateway.cluster_client import ClusterClient
        from zeebe_tpu.models.bpmn.builder import Bpmn
        from zeebe_tpu.runtime.cluster_broker import ClusterBroker
        from zeebe_tpu.runtime.config import BrokerCfg
        from zeebe_tpu.runtime.engines import engine_factory_from_config

        cfg = BrokerCfg()
        cfg.network.client_port = 0
        cfg.network.management_port = 0
        cfg.network.subscription_port = 0
        cfg.metrics.port = 0
        cfg.metrics.enabled = False
        cfg.cluster.partitions = 1
        cfg.engine.type = "tpu"
        cfg.engine.capacity = 1 << 10
        cfg.mesh.sharded_partitions = 4
        broker = ClusterBroker(
            cfg, os.path.join(str(tmp_path), "b0"),
            engine_factory=engine_factory_from_config(cfg),
        )
        client = None
        try:
            broker.open_partition(0).join(60)
            broker.bootstrap_partition(0, {})
            deadline = time.monotonic() + 60
            while (
                time.monotonic() < deadline
                and not broker.partitions[0].is_leader
            ):
                time.sleep(0.02)
            assert broker.partitions[0].is_leader

            plan = broker.device_plan
            span = plan.device_indices(0)
            assert len(span) == 4
            engine = broker.partitions[0].engine
            assert engine.device_indices == span
            assert engine._mesh is not None

            client = ClusterClient(
                [broker.client_address], num_partitions=1,
                request_timeout_ms=120_000,
            )
            client.deploy_model(
                Bpmn.create_process("flap").start_event("s").end_event("e")
                .done()
            )
            assert client.create_instance(
                "flap", partition_id=0
            ).value.workflow_instance_key > 0

            # leader flap: uninstall frees the WHOLE span, reinstall
            # re-spans and serving continues on the sharded engine
            server = broker.partitions[0]
            term = server.raft.term
            broker.actor.call(server._uninstall_leader).join(10)
            assert plan.device_indices(0) == []
            broker.actor.call(lambda: server._install_leader(term)).join(60)
            new_span = plan.device_indices(0)
            assert len(new_span) == 4
            assert broker.partitions[0].engine.device_indices == new_span
            assert client.create_instance(
                "flap", partition_id=0
            ).value.workflow_instance_key > 0
        finally:
            if client is not None:
                client.close()
            broker.close()
