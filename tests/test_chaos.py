"""Chaos tests: seeded fault injection against the cluster's invariants.

The four invariants (docs/CHAOS.md) that define the paper's semantics:

1. **No acked append is ever lost** — a record whose position reached the
   commit position survives partitions, leader crashes and torn disk
   writes, identically on every live replica.
2. **At most one raft leader per term.**
3. **Replay parity** — replaying the surviving committed log through the
   host oracle engine is deterministic (bit-identical across independent
   replays) and reconstructs the live leader's state.
4. **Snapshot-restore convergence** — a crash at any point inside the
   snapshot commit's two-rename swap leaves a salvageable snapshot, and
   restore + replay converges to the same state.

Fixed-seed runs (tier-1, wired into ci.sh) replay the identical fault
schedule every time; the randomized sweep across seeds is ``slow``.
"""

import os

import pytest

from zeebe_tpu.log import LogStream, SegmentedLogStorage
from zeebe_tpu.log.snapshot import SnapshotMetadata, SnapshotStorage
from zeebe_tpu.runtime.actors import ActorScheduler
from zeebe_tpu.runtime.metrics import event_count
from zeebe_tpu.testing.chaos import (
    ChaosHarness,
    DiskFaults,
    FaultPlane,
    invariant,
    oracle_state_bytes,
    replay_oracle,
)

from tests.test_raft import FAST, Cluster, append_with_retry, job_record, wait_until

SEED = 0xC0FFEE


@pytest.fixture
def scheduler():
    s = ActorScheduler(cpu_threads=2, io_threads=2).start()
    yield s
    s.stop()


# ---------------------------------------------------------------------------
# fault schedule determinism
# ---------------------------------------------------------------------------


class TestFaultScheduleDeterminism:
    @staticmethod
    def _drive(plane):
        plane.set_rule(drop=0.3, duplicate=0.2, delay_ms=5, delay_jitter_ms=10)
        for i in range(300):
            plane.decide(f"n{i % 3}", f"n{(i + 1) % 3}", b"x" * (i % 17))
        return list(plane.trace)

    def test_same_seed_replays_identical_schedule(self):
        """Acceptance: the same seed replays the identical fault schedule
        twice (decision sequence AND verbs, per edge)."""
        assert self._drive(FaultPlane(seed=SEED)) == self._drive(FaultPlane(seed=SEED))

    def test_different_seed_changes_the_schedule(self):
        assert self._drive(FaultPlane(seed=SEED)) != self._drive(FaultPlane(seed=SEED + 1))

    def test_partition_blocks_both_directions_and_heals(self):
        plane = FaultPlane(seed=1)
        plane.partition("a", "b")
        assert plane.decide("a", "b", b"x") == []
        assert plane.decide("b", "a", b"x") == []
        assert plane.decide("a", "c", b"x") is None
        plane.heal("a", "b")
        assert plane.decide("a", "b", b"x") is None

    def test_asymmetric_partition(self):
        plane = FaultPlane(seed=1)
        plane.partition("a", "b", symmetric=False)
        assert plane.decide("a", "b", b"x") == []
        assert plane.decide("b", "a", b"x") is None

    def test_isolate_blocks_unknown_destinations_too(self):
        plane = FaultPlane(seed=1)
        plane.isolate("a")
        assert plane.decide("a", None, b"x") == []  # server-side responses
        assert plane.decide("a", "b", b"x") == []
        assert plane.decide("c", "a", b"x") == []
        assert plane.decide("c", "b", b"x") is None
        plane.heal("a")
        assert plane.decide("a", "b", b"x") is None


# ---------------------------------------------------------------------------
# disk fault injection: snapshot commit crash points + fsync failure
# ---------------------------------------------------------------------------


class TestDiskFaults:
    def test_crash_after_aside_restores_the_committed_snapshot(self, tmp_path):
        """Crash between _swap_in's two renames: the final dir is gone and
        only the set-aside holds the committed snapshot — open() must
        restore it (and delete the torn .tmp), not skip it."""
        root = str(tmp_path)
        storage = SnapshotStorage(root)
        meta = SnapshotMetadata(10, 12, 1)
        storage.write(meta, b"v1-committed")
        s0 = event_count("snapshot_salvage_events")
        DiskFaults.crash_snapshot_commit(
            storage, meta, b"v2-torn", DiskFaults.CRASH_OLD_ASIDE
        )
        assert not os.path.exists(os.path.join(root, meta.dirname))

        reopened = SnapshotStorage(root)
        assert reopened.read(meta) == b"v1-committed"
        assert event_count("snapshot_salvage_events") - s0 >= 2
        leftovers = [
            n for n in os.listdir(root)
            if n.endswith(".tmp") or n.endswith(".aside") or n.endswith(".old")
        ]
        assert leftovers == []

    def test_crash_after_swap_keeps_replacement_and_deletes_orphan(self, tmp_path):
        root = str(tmp_path)
        storage = SnapshotStorage(root)
        meta = SnapshotMetadata(10, 12, 1)
        storage.write(meta, b"v1")
        DiskFaults.crash_snapshot_commit(
            storage, meta, b"v2-replacement", DiskFaults.CRASH_SWAPPED
        )
        # replacement landed; the set-aside old dir is the orphan
        assert os.path.exists(os.path.join(root, meta.dirname + ".aside"))
        reopened = SnapshotStorage(root)
        assert reopened.read(meta) == b"v2-replacement"
        assert not os.path.exists(os.path.join(root, meta.dirname + ".aside"))

    def test_crash_with_only_tmp_written_sweeps_it(self, tmp_path):
        root = str(tmp_path)
        storage = SnapshotStorage(root)
        meta = SnapshotMetadata(5, 6, 0)
        DiskFaults.crash_snapshot_commit(
            storage, meta, b"torn", DiskFaults.CRASH_TMP_WRITTEN
        )
        reopened = SnapshotStorage(root)
        assert reopened.list() == []
        assert not os.path.exists(os.path.join(root, meta.dirname + ".tmp"))

    def test_legacy_old_suffix_still_salvaged(self, tmp_path):
        """Set-aside dirs written by the pre-chaos '.old' spelling are
        swept identically."""
        root = str(tmp_path)
        storage = SnapshotStorage(root)
        meta = SnapshotMetadata(3, 4, 0)
        storage.write(meta, b"v1")
        os.rename(
            os.path.join(root, meta.dirname),
            os.path.join(root, meta.dirname + ".old"),
        )
        reopened = SnapshotStorage(root)
        assert reopened.read(meta) == b"v1"

    def test_break_fsync_fails_then_recovers(self, tmp_path):
        storage = SegmentedLogStorage(str(tmp_path / "log"))
        storage.append(b"block")
        DiskFaults.break_fsync(storage, times=2)
        with pytest.raises(OSError):
            storage.flush()
        with pytest.raises(OSError):
            storage.flush()
        storage.flush()  # restored
        storage.close()


# ---------------------------------------------------------------------------
# fixed-seed raft chaos: partition + leader crash + torn segment tail
# ---------------------------------------------------------------------------


class LeaderLedger:
    """Records every LEADER transition as (node, term) for invariant 2."""

    def __init__(self):
        self.entries = []

    def attach(self, raft):
        from zeebe_tpu.cluster import RaftState

        raft.on_state_change(
            lambda state, term, nid=raft.node_id: self.entries.append((nid, term))
            if state == RaftState.LEADER
            else None
        )

    def assert_at_most_one_leader_per_term(self):
        by_term = {}
        for node, term in self.entries:
            by_term.setdefault(term, set()).add(node)
        offenders = {t: nodes for t, nodes in by_term.items() if len(nodes) > 1}
        assert not offenders, f"multiple leaders in a term: {offenders}"


class TestChaosRaftFixedSeed:
    def _capture_acked(self, log, first: int, last: int, acked: dict) -> None:
        for pos in range(first, last + 1):
            record = log.record_at(pos)
            assert record is not None
            acked[pos] = (record.raft_term, getattr(record.value, "type", None))

    def test_partition_leader_crash_torn_tail(self, scheduler, tmp_path):
        """The acceptance scenario: background message chaos, a partial
        partition, a full partition forcing failover, a leader crash with
        a torn segment tail, restart, heal — then invariants 1 + 2."""
        plane = FaultPlane(seed=SEED)
        # background noise on every edge: seeded drops + reordering jitter
        plane.set_rule(drop=0.05, delay_ms=0, delay_jitter_ms=5)
        cluster = Cluster(scheduler, tmp_path, 3)
        ledger = LeaderLedger()
        try:
            for nid, raft in cluster.nodes.items():
                plane.register_endpoint(nid, raft.address)
                plane.install_client(raft.client, nid)
                ledger.attach(raft)
            leader = cluster.await_leader()
            lid = leader.node_id
            acked = {}

            # warm-up: the leader's initial no-op reaches every log before
            # chaos accounting starts (replication sessions established)
            assert wait_until(
                lambda: all(l.commit_position >= 0 for l in cluster.logs.values()),
                timeout=40,
            ), {nid: l.commit_position for nid, l in cluster.logs.items()}

            # phase 1: clean-ish appends (noise rule active) — all commit
            leader, last = append_with_retry(
                cluster, [job_record(i) for i in range(10)], timeout=30
            )
            assert wait_until(
                lambda: all(l.commit_position >= last for l in cluster.logs.values()),
                timeout=40,
            ), {nid: l.commit_position for nid, l in cluster.logs.items()}
            self._capture_acked(cluster.logs[leader.node_id], last - 9, last, acked)

            # phase 2: partial partition (leader cut off from ONE follower);
            # the remaining majority keeps committing
            lid = leader.node_id
            followers = [nid for nid in cluster.nodes if nid != lid]
            plane.partition(lid, followers[0])
            leader, last = append_with_retry(
                cluster, [job_record(100 + i) for i in range(10)], timeout=30
            )
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= last,
                timeout=40,
            )
            self._capture_acked(cluster.logs[leader.node_id], last - 9, last, acked)

            # phase 3: full partition of the leader, then crash it with a
            # torn tail; the connected majority elects a successor
            plane.heal()
            plane.isolate(lid)
            assert wait_until(
                lambda: any(
                    cluster.nodes[f].state.value == "leader" for f in followers
                ),
                timeout=40,
            ), {nid: n.state for nid, n in cluster.nodes.items()}
            crashed_log = cluster.logs[lid]
            crashed_dir = crashed_log.storage.directory
            cluster.nodes[lid].close()
            del cluster.nodes[lid]
            plane.heal(lid)

            torn0 = event_count("log_torn_tail_truncations")
            DiskFaults.tear_log_tail(crashed_dir, nbytes=11)

            # the successor keeps acking appends meanwhile
            leader, last = append_with_retry(
                cluster, [job_record(200 + i) for i in range(10)], timeout=30
            )
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= last,
                timeout=40,
            )
            self._capture_acked(cluster.logs[leader.node_id], last - 9, last, acked)

            # phase 4: restart the crashed node from its torn disk state —
            # recovery must truncate to the last whole record and rejoin
            from zeebe_tpu.cluster import Raft

            storage = SegmentedLogStorage(crashed_dir)
            log = LogStream(storage, partition_id=0, recover_commit=False)
            assert event_count("log_torn_tail_truncations") > torn0
            raft = Raft(
                lid,
                log,
                scheduler,
                config=FAST,
                storage_path=os.path.join(str(tmp_path), f"raft-{lid}.meta"),
            )
            cluster.nodes[lid] = raft
            cluster.logs[lid] = log
            ledger.attach(raft)
            plane.register_endpoint(lid, raft.address)
            plane.install_client(raft.client, lid)
            members = {nid: n.address for nid, n in cluster.nodes.items()}
            for node in cluster.nodes.values():
                node.bootstrap(members)

            leader, last = append_with_retry(
                cluster, [job_record(300 + i) for i in range(5)], timeout=30
            )
            assert wait_until(
                lambda: all(l.commit_position >= last for l in cluster.logs.values()),
                timeout=60,
            ), {nid: l.commit_position for nid, l in cluster.logs.items()}
            self._capture_acked(cluster.logs[leader.node_id], last - 4, last, acked)

            # invariant 1: every acked record survives identically everywhere
            for nid, log_ in cluster.logs.items():
                for pos, (term, jtype) in acked.items():
                    record = log_.record_at(pos)
                    invariant(
                        record is not None,
                        f"invariant 1: acked record lost on {nid} at {pos}",
                    )
                    invariant(
                        record.raft_term == term,
                        f"invariant 1: acked record term diverged on "
                        f"{nid} at {pos}",
                    )
                    invariant(
                        getattr(record.value, "type", None) == jtype,
                        f"invariant 1: acked record value diverged on "
                        f"{nid} at {pos}",
                    )

            # invariant 2: at most one leader per term
            ledger.assert_at_most_one_leader_per_term()

            # the plane actually injected faults on this schedule
            verbs = {entry[3] for entry in plane.trace}
            assert "drop" in verbs or "drop-partition" in verbs
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# broker-level chaos: snapshot mid-commit crash + oracle replay parity
# ---------------------------------------------------------------------------


def order_process():
    from zeebe_tpu.models.bpmn.builder import Bpmn

    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


def _drained(server) -> bool:
    return server.next_read_position - 1 == server.log.commit_position


def _assert_oracle_parity(harness):
    """Invariant 3: replay of the surviving committed log is deterministic
    bit-for-bit, and reconstructs the live leader's engine state."""
    import time as _time

    # settle: the log must be drained AND quiescent — a worker's last
    # in-flight async completion may commit AFTER the drain check, and a
    # leadership flap can step the captured leader down mid-wait (engine
    # becomes None) — so re-resolve the leader every round and require
    # the commit position to hold still before trusting the captured set
    committed = []
    live = None
    server = None
    deadline = _time.monotonic() + 20
    while _time.monotonic() < deadline:
        leader = harness.leader_of(0)
        if leader is None:
            _time.sleep(0.2)
            continue
        server = leader.partitions[0]
        before = server.log.commit_position
        _time.sleep(0.6)
        engine = server.engine
        if (
            engine is None
            or server.log.commit_position != before
            or not _drained(server)
        ):
            continue
        committed = server.log.reader(0).read_committed()
        if committed and (
            committed[-1].position == engine.last_processed_position
        ):
            live = engine
            break
        committed = []
    assert committed and live is not None, (
        None if server is None
        else (server.next_read_position, server.log.commit_position)
    )
    oracle_a = replay_oracle(committed)
    oracle_b = replay_oracle(committed)
    invariant(
        oracle_state_bytes(oracle_a) == oracle_state_bytes(oracle_b),
        "invariant 3: independent oracle replays diverged bit-for-bit",
    )
    invariant(
        set(oracle_a.jobs) == set(live.jobs),
        "invariant 3: oracle replay job set diverged from the live engine",
    )
    for key, job in live.jobs.items():
        invariant(
            oracle_a.jobs[key].state == job.state,
            f"invariant 3: job {key} state diverged between replay and "
            "live engine",
        )
    invariant(
        sorted(oracle_a.element_instances.instances)
        == sorted(live.element_instances.instances),
        "invariant 3: element-instance set diverged between replay and "
        "live engine",
    )
    invariant(
        oracle_a.last_processed_position == live.last_processed_position,
        "invariant 3: last processed position diverged between replay "
        "and live engine",
    )


class TestChaosBrokerFixedSeed:
    def test_mid_commit_snapshot_crash_converges(self, tmp_path):
        """Invariant 4: a crash between the snapshot swap's two renames is
        salvaged on restart, and restore + replay converges (the next
        instance completes end-to-end on the recovered state)."""
        from zeebe_tpu.log import stateser

        harness = ChaosHarness(str(tmp_path), n_brokers=1)
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {"paid": True},
            )
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 1, timeout=30)
            worker.close()

            broker = harness.brokers["b0"]
            broker.snapshot_all()
            server = broker.partitions[0]
            metas = server.snapshots.storage.list()
            assert metas, "snapshot_all produced no snapshot"
            meta = metas[0]

            # crash while REWRITING the same snapshot: old final set aside,
            # replacement never renamed in
            s0 = event_count("snapshot_salvage_events")
            DiskFaults.crash_snapshot_commit(
                server.snapshots.storage,
                meta,
                stateser.encode_state({"torn": True}),
                DiskFaults.CRASH_OLD_ASIDE,
            )
            client.close()
            client = None
            harness.crash("b0")
            harness.restart("b0")
            assert event_count("snapshot_salvage_events") - s0 >= 2
            harness.await_leaders()

            # recovered broker: the salvaged snapshot + replay serve traffic
            client = harness.client()
            done2 = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done2.append(rec.key) or {"paid": True},
            )
            client.create_instance("order-process")
            assert wait_until(lambda: len(done2) >= 1, timeout=30)
            worker.close()
            _assert_oracle_parity(harness)
        finally:
            if client is not None:
                client.close()
            harness.close()

    def test_replay_parity_after_leader_crash(self, tmp_path):
        """Invariant 3 under failover: crash the partition leader mid-
        traffic (with seeded network jitter), restart it, finish the work,
        then prove the surviving committed log replays to the live state."""
        plane = FaultPlane(seed=SEED)
        plane.set_rule(delay_ms=0, delay_jitter_ms=3)  # reorder-y jitter
        harness = ChaosHarness(str(tmp_path), n_brokers=3, plane=plane)
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {"paid": True},
            )
            client.create_instance("order-process")
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 2, timeout=30), done

            old = harness.leader_of(0)
            old_id = old.node_id
            harness.crash(old_id)
            assert wait_until(
                lambda: harness.leader_of(0) is not None, timeout=30
            ), "no successor elected"
            new_leader = harness.leader_of(0)
            assert wait_until(
                lambda: new_leader.repository.latest("order-process") is not None,
                timeout=20,
            )
            harness.restart(old_id)

            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 3, timeout=30), done
            worker.close()
            _assert_oracle_parity(harness)
        finally:
            if client is not None:
                client.close()
            harness.close()


# ---------------------------------------------------------------------------
# wave scheduler under chaos (ISSUE 8): crash-stop / failover / fault-
# injected packing — acked records survive, per-partition order holds,
# cursors resume gap-free, one wedged partition never stalls the rest
# ---------------------------------------------------------------------------


def _scheduler_chaos_round(seed, feeds_spec, rounds=50):
    """Property harness for the scheduler core: seeded fault-injected
    feeds (random backlog growth, random dispatch failures, random
    leader-flap unregister/reregister) driven through real drains.
    Invariants checked: per-feed dispatch order is exactly cursor order
    with NO gaps and no loss (a failed dispatch re-drains), and sparse
    feeds are never starved by deep ones."""
    import random

    from zeebe_tpu.scheduler import WaveScheduler

    class ChaosFeed:
        def __init__(self, pid, fail_rate, pipelined):
            self.partition_id = pid
            self.cursor = 0
            self.available = 0
            self.fail_rate = fail_rate
            self.pipelined = pipelined
            self.dispatched = []
            self.collected = 0
            self.rng = random.Random(seed * 31 + pid)

        def backlog(self):
            return self.available - self.cursor

        def take(self, limit):
            n = min(limit, self.available - self.cursor)
            if n <= 0:
                return []
            out = list(range(self.cursor, self.cursor + n))
            self.cursor += n
            return out

        def dispatch(self, records):
            if self.rng.random() < self.fail_rate:
                raise RuntimeError("chaos dispatch failure")
            self.dispatched.extend(records)
            if self.pipelined:
                return list(records), 0.0, 0.0
            self.collected += len(records)
            return None, 0.0, 0.0

        def collect(self, pending):
            self.collected += len(pending)
            return 0.0, 0.0

        def rewind(self, position):
            self.cursor = min(self.cursor, position)
            # a rewound span re-drains: drop it from the dispatched tally
            self.dispatched = [p for p in self.dispatched if p < position]

        def tick(self):
            pass

    rng = random.Random(seed)
    ws = WaveScheduler(wave_size=64, quantum=8, backpressure_limit=64)
    feeds = [
        ChaosFeed(pid, fail, pipe)
        for pid, (fail, pipe) in enumerate(feeds_spec)
    ]
    registered = set()
    for f in feeds:
        ws.register(f)
        registered.add(f.partition_id)
    for _ in range(rounds):
        # traffic arrival (skewed): feed 0 heavy, the rest sparse
        for f in feeds:
            f.available += rng.choice(
                (24, 48) if f.partition_id == 0 else (0, 1, 3)
            )
        # leader flaps: random unregister/reregister
        if rng.random() < 0.2 and len(registered) > 1:
            pid = rng.choice(sorted(registered))
            ws.unregister(pid)
            registered.discard(pid)
        if rng.random() < 0.4:
            for f in feeds:
                if f.partition_id not in registered:
                    ws.register(f)
                    registered.add(f.partition_id)
                    break
        try:
            ws.drain()
        except RuntimeError:
            pass  # chaos dispatch failure: the records must re-drain
    for f in feeds:
        f.fail_rate = 0.0
        if f.partition_id not in registered:
            ws.register(f)
    ws.drain()
    for f in feeds:
        # order + gap-free: the dispatched sequence IS cursor order
        assert f.dispatched == list(range(len(f.dispatched))), (
            f"feed {f.partition_id} order/gap violation"
        )
        # nothing lost or stuck: everything available was dispatched AND
        # collected despite failures, flaps and backpressure
        assert len(f.dispatched) == f.available
        assert f.collected == f.available, (
            f"feed {f.partition_id}: {f.collected}/{f.available} collected"
        )


class TestSchedulerChaosFixedSeed:
    def test_packing_invariants_under_fault_injected_feeds(self):
        """Fixed-seed scheduler-core chaos: dispatch failures + leader
        flaps + a deep feed next to sparse pipelined ones."""
        _scheduler_chaos_round(
            SEED,
            feeds_spec=[(0.1, False), (0.05, True), (0.0, True), (0.1, False)],
        )

    def test_wedged_partition_backpressure_never_stalls_others(self):
        """A pipelined feed pinned at its in-flight cap (its collects are
        deferred to the scheduler's own unblocking path) must not stop
        the OTHER feeds from fully draining in the same waves."""
        from zeebe_tpu.scheduler import WaveScheduler

        class SlowFeed:
            """Deep pipelined backlog: always has more to take."""

            partition_id = 0

            def __init__(self):
                self.cursor = 0

            def backlog(self):
                return 100_000 - self.cursor

            def take(self, limit):
                n = min(limit, 100_000 - self.cursor)
                out = list(range(self.cursor, self.cursor + n))
                self.cursor += n
                return out

            def dispatch(self, records):
                return list(records), 0.0, 0.0

            def collect(self, pending):
                return 0.0, 0.0

            def rewind(self, position):
                self.cursor = min(self.cursor, position)

            def tick(self):
                pass

        class SparseFeed(SlowFeed):
            partition_id = 1

            def __init__(self):
                super().__init__()
                self.total = 40
                self.dispatched = 0

            def backlog(self):
                return self.total - self.cursor

            def take(self, limit):
                n = min(limit, self.total - self.cursor)
                out = list(range(self.cursor, self.cursor + n))
                self.cursor += n
                return out

            def dispatch(self, records):
                self.dispatched += len(records)
                return None, 0.0, 0.0

        ws = WaveScheduler(wave_size=32, quantum=8, backpressure_limit=32)
        slow, sparse = SlowFeed(), SparseFeed()
        ws.register(slow)
        ws.register(sparse)
        ws.drain(max_records=2048)
        assert sparse.dispatched == 40, "sparse feed starved by wedged one"

    def test_crash_stop_multi_partition_no_acked_loss(self, tmp_path):
        """Crash-stop the broker mid-multi-partition traffic under the
        shared-wave drain: every ACKED create survives restart on its own
        partition, cursors resume gap-free (traffic completes on both
        partitions), and the committed logs replay deterministically."""
        harness = ChaosHarness(str(tmp_path), n_brokers=1, partitions=2)
        client = None
        try:
            harness.await_leaders()
            broker = harness.brokers["b0"]
            assert broker.wave_scheduler is not None
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(pid) or {"paid": True},
            )
            acked = {0: [], 1: []}
            for i in range(6):
                pid = i % 2
                rsp = client.create_instance(
                    "order-process", partition_id=pid
                )
                acked[pid].append(rsp.value.workflow_instance_key)
            assert wait_until(lambda: len(done) >= 6, timeout=30), done
            worker.close()
            client.close()
            client = None

            harness.crash("b0")
            harness.restart("b0")
            harness.await_leaders()
            broker = harness.brokers["b0"]
            # invariant 1 per partition: acked creates are in THEIR
            # partition's recovered log, in issue order
            from zeebe_tpu.protocol.enums import RecordType, ValueType
            from zeebe_tpu.protocol.intents import (
                WorkflowInstanceIntent as WI,
            )

            for pid, keys in acked.items():
                log = broker.partitions[pid].log
                created = [
                    r.value.workflow_instance_key
                    for r in log.reader(0)
                    if r.metadata.value_type == ValueType.WORKFLOW_INSTANCE
                    and r.metadata.record_type == RecordType.EVENT
                    and r.metadata.intent == int(WI.CREATED)
                ]
                for key in keys:
                    assert key in created, (
                        f"acked instance {key} lost on partition {pid}"
                    )
                assert [k for k in created if k in keys] == keys, (
                    f"partition {pid} lost issue order"
                )
            # cursors resumed: new traffic completes on both partitions
            client = harness.client()
            done2 = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done2.append(pid) or {"paid": True},
            )
            client.create_instance("order-process", partition_id=0)
            client.create_instance("order-process", partition_id=1)
            assert wait_until(lambda: len(done2) >= 2, timeout=30), done2
            assert set(done2) == {0, 1}
            worker.close()
            _assert_oracle_parity(harness)
        finally:
            if client is not None:
                client.close()
            harness.close()

    def test_leader_failover_scheduler_resumes(self, tmp_path):
        """Failover under seeded network jitter with the scheduler
        draining: the new leader's feed picks up at the replayed cursor
        and traffic completes (the shared-wave analogue of invariant 3's
        failover case)."""
        plane = FaultPlane(seed=SEED)
        plane.set_rule(delay_ms=0, delay_jitter_ms=3)
        harness = ChaosHarness(str(tmp_path), n_brokers=3, plane=plane)
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {"paid": True},
            )
            client.create_instance("order-process")
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 2, timeout=30), done

            old = harness.leader_of(0)
            harness.crash(old.node_id)
            assert wait_until(
                lambda: harness.leader_of(0) is not None, timeout=30
            )
            new_leader = harness.leader_of(0)
            assert new_leader.wave_scheduler is not None
            assert wait_until(
                lambda: new_leader.repository.latest("order-process")
                is not None,
                timeout=20,
            )
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 3, timeout=30), done
            worker.close()
            _assert_oracle_parity(harness)
        finally:
            if client is not None:
                client.close()
            harness.close()


@pytest.mark.slow
class TestSchedulerChaosRandomized:
    @pytest.mark.parametrize("seed", [11, 12, 13, 14, 15])
    def test_packing_invariants_random_seeds(self, seed):
        import random

        rng = random.Random(seed)
        spec = [
            (rng.choice((0.0, 0.05, 0.15)), rng.random() < 0.5)
            for _ in range(rng.randint(2, 6))
        ]
        _scheduler_chaos_round(seed, feeds_spec=spec, rounds=120)


# ---------------------------------------------------------------------------
# randomized sweep (slow): many seeds, probabilistic faults
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestChaosRandomizedSweep:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_invariants_hold_under_random_faults(self, scheduler, tmp_path, seed):
        plane = FaultPlane(seed=seed)
        plane.set_rule(drop=0.1, duplicate=0.05, delay_ms=1, delay_jitter_ms=8)
        cluster = Cluster(scheduler, tmp_path, 3)
        ledger = LeaderLedger()
        try:
            for nid, raft in cluster.nodes.items():
                plane.register_endpoint(nid, raft.address)
                plane.install_client(raft.client, nid)
                ledger.attach(raft)
            cluster.await_leader()
            acked = {}
            for batch in range(6):
                leader, last = append_with_retry(
                    cluster, [job_record(batch * 10 + i) for i in range(5)],
                    timeout=30,
                )
                assert wait_until(
                    lambda: cluster.logs[leader.node_id].commit_position >= last,
                    timeout=30,
                )
                log = cluster.logs[leader.node_id]
                for pos in range(last - 4, last + 1):
                    record = log.record_at(pos)
                    acked[pos] = (record.raft_term, getattr(record.value, "type", None))
            plane.clear_rules()
            leader, last = append_with_retry(cluster, [job_record(999)], timeout=30)
            assert wait_until(
                lambda: all(l.commit_position >= last for l in cluster.logs.values()),
                timeout=30,
            )
            for nid, log_ in cluster.logs.items():
                for pos, (term, jtype) in acked.items():
                    record = log_.record_at(pos)
                    assert record is not None, (nid, pos)
                    assert (record.raft_term, getattr(record.value, "type", None)) == (
                        term, jtype,
                    ), (nid, pos)
            ledger.assert_at_most_one_leader_per_term()
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# exporter plane under chaos: at-least-once, in order, no gaps — across
# crash-stop/restart and leader failover (tier-1 acceptance: two exporters,
# JSONL + in-memory, resume from the last acked position with no gap and no
# compaction of unexported records)
# ---------------------------------------------------------------------------


def _exporter_cfg_tweaks(audit_dir):
    from zeebe_tpu.runtime.config import ExporterCfg

    def tweaks(cfg):
        cfg.exporters = [
            ExporterCfg(id="chaos-mem", type="memory"),
            ExporterCfg(id="chaos-audit", type="jsonl",
                        args={"path": audit_dir}),
        ]

    return tweaks


def _committed_visible(server):
    """Non-admin committed records of a partition (what exporters see)."""
    from zeebe_tpu.protocol.enums import ValueType

    commit = server.log.commit_position
    return [
        r for r in server.log.reader(0)
        if r.position <= commit
        and int(r.metadata.value_type) != int(ValueType.EXPORTER)
    ]


def _acks_durable(server, exporter_ids):
    """Every exporter's COMMITTED-and-processed ack covers the last
    visible record — only then is a crash guaranteed duplicate-free (an
    ack still in flight re-exports its batch on restart: at-least-once)."""
    engine = server.engine  # snapshot: a step-down nulls it mid-poll
    if engine is None:
        return False
    committed = _committed_visible(server)
    last = committed[-1].position if committed else -1
    return all(
        engine.exporter_positions.get(i, -1) >= last
        for i in exporter_ids
    )


def _settled(harness, exporter_ids, hold=0.5):
    """True once the partition has quiesced — no new commits for ``hold``
    seconds (a workflow keeps committing its completion chain after the
    job handler returns) — AND every exporter's durable ack covers the
    tail.  Only then is the exported sequence a fixed target: comparing
    against a snapshot taken mid-chain flakes on the trailing records."""
    import time

    leader = harness.leader_of(0)
    if leader is None:  # transient leaderless window: poll again
        return False
    server = leader.partitions[0]
    if server.engine is None:  # step-down raced the leader snapshot
        return False
    before = server.log.commit_position
    if not _acks_durable(server, exporter_ids):
        return False
    time.sleep(hold)
    return (server.engine is not None
            and server.log.commit_position == before
            and _acks_durable(server, exporter_ids))


def _assert_exporter_invariants(harness, exporter_id="chaos-mem"):
    """The registered exporter observed every committed record at-least-
    once, in order, with no gaps — across every incarnation."""
    from zeebe_tpu.exporter import InMemoryExporter
    import time as _time

    # a leadership flap right after the settle wait may leave a transient
    # leaderless window — wait it out rather than crash on None
    leader = harness.leader_of(0)
    deadline = _time.monotonic() + 15
    while leader is None and _time.monotonic() < deadline:
        _time.sleep(0.2)
        leader = harness.leader_of(0)
    assert leader is not None, "no leader to verify exporter invariants on"
    server = leader.partitions[0]
    committed = _committed_visible(server)
    expected = [r.position for r in committed]
    assert expected, "no committed records to check against"

    sink = InMemoryExporter.sink(exporter_id)
    seen = {r.position for r in sink}
    missing = [p for p in expected if p not in seen]
    invariant(
        not missing,
        f"exporter {exporter_id!r} never saw committed positions "
        f"{missing[:10]} (gap: at-least-once violated)",
    )
    for i, episode in enumerate(InMemoryExporter.episodes(exporter_id)):
        positions = [r.position for r in episode]
        invariant(
            positions == sorted(positions),
            f"exporter episode {i} delivered out of order",
        )
        # gap-free within an episode: the positions it saw are a
        # contiguous slice of the committed non-admin sequence
        idx = {p: n for n, p in enumerate(expected)}
        views = [idx[p] for p in positions if p in idx]
        if views:
            invariant(
                views == list(range(views[0], views[0] + len(views))),
                f"exporter episode {i} skipped committed records "
                "mid-stream",
            )
    return committed


class BlockingExporter:
    """export_batch BLOCKS (never raises) until the class gate opens —
    the pathological custom sink the director's own actor must contain.
    Configured via the ``module:Class`` path, so it exercises the same
    loading path an operator's exporter would."""

    MANUAL_ACK = False
    gate = None  # threading.Event, armed by the test

    def configure(self, context):
        pass

    def open(self, controller):
        pass

    def export_batch(self, records):
        if BlockingExporter.gate is not None:
            BlockingExporter.gate.wait(30)

    def close(self):
        pass


class TestExporterChaos:
    def test_blocking_exporter_does_not_stall_processing(self, tmp_path):
        """Failure isolation's last clause: a custom exporter whose
        export_batch BLOCKS (rather than raises) stalls only the exporter
        actor — workflows keep completing; once unblocked it catches up."""
        import threading

        from zeebe_tpu.exporter import InMemoryExporter
        from zeebe_tpu.runtime.config import ExporterCfg

        InMemoryExporter.reset()
        BlockingExporter.gate = threading.Event()  # closed: blocks

        # the type path must name THIS module object: under pytest (no
        # tests/__init__.py) the module imports as 'test_chaos', while
        # 'tests.test_chaos' resolves to a SECOND namespace-package copy
        # whose class gate is None — the blocker then never blocks
        blocker_type = (
            f"{BlockingExporter.__module__}:{BlockingExporter.__qualname__}"
        )

        def tweaks(cfg):
            cfg.exporters = [
                ExporterCfg(id="blocker", type=blocker_type),
                ExporterCfg(id="chaos-mem", type="memory"),
            ]

        harness = ChaosHarness(
            str(tmp_path / "cluster"), n_brokers=1, cfg_tweaks=tweaks
        )
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {"paid": True},
            )
            # with the blocker wedged mid-export_batch, processing must
            # still complete workflows end-to-end
            for _ in range(3):
                client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 3, timeout=30), (
                "a blocking exporter stalled record processing"
            )
            server = harness.brokers["b0"].partitions[0]
            assert server.engine.exporter_positions.get("blocker", -1) == -1, (
                "blocker acked while wedged?"
            )
            worker.close()

            # release the gate: the blocker drains and its ack catches up
            BlockingExporter.gate.set()
            assert wait_until(
                lambda: _settled(harness, ["blocker", "chaos-mem"]),
                timeout=30,
            ), "blocker never caught up after unblocking"
            _assert_exporter_invariants(harness)
        finally:
            if BlockingExporter.gate is not None:
                BlockingExporter.gate.set()  # release a wedged worker
            BlockingExporter.gate = None
            if client is not None:
                client.close()
            harness.close()
            InMemoryExporter.reset()

    def test_crash_stop_restart_resumes_without_gap_or_duplicates(self, tmp_path):
        """Acceptance: two exporters (JSONL + in-memory), broker crash-
        stopped mid-stream, restarted — export resumes from the last acked
        position with no gap; unexported records were never compacted."""
        from zeebe_tpu.exporter import InMemoryExporter, read_audit_docs

        InMemoryExporter.reset()
        audit_dir = str(tmp_path / "audit")
        harness = ChaosHarness(
            str(tmp_path / "cluster"), n_brokers=1,
            cfg_tweaks=_exporter_cfg_tweaks(audit_dir),
        )
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {"paid": True},
            )
            for _ in range(3):
                client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 3, timeout=30)
            worker.close()
            client.close()
            client = None

            broker = harness.brokers["b0"]
            server = broker.partitions[0]
            # wait until the partition quiesces with BOTH exporters' acks
            # durable past the tail — only then does the crash guarantee a
            # duplicate-free resume (an ack still in flight re-exports
            # its batch: at-least-once, but not this test's claim)
            assert wait_until(
                lambda: _settled(harness, ["chaos-mem", "chaos-audit"]),
                timeout=30,
            ), "exporter acks never became durable"
            exported_before = len(InMemoryExporter.sink("chaos-mem"))
            holes_before = event_count("exporter_audit_holes")

            # crash-stop mid-stream, restart
            harness.crash("b0")
            harness.restart("b0")
            harness.await_leaders()

            broker = harness.brokers["b0"]
            server = broker.partitions[0]
            # no compaction of unexported records: everything from the
            # resumed position is still in the log
            resumed_at = min(
                server.engine.exporter_positions.get("chaos-mem", -1),
                server.engine.exporter_positions.get("chaos-audit", -1),
            ) + 1
            assert server.log.base_position <= max(0, resumed_at)

            client = harness.client()
            done2 = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done2.append(rec.key) or {"paid": True},
            )
            client.create_instance("order-process")
            assert wait_until(lambda: len(done2) >= 1, timeout=30)
            worker.close()

            # settle again before capturing the comparison sequence: the
            # fourth instance's completion chain commits (and exports)
            # after the job handler returns
            assert wait_until(
                lambda: _settled(harness, ["chaos-mem", "chaos-audit"]),
                timeout=30,
            ), "exporters never settled after restart"
            committed = _assert_exporter_invariants(harness)
            # resume was exact: the restarted incarnation did not re-export
            # already-acked records (no duplicates at the crash boundary)
            sink = InMemoryExporter.sink("chaos-mem")
            sink_positions = [r.position for r in sink]
            assert len(sink_positions) == len(set(sink_positions)), (
                "duplicate export across a clean crash-stop/restart"
            )
            assert len(sink) > exported_before, "nothing exported after restart"

            # the JSONL audit trail replays to the exact committed sequence
            # (the settle wait above already covered chaos-audit's ack)
            docs = read_audit_docs(audit_dir)
            assert [d["position"] for d in docs] == [r.position for r in committed]
            # and the JSONL sink did NOT false-report an audit hole on
            # reopen: the replicated ack always lands on a VISIBLE record
            # the file actually contains, never on a trailing hidden
            # admin position the exporter could not have written
            assert event_count("exporter_audit_holes") == holes_before, (
                "audit-hole false positive across a clean crash-stop"
            )
        finally:
            if client is not None:
                client.close()
            harness.close()
            from zeebe_tpu.exporter import InMemoryExporter as _IM

            _IM.reset()

    def test_leader_failover_keeps_at_least_once_in_order(self, tmp_path):
        """Crash the partition LEADER mid-stream: the new leader's director
        resumes from the replicated acked positions — every committed
        record still reaches the exporter, in order, no gaps."""
        from zeebe_tpu.exporter import InMemoryExporter

        InMemoryExporter.reset()
        audit_dir = str(tmp_path / "audit")
        harness = ChaosHarness(
            str(tmp_path / "cluster"), n_brokers=3,
            cfg_tweaks=_exporter_cfg_tweaks(audit_dir),
        )
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {"paid": True},
            )
            for _ in range(2):
                client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 2, timeout=30)

            old = harness.leader_of(0)
            old_id = old.node_id
            harness.crash(old_id)
            assert wait_until(lambda: harness.leader_of(0) is not None, timeout=30)
            new_leader = harness.leader_of(0)
            assert wait_until(
                lambda: new_leader.repository.latest("order-process") is not None,
                timeout=20,
            )
            harness.restart(old_id)
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 3, timeout=30)
            worker.close()

            assert wait_until(
                lambda: _settled(harness, ["chaos-mem", "chaos-audit"]),
                timeout=30,
            ), "exporter did not catch up after failover"
            _assert_exporter_invariants(harness)
        finally:
            if client is not None:
                client.close()
            harness.close()
            from zeebe_tpu.exporter import InMemoryExporter as _IM

            _IM.reset()


@pytest.mark.slow
class TestExporterChaosRandomized:
    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_exporter_invariants_under_random_faults(self, tmp_path, seed):
        """Randomized sweep: seeded network jitter + a leader crash at a
        seed-chosen point; the at-least-once/in-order/no-gap contract must
        hold on every schedule."""
        import random as _random

        from zeebe_tpu.exporter import InMemoryExporter

        InMemoryExporter.reset()
        rng = _random.Random(seed)
        plane = FaultPlane(seed=seed)
        plane.set_rule(drop=0.05, delay_ms=1, delay_jitter_ms=5)
        audit_dir = str(tmp_path / "audit")
        harness = ChaosHarness(
            str(tmp_path / "cluster"), n_brokers=3, plane=plane,
            cfg_tweaks=_exporter_cfg_tweaks(audit_dir),
        )
        client = None
        try:
            harness.await_leaders(120)
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {"paid": True},
            )
            n_before = rng.randint(1, 4)
            for _ in range(n_before):
                client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= n_before, timeout=60)

            victim = harness.leader_of(0).node_id
            harness.crash(victim)
            assert wait_until(
                lambda: harness.leader_of(0) is not None, timeout=60
            )
            new_leader = harness.leader_of(0)
            assert wait_until(
                lambda: new_leader.repository.latest("order-process") is not None,
                timeout=30,
            )
            harness.restart(victim)
            n_after = rng.randint(1, 3)
            for _ in range(n_after):
                client.create_instance("order-process")
            assert wait_until(
                lambda: len(done) >= n_before + n_after, timeout=60
            )
            worker.close()
            plane.clear_rules()
            assert wait_until(
                lambda: _settled(harness, ["chaos-mem", "chaos-audit"]),
                timeout=60,
            )
            _assert_exporter_invariants(harness)
        finally:
            if client is not None:
                client.close()
            harness.close()
            from zeebe_tpu.exporter import InMemoryExporter as _IM

            _IM.reset()
