"""Chaos tests: seeded fault injection against the cluster's invariants.

The four invariants (docs/CHAOS.md) that define the paper's semantics:

1. **No acked append is ever lost** — a record whose position reached the
   commit position survives partitions, leader crashes and torn disk
   writes, identically on every live replica.
2. **At most one raft leader per term.**
3. **Replay parity** — replaying the surviving committed log through the
   host oracle engine is deterministic (bit-identical across independent
   replays) and reconstructs the live leader's state.
4. **Snapshot-restore convergence** — a crash at any point inside the
   snapshot commit's two-rename swap leaves a salvageable snapshot, and
   restore + replay converges to the same state.

Fixed-seed runs (tier-1, wired into ci.sh) replay the identical fault
schedule every time; the randomized sweep across seeds is ``slow``.
"""

import os

import pytest

from zeebe_tpu.log import LogStream, SegmentedLogStorage
from zeebe_tpu.log.snapshot import SnapshotMetadata, SnapshotStorage
from zeebe_tpu.runtime.actors import ActorScheduler
from zeebe_tpu.runtime.metrics import event_count
from zeebe_tpu.testing.chaos import (
    ChaosHarness,
    DiskFaults,
    FaultPlane,
    oracle_state_bytes,
    replay_oracle,
)

from tests.test_raft import FAST, Cluster, append_with_retry, job_record, wait_until

SEED = 0xC0FFEE


@pytest.fixture
def scheduler():
    s = ActorScheduler(cpu_threads=2, io_threads=2).start()
    yield s
    s.stop()


# ---------------------------------------------------------------------------
# fault schedule determinism
# ---------------------------------------------------------------------------


class TestFaultScheduleDeterminism:
    @staticmethod
    def _drive(plane):
        plane.set_rule(drop=0.3, duplicate=0.2, delay_ms=5, delay_jitter_ms=10)
        for i in range(300):
            plane.decide(f"n{i % 3}", f"n{(i + 1) % 3}", b"x" * (i % 17))
        return list(plane.trace)

    def test_same_seed_replays_identical_schedule(self):
        """Acceptance: the same seed replays the identical fault schedule
        twice (decision sequence AND verbs, per edge)."""
        assert self._drive(FaultPlane(seed=SEED)) == self._drive(FaultPlane(seed=SEED))

    def test_different_seed_changes_the_schedule(self):
        assert self._drive(FaultPlane(seed=SEED)) != self._drive(FaultPlane(seed=SEED + 1))

    def test_partition_blocks_both_directions_and_heals(self):
        plane = FaultPlane(seed=1)
        plane.partition("a", "b")
        assert plane.decide("a", "b", b"x") == []
        assert plane.decide("b", "a", b"x") == []
        assert plane.decide("a", "c", b"x") is None
        plane.heal("a", "b")
        assert plane.decide("a", "b", b"x") is None

    def test_asymmetric_partition(self):
        plane = FaultPlane(seed=1)
        plane.partition("a", "b", symmetric=False)
        assert plane.decide("a", "b", b"x") == []
        assert plane.decide("b", "a", b"x") is None

    def test_isolate_blocks_unknown_destinations_too(self):
        plane = FaultPlane(seed=1)
        plane.isolate("a")
        assert plane.decide("a", None, b"x") == []  # server-side responses
        assert plane.decide("a", "b", b"x") == []
        assert plane.decide("c", "a", b"x") == []
        assert plane.decide("c", "b", b"x") is None
        plane.heal("a")
        assert plane.decide("a", "b", b"x") is None


# ---------------------------------------------------------------------------
# disk fault injection: snapshot commit crash points + fsync failure
# ---------------------------------------------------------------------------


class TestDiskFaults:
    def test_crash_after_aside_restores_the_committed_snapshot(self, tmp_path):
        """Crash between _swap_in's two renames: the final dir is gone and
        only the set-aside holds the committed snapshot — open() must
        restore it (and delete the torn .tmp), not skip it."""
        root = str(tmp_path)
        storage = SnapshotStorage(root)
        meta = SnapshotMetadata(10, 12, 1)
        storage.write(meta, b"v1-committed")
        s0 = event_count("snapshot_salvage_events")
        DiskFaults.crash_snapshot_commit(
            storage, meta, b"v2-torn", DiskFaults.CRASH_OLD_ASIDE
        )
        assert not os.path.exists(os.path.join(root, meta.dirname))

        reopened = SnapshotStorage(root)
        assert reopened.read(meta) == b"v1-committed"
        assert event_count("snapshot_salvage_events") - s0 >= 2
        leftovers = [
            n for n in os.listdir(root)
            if n.endswith(".tmp") or n.endswith(".aside") or n.endswith(".old")
        ]
        assert leftovers == []

    def test_crash_after_swap_keeps_replacement_and_deletes_orphan(self, tmp_path):
        root = str(tmp_path)
        storage = SnapshotStorage(root)
        meta = SnapshotMetadata(10, 12, 1)
        storage.write(meta, b"v1")
        DiskFaults.crash_snapshot_commit(
            storage, meta, b"v2-replacement", DiskFaults.CRASH_SWAPPED
        )
        # replacement landed; the set-aside old dir is the orphan
        assert os.path.exists(os.path.join(root, meta.dirname + ".aside"))
        reopened = SnapshotStorage(root)
        assert reopened.read(meta) == b"v2-replacement"
        assert not os.path.exists(os.path.join(root, meta.dirname + ".aside"))

    def test_crash_with_only_tmp_written_sweeps_it(self, tmp_path):
        root = str(tmp_path)
        storage = SnapshotStorage(root)
        meta = SnapshotMetadata(5, 6, 0)
        DiskFaults.crash_snapshot_commit(
            storage, meta, b"torn", DiskFaults.CRASH_TMP_WRITTEN
        )
        reopened = SnapshotStorage(root)
        assert reopened.list() == []
        assert not os.path.exists(os.path.join(root, meta.dirname + ".tmp"))

    def test_legacy_old_suffix_still_salvaged(self, tmp_path):
        """Set-aside dirs written by the pre-chaos '.old' spelling are
        swept identically."""
        root = str(tmp_path)
        storage = SnapshotStorage(root)
        meta = SnapshotMetadata(3, 4, 0)
        storage.write(meta, b"v1")
        os.rename(
            os.path.join(root, meta.dirname),
            os.path.join(root, meta.dirname + ".old"),
        )
        reopened = SnapshotStorage(root)
        assert reopened.read(meta) == b"v1"

    def test_break_fsync_fails_then_recovers(self, tmp_path):
        storage = SegmentedLogStorage(str(tmp_path / "log"))
        storage.append(b"block")
        DiskFaults.break_fsync(storage, times=2)
        with pytest.raises(OSError):
            storage.flush()
        with pytest.raises(OSError):
            storage.flush()
        storage.flush()  # restored
        storage.close()


# ---------------------------------------------------------------------------
# fixed-seed raft chaos: partition + leader crash + torn segment tail
# ---------------------------------------------------------------------------


class LeaderLedger:
    """Records every LEADER transition as (node, term) for invariant 2."""

    def __init__(self):
        self.entries = []

    def attach(self, raft):
        from zeebe_tpu.cluster import RaftState

        raft.on_state_change(
            lambda state, term, nid=raft.node_id: self.entries.append((nid, term))
            if state == RaftState.LEADER
            else None
        )

    def assert_at_most_one_leader_per_term(self):
        by_term = {}
        for node, term in self.entries:
            by_term.setdefault(term, set()).add(node)
        offenders = {t: nodes for t, nodes in by_term.items() if len(nodes) > 1}
        assert not offenders, f"multiple leaders in a term: {offenders}"


class TestChaosRaftFixedSeed:
    def _capture_acked(self, log, first: int, last: int, acked: dict) -> None:
        for pos in range(first, last + 1):
            record = log.record_at(pos)
            assert record is not None
            acked[pos] = (record.raft_term, getattr(record.value, "type", None))

    def test_partition_leader_crash_torn_tail(self, scheduler, tmp_path):
        """The acceptance scenario: background message chaos, a partial
        partition, a full partition forcing failover, a leader crash with
        a torn segment tail, restart, heal — then invariants 1 + 2."""
        plane = FaultPlane(seed=SEED)
        # background noise on every edge: seeded drops + reordering jitter
        plane.set_rule(drop=0.05, delay_ms=0, delay_jitter_ms=5)
        cluster = Cluster(scheduler, tmp_path, 3)
        ledger = LeaderLedger()
        try:
            for nid, raft in cluster.nodes.items():
                plane.register_endpoint(nid, raft.address)
                plane.install_client(raft.client, nid)
                ledger.attach(raft)
            leader = cluster.await_leader()
            lid = leader.node_id
            acked = {}

            # warm-up: the leader's initial no-op reaches every log before
            # chaos accounting starts (replication sessions established)
            assert wait_until(
                lambda: all(l.commit_position >= 0 for l in cluster.logs.values()),
                timeout=40,
            ), {nid: l.commit_position for nid, l in cluster.logs.items()}

            # phase 1: clean-ish appends (noise rule active) — all commit
            leader, last = append_with_retry(
                cluster, [job_record(i) for i in range(10)], timeout=30
            )
            assert wait_until(
                lambda: all(l.commit_position >= last for l in cluster.logs.values()),
                timeout=40,
            ), {nid: l.commit_position for nid, l in cluster.logs.items()}
            self._capture_acked(cluster.logs[leader.node_id], last - 9, last, acked)

            # phase 2: partial partition (leader cut off from ONE follower);
            # the remaining majority keeps committing
            lid = leader.node_id
            followers = [nid for nid in cluster.nodes if nid != lid]
            plane.partition(lid, followers[0])
            leader, last = append_with_retry(
                cluster, [job_record(100 + i) for i in range(10)], timeout=30
            )
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= last,
                timeout=40,
            )
            self._capture_acked(cluster.logs[leader.node_id], last - 9, last, acked)

            # phase 3: full partition of the leader, then crash it with a
            # torn tail; the connected majority elects a successor
            plane.heal()
            plane.isolate(lid)
            assert wait_until(
                lambda: any(
                    cluster.nodes[f].state.value == "leader" for f in followers
                ),
                timeout=40,
            ), {nid: n.state for nid, n in cluster.nodes.items()}
            crashed_log = cluster.logs[lid]
            crashed_dir = crashed_log.storage.directory
            cluster.nodes[lid].close()
            del cluster.nodes[lid]
            plane.heal(lid)

            torn0 = event_count("log_torn_tail_truncations")
            DiskFaults.tear_log_tail(crashed_dir, nbytes=11)

            # the successor keeps acking appends meanwhile
            leader, last = append_with_retry(
                cluster, [job_record(200 + i) for i in range(10)], timeout=30
            )
            assert wait_until(
                lambda: cluster.logs[leader.node_id].commit_position >= last,
                timeout=40,
            )
            self._capture_acked(cluster.logs[leader.node_id], last - 9, last, acked)

            # phase 4: restart the crashed node from its torn disk state —
            # recovery must truncate to the last whole record and rejoin
            from zeebe_tpu.cluster import Raft

            storage = SegmentedLogStorage(crashed_dir)
            log = LogStream(storage, partition_id=0, recover_commit=False)
            assert event_count("log_torn_tail_truncations") > torn0
            raft = Raft(
                lid,
                log,
                scheduler,
                config=FAST,
                storage_path=os.path.join(str(tmp_path), f"raft-{lid}.meta"),
            )
            cluster.nodes[lid] = raft
            cluster.logs[lid] = log
            ledger.attach(raft)
            plane.register_endpoint(lid, raft.address)
            plane.install_client(raft.client, lid)
            members = {nid: n.address for nid, n in cluster.nodes.items()}
            for node in cluster.nodes.values():
                node.bootstrap(members)

            leader, last = append_with_retry(
                cluster, [job_record(300 + i) for i in range(5)], timeout=30
            )
            assert wait_until(
                lambda: all(l.commit_position >= last for l in cluster.logs.values()),
                timeout=60,
            ), {nid: l.commit_position for nid, l in cluster.logs.items()}
            self._capture_acked(cluster.logs[leader.node_id], last - 4, last, acked)

            # invariant 1: every acked record survives identically everywhere
            for nid, log_ in cluster.logs.items():
                for pos, (term, jtype) in acked.items():
                    record = log_.record_at(pos)
                    assert record is not None, (nid, pos)
                    assert record.raft_term == term, (nid, pos)
                    assert getattr(record.value, "type", None) == jtype, (nid, pos)

            # invariant 2: at most one leader per term
            ledger.assert_at_most_one_leader_per_term()

            # the plane actually injected faults on this schedule
            verbs = {entry[3] for entry in plane.trace}
            assert "drop" in verbs or "drop-partition" in verbs
        finally:
            cluster.close()


# ---------------------------------------------------------------------------
# broker-level chaos: snapshot mid-commit crash + oracle replay parity
# ---------------------------------------------------------------------------


def order_process():
    from zeebe_tpu.models.bpmn.builder import Bpmn

    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


def _drained(server) -> bool:
    return server.next_read_position - 1 == server.log.commit_position


def _assert_oracle_parity(leader_broker):
    """Invariant 3: replay of the surviving committed log is deterministic
    bit-for-bit, and reconstructs the live leader's engine state."""
    import time as _time

    server = leader_broker.partitions[0]
    # settle: the log must be drained AND quiescent — a worker's last
    # in-flight async completion may commit AFTER the drain check, so
    # require the commit position to hold still across a settle window
    # before trusting the captured record set
    committed = []
    deadline = _time.monotonic() + 20
    while _time.monotonic() < deadline:
        before = server.log.commit_position
        _time.sleep(0.6)
        if server.log.commit_position != before or not _drained(server):
            continue
        committed = server.log.reader(0).read_committed()
        if committed and (
            committed[-1].position == server.engine.last_processed_position
        ):
            break
        committed = []
    assert committed, (server.next_read_position, server.log.commit_position)
    oracle_a = replay_oracle(committed)
    oracle_b = replay_oracle(committed)
    assert oracle_state_bytes(oracle_a) == oracle_state_bytes(oracle_b)
    live = server.engine
    assert set(oracle_a.jobs) == set(live.jobs)
    for key, job in live.jobs.items():
        assert oracle_a.jobs[key].state == job.state, key
    assert sorted(oracle_a.element_instances.instances) == sorted(
        live.element_instances.instances
    )
    assert oracle_a.last_processed_position == live.last_processed_position


class TestChaosBrokerFixedSeed:
    def test_mid_commit_snapshot_crash_converges(self, tmp_path):
        """Invariant 4: a crash between the snapshot swap's two renames is
        salvaged on restart, and restore + replay converges (the next
        instance completes end-to-end on the recovered state)."""
        from zeebe_tpu.log import stateser

        harness = ChaosHarness(str(tmp_path), n_brokers=1)
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {"paid": True},
            )
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 1, timeout=30)
            worker.close()

            broker = harness.brokers["b0"]
            broker.snapshot_all()
            server = broker.partitions[0]
            metas = server.snapshots.storage.list()
            assert metas, "snapshot_all produced no snapshot"
            meta = metas[0]

            # crash while REWRITING the same snapshot: old final set aside,
            # replacement never renamed in
            s0 = event_count("snapshot_salvage_events")
            DiskFaults.crash_snapshot_commit(
                server.snapshots.storage,
                meta,
                stateser.encode_state({"torn": True}),
                DiskFaults.CRASH_OLD_ASIDE,
            )
            client.close()
            client = None
            harness.crash("b0")
            harness.restart("b0")
            assert event_count("snapshot_salvage_events") - s0 >= 2
            harness.await_leaders()

            # recovered broker: the salvaged snapshot + replay serve traffic
            client = harness.client()
            done2 = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done2.append(rec.key) or {"paid": True},
            )
            client.create_instance("order-process")
            assert wait_until(lambda: len(done2) >= 1, timeout=30)
            worker.close()
            _assert_oracle_parity(harness.leader_of(0))
        finally:
            if client is not None:
                client.close()
            harness.close()

    def test_replay_parity_after_leader_crash(self, tmp_path):
        """Invariant 3 under failover: crash the partition leader mid-
        traffic (with seeded network jitter), restart it, finish the work,
        then prove the surviving committed log replays to the live state."""
        plane = FaultPlane(seed=SEED)
        plane.set_rule(delay_ms=0, delay_jitter_ms=3)  # reorder-y jitter
        harness = ChaosHarness(str(tmp_path), n_brokers=3, plane=plane)
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process())
            done = []
            worker = client.open_job_worker(
                "payment-service",
                lambda pid, rec: done.append(rec.key) or {"paid": True},
            )
            client.create_instance("order-process")
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 2, timeout=30), done

            old = harness.leader_of(0)
            old_id = old.node_id
            harness.crash(old_id)
            assert wait_until(
                lambda: harness.leader_of(0) is not None, timeout=30
            ), "no successor elected"
            new_leader = harness.leader_of(0)
            assert wait_until(
                lambda: new_leader.repository.latest("order-process") is not None,
                timeout=20,
            )
            harness.restart(old_id)

            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 3, timeout=30), done
            worker.close()
            _assert_oracle_parity(harness.leader_of(0))
        finally:
            if client is not None:
                client.close()
            harness.close()


# ---------------------------------------------------------------------------
# randomized sweep (slow): many seeds, probabilistic faults
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestChaosRandomizedSweep:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_invariants_hold_under_random_faults(self, scheduler, tmp_path, seed):
        plane = FaultPlane(seed=seed)
        plane.set_rule(drop=0.1, duplicate=0.05, delay_ms=1, delay_jitter_ms=8)
        cluster = Cluster(scheduler, tmp_path, 3)
        ledger = LeaderLedger()
        try:
            for nid, raft in cluster.nodes.items():
                plane.register_endpoint(nid, raft.address)
                plane.install_client(raft.client, nid)
                ledger.attach(raft)
            cluster.await_leader()
            acked = {}
            for batch in range(6):
                leader, last = append_with_retry(
                    cluster, [job_record(batch * 10 + i) for i in range(5)],
                    timeout=30,
                )
                assert wait_until(
                    lambda: cluster.logs[leader.node_id].commit_position >= last,
                    timeout=30,
                )
                log = cluster.logs[leader.node_id]
                for pos in range(last - 4, last + 1):
                    record = log.record_at(pos)
                    acked[pos] = (record.raft_term, getattr(record.value, "type", None))
            plane.clear_rules()
            leader, last = append_with_retry(cluster, [job_record(999)], timeout=30)
            assert wait_until(
                lambda: all(l.commit_position >= last for l in cluster.logs.values()),
                timeout=30,
            )
            for nid, log_ in cluster.logs.items():
                for pos, (term, jtype) in acked.items():
                    record = log_.record_at(pos)
                    assert record is not None, (nid, pos)
                    assert (record.raft_term, getattr(record.value, "type", None)) == (
                        term, jtype,
                    ), (nid, pos)
            ledger.assert_at_most_one_leader_per_term()
        finally:
            cluster.close()
