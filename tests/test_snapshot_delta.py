"""Dirty-delta snapshots: family tracking, capture/commit fence, delta-vs-
full bit-identity, GC safety, and crash-mid-delta-commit invariants
(zeebe_tpu/log/{stateser,snapshot}.py, engine dirty tracking).

The two invariants the tentpole adds to the chaos contract:
5. a delta-chain snapshot restores BIT-IDENTICALLY to a from-scratch full
   take of the same state, and
6. a crash mid-delta-commit never orphans the previous snapshot's
   referenced segments (the previous snapshot stays fully restorable,
   even across the GC sweep).
"""

import dataclasses
import os
import time

import numpy as np
import pytest

from zeebe_tpu.gateway import JobWorker, ZeebeClient
from zeebe_tpu.log import stateser
from zeebe_tpu.log.snapshot import (
    SnapshotController,
    SnapshotMetadata,
    SnapshotStorage,
    _SEGMENTS_DIR,
    part_hash,
)
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.runtime import Broker, ControlledClock
from zeebe_tpu.runtime.metrics import event_count
from zeebe_tpu.testing.chaos import DiskFaults


def order_process_model():
    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )


def _broker_with_traffic(tmp_path, n_instances=4):
    clock = ControlledClock(start_ms=1_000_000)
    data = str(tmp_path / "data")
    broker = Broker(num_partitions=1, data_dir=data, clock=clock)
    client = ZeebeClient(broker)
    client.deploy_model(order_process_model())
    JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
    for i in range(n_instances):
        client.create_instance("order-process", payload={"orderId": i})
    broker.run_until_idle()
    return broker, client, clock


def _age_segments(root, by_sec=3600.0):
    """Backdate every segment file past the GC grace window."""
    seg_dir = os.path.join(root, _SEGMENTS_DIR)
    past = time.time() - by_sec
    for name in os.listdir(seg_dir):
        os.utime(os.path.join(seg_dir, name), (past, past))


# ---------------------------------------------------------------------------
# host-engine dirty tracking
# ---------------------------------------------------------------------------


class TestHostDirtyTracking:
    def test_second_take_with_no_traffic_is_free(self, tmp_path):
        """Acceptance pin: unchanged state between two takes → the second
        take re-encodes nothing but the tiny root and reports
        new_bytes == 0."""
        broker, _, _ = _broker_with_traffic(tmp_path)
        try:
            broker.snapshot()
            first = dict(broker.partitions[0].snapshots.last_take_stats)
            assert first["new_bytes"] > 0  # cold take is full
            assert first["reused_parts"] == 0

            broker.snapshot()
            second = dict(broker.partitions[0].snapshots.last_take_stats)
            assert second["new_bytes"] == 0
            assert second["new_segments"] == 0
            assert second["total_bytes"] == first["total_bytes"]
            # every family part was reused from the previous manifest —
            # only _root was re-encoded
            assert second["reused_parts"] == second["parts"] - 1
        finally:
            broker.close()

    def test_take_cost_scales_with_the_delta(self, tmp_path):
        """Under traffic between takes, new bytes track the CHANGED
        families, not total state."""
        broker, client, _ = _broker_with_traffic(tmp_path, n_instances=16)
        try:
            broker.snapshot()
            total = broker.partitions[0].snapshots.last_take_stats["total_bytes"]

            # small delta: one more instance through the same workflow
            client.create_instance("order-process", payload={"orderId": 99})
            broker.run_until_idle()
            broker.snapshot()
            stats = dict(broker.partitions[0].snapshots.last_take_stats)
            assert stats["reused_parts"] >= 1  # e.g. clean workflows family
            assert 0 < stats["new_bytes"] < stats["total_bytes"]
            assert stats["new_bytes"] < total
        finally:
            broker.close()

    def test_family_marking_is_selective(self, tmp_path):
        """A message publish dirties the messages family but not the (much
        larger) instance family."""
        broker, client, _ = _broker_with_traffic(tmp_path)
        try:
            engine = broker.partitions[0].engine
            engine.snapshot_mark_clean()
            assert engine.snapshot_dirty_families() == frozenset()
            client.publish_message(
                "some-event", "corr-1", {"x": 1}, time_to_live_ms=60_000
            )
            broker.run_until_idle()
            dirty = engine.snapshot_dirty_families()
            assert "h/messages" in dirty
            assert "h/control" in dirty
            assert "h/instances" not in dirty
            assert "h/workflows" not in dirty
        finally:
            broker.close()

    def test_unknown_value_type_marks_everything(self, tmp_path):
        broker, _, _ = _broker_with_traffic(tmp_path, n_instances=1)
        try:
            engine = broker.partitions[0].engine
            engine.snapshot_mark_clean()
            engine._mark_dirty_for_record(9999)
            assert engine.snapshot_dirty_families() is None
        finally:
            broker.close()

    def test_delta_chain_bit_identical_to_full_take(self, tmp_path):
        """Invariant 5 (unit form): after a chain of delta takes, the
        on-disk parts equal a freshly encoded FULL snapshot of the live
        engine, byte for byte."""
        broker, client, _ = _broker_with_traffic(tmp_path)
        try:
            broker.snapshot()  # full base
            for i in range(3):  # delta chain with varied traffic
                client.create_instance("order-process", payload={"orderId": 100 + i})
                if i == 1:
                    client.publish_message("evt", f"k{i}", {}, time_to_live_ms=5_000)
                broker.run_until_idle()
                broker.snapshot()
            assert broker.partitions[0].snapshots.last_take_stats["reused_parts"] > 0

            partition = broker.partitions[0]
            newest = partition.snapshots.storage.list()[0]
            on_disk = partition.snapshots.storage.read_parts(newest)
            fresh = dict(stateser.encode_state_parts(partition.engine.snapshot_state()))
            assert on_disk == fresh
        finally:
            broker.close()

    def test_incident_resolve_delta_equals_full(self, tmp_path):
        """Regression (review finding): incident RESOLVE re-writes the
        failure event through _write_wi_followup, mutating the element
        instance index — the INCIDENT value type must dirty h/instances or
        the delta take reuses a stale instances segment."""
        clock = ControlledClock(start_ms=1_000_000)
        broker = Broker(num_partitions=1, data_dir=str(tmp_path / "d"), clock=clock)
        try:
            client = ZeebeClient(broker)
            # IO_MAPPING_ERROR on a SERVICE TASK: the failure event is the
            # task's ELEMENT_READY, a LIVE element instance whose value the
            # resolve rewrite mutates in place
            model = (
                Bpmn.create_process("flow")
                .start_event("s")
                .service_task("work", type="t", inputs=[("$.missing", "$.x")])
                .end_event("e")
                .done()
            )
            client.deploy_model(model)
            inst = client.create_instance("flow", {})  # missing variable
            broker.run_until_idle()
            broker.snapshot()  # base take under the OPEN incident

            from zeebe_tpu.protocol.enums import ValueType
            from zeebe_tpu.protocol.intents import IncidentIntent

            incident = [
                r for r in broker.records(0)
                if r.metadata.value_type == ValueType.INCIDENT
                and r.metadata.intent == int(IncidentIntent.CREATED)
            ][0]
            # process ONLY the RESOLVE command — its _write_wi_followup
            # mutates the element instance, and the take fence can land
            # BEFORE the re-written WI follow-up (which would also mark
            # h/instances) gets processed: exactly the uncovered window
            from zeebe_tpu.protocol.records import IncidentRecord

            partition = broker.partitions[0]
            engine = partition.engine
            broker.write_command(
                0,
                IncidentRecord(
                    workflow_instance_key=inst.workflow_instance_key,
                    activity_instance_key=incident.value.activity_instance_key,
                    payload={"missing": 500},
                ),
                IncidentIntent.RESOLVE,
                key=incident.key,
                with_response=False,
            )
            resolve = partition.log.reader(partition.next_read_position)
            record = resolve.read_committed()[0]
            engine.process(record)  # follow-ups deliberately NOT applied
            instance = engine.element_instances.get(
                incident.value.activity_instance_key
            )
            assert instance is not None and instance.value.payload.get(
                "missing") == 500, "fixture must mutate the instance"

            meta = SnapshotMetadata(record.position, record.position, 0)
            partition.snapshots.take_engine(engine, meta)  # delta take
            assert partition.snapshots.last_take_stats["reused_parts"] > 0
            on_disk = partition.snapshots.storage.read_parts(meta)
            fresh = dict(stateser.encode_state_parts(engine.snapshot_state()))
            assert on_disk == fresh  # bit-identical incl. h/instances
        finally:
            broker.close()

    def test_restored_broker_resumes_after_delta_chain(self, tmp_path):
        broker, client, clock = _broker_with_traffic(tmp_path)
        data = broker.data_dir
        try:
            broker.snapshot()
            client.create_instance("order-process", payload={"orderId": 50})
            broker.run_until_idle()
            broker.snapshot()  # delta take; compaction runs below it
            live = stateser.encode_host_state(
                broker.partitions[0].engine.snapshot_state()
            )
        finally:
            broker.close()
        broker = Broker(num_partitions=1, data_dir=data, clock=clock)
        try:
            broker.run_until_idle()
            restored = stateser.encode_host_state(
                broker.partitions[0].engine.snapshot_state()
            )
            assert restored == live
            # and the restored engine keeps serving
            client = ZeebeClient(broker)
            JobWorker(broker, "payment-service", lambda ctx: None)
            client.create_instance("order-process")
            broker.run_until_idle()
        finally:
            broker.close()

    def test_commit_failure_remarks_dirty_and_next_take_is_full(
        self, tmp_path, monkeypatch
    ):
        """The capture fence resets tracking; a failed commit must merge
        the captured families back so nothing is lost, and the delta base
        is dropped (unknown on-disk state ⇒ full take next)."""
        broker, client, _ = _broker_with_traffic(tmp_path)
        try:
            broker.snapshot()
            client.create_instance("order-process", payload={"orderId": 7})
            broker.run_until_idle()
            controller = broker.partitions[0].snapshots
            engine = broker.partitions[0].engine

            def boom(*a, **k):
                raise OSError("injected fsync failure")

            monkeypatch.setattr(controller.storage, "_write_segment", boom)
            with pytest.raises(OSError):
                broker.snapshot()
            monkeypatch.undo()
            dirty = engine.snapshot_dirty_families()
            assert dirty is None or "h/instances" in dirty

            broker.snapshot()  # full again (delta base dropped), succeeds
            stats = controller.last_take_stats
            assert stats["reused_parts"] == 0
            newest = controller.storage.list()[0]
            on_disk = controller.storage.read_parts(newest)
            fresh = dict(stateser.encode_state_parts(engine.snapshot_state()))
            assert on_disk == fresh
        finally:
            broker.close()


# ---------------------------------------------------------------------------
# device-engine dirty tracking
# ---------------------------------------------------------------------------


def _device_engine(n_jobs=4, capacity=256):
    """Device engine with synthetic device-table jobs + one credited
    subscription (no kernel dispatch needed)."""
    import jax.numpy as jnp

    from zeebe_tpu.protocol.intents import JobIntent as JI
    from zeebe_tpu.tpu.engine import TpuPartitionEngine

    eng = TpuPartitionEngine(capacity=capacity, sub_capacity=8)
    s = eng.state
    tid = eng.interns.intern("work")
    job_i32 = np.asarray(s.job_i32).copy()
    job_i64 = np.asarray(s.job_i64).copy()
    for i in range(n_jobs):
        job_i32[i] = (int(JI.CREATED), 0, 0, tid, 3, 0)
        job_i64[i] = (100 + 5 * i, -1, -1, -1)
    sub_key = np.asarray(s.sub_key).copy()
    sub_type = np.asarray(s.sub_type).copy()
    sub_worker = np.asarray(s.sub_worker).copy()
    sub_credits = np.asarray(s.sub_credits).copy()
    sub_timeout = np.asarray(s.sub_timeout).copy()
    sub_valid = np.asarray(s.sub_valid).copy()
    sub_key[0], sub_type[0] = 1, tid
    sub_worker[0] = eng.interns.intern("w-1")
    sub_credits[0], sub_timeout[0], sub_valid[0] = 10, 1000, True
    eng.state = dataclasses.replace(
        s,
        job_i32=jnp.asarray(job_i32), job_i64=jnp.asarray(job_i64),
        sub_key=jnp.asarray(sub_key), sub_type=jnp.asarray(sub_type),
        sub_worker=jnp.asarray(sub_worker),
        sub_credits=jnp.asarray(sub_credits),
        sub_timeout=jnp.asarray(sub_timeout),
        sub_valid=jnp.asarray(sub_valid),
    )
    return eng


class TestDeviceDirtyTracking:
    def test_second_take_does_zero_device_readback(self, tmp_path):
        """Acceptance pin: with unchanged state, the second take performs
        ZERO device→host readback (no np.asarray of any table) and
        new_bytes == 0."""
        eng = _device_engine()
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        controller.take_engine(eng, SnapshotMetadata(10, 12, 1))
        assert len(eng.last_snapshot_readback) > 0  # cold take read all

        controller.take_engine(eng, SnapshotMetadata(20, 22, 1))
        assert eng.last_snapshot_readback == []
        stats = controller.last_take_stats
        assert stats["new_bytes"] == 0
        assert stats["new_segments"] == 0
        assert stats["reused_parts"] > 0

    def test_tick_mutation_reads_back_only_its_family(self, tmp_path):
        eng = _device_engine()
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        controller.take_engine(eng, SnapshotMetadata(10, 12, 1))

        out = eng.device_backlog_activations()  # mutates sub credits/cursor
        assert out, "fixture must assign at least one backlog job"
        assert eng.snapshot_dirty_families() == frozenset({"d/sub"})
        controller.take_engine(eng, SnapshotMetadata(20, 22, 1))
        read = set(eng.last_snapshot_readback)
        assert read, "the dirty sub family must be re-read"
        assert all(name.startswith("sub_") for name in read), read
        # the big ei/job/payload tables were NOT transferred
        assert not any(name.startswith(("ei_", "job_", "msg_")) for name in read)

    def test_kernel_dispatch_marks_all_device_families_not_cold(self):
        """A wave dispatch dirties every DEVICE family but must keep the
        HOST family tracking live — else every serving wave degrades the
        next take to fully-full (clean host bulk like workflows would be
        re-encoded every period)."""
        from zeebe_tpu.tpu.engine import TpuPartitionEngine

        assert set(TpuPartitionEngine._ALL_DEVICE_FAMILIES) == set(
            stateser.DEVICE_ARRAY_FAMILIES
        )
        eng = _device_engine()
        eng.snapshot_mark_clean()
        eng._mark_device_dirty()  # what _dispatch_device does per wave
        dirty = eng.snapshot_dirty_families()
        assert dirty is not None, "dispatch must not collapse tracking to cold"
        assert {"d/" + f for f in stateser.DEVICE_ARRAY_FAMILIES} <= set(dirty)
        assert "h/workflows" not in dirty

    def test_device_delta_restores_bit_identically(self, tmp_path):
        eng = _device_engine()
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        controller.take_engine(eng, SnapshotMetadata(10, 12, 1))
        eng.device_backlog_activations()
        eng.increase_job_credits(1, 5)
        controller.take_engine(eng, SnapshotMetadata(20, 22, 1))
        assert controller.last_take_stats["reused_parts"] > 0

        newest = controller.storage.list()[0]
        on_disk = controller.storage.read_parts(newest)
        fresh = dict(stateser.encode_state_parts(eng.snapshot_state()))
        assert on_disk == fresh
        # and the streamed restore reassembles the exact bytes
        state, meta = controller.recover(log_last_position=100)
        assert meta == SnapshotMetadata(20, 22, 1)
        assert dict(stateser.encode_state_parts(state)) == on_disk


# ---------------------------------------------------------------------------
# gc_segments edge cases (satellite)
# ---------------------------------------------------------------------------


class TestSegmentGc:
    def _controller(self, tmp_path):
        return SnapshotController(SnapshotStorage(str(tmp_path)))

    def test_young_unreferenced_segment_survives_grace(self, tmp_path):
        """An unreferenced segment younger than the grace window may belong
        to an install whose manifest has not committed yet — kept."""
        storage = SnapshotStorage(str(tmp_path))
        storage.write_parts(
            SnapshotMetadata(5, 6, 0), stateser.encode_state_parts({"v": 1})
        )
        # an in-flight install's segment: present, referenced by nothing
        orphan = part_hash(b"in-flight-part")
        storage._write_segment(orphan, b"x" * 8)
        assert storage.gc_segments() == 0
        assert storage.has_segment(orphan)

    def test_old_unreferenced_segment_is_reaped(self, tmp_path):
        storage = SnapshotStorage(str(tmp_path))
        storage.write_parts(
            SnapshotMetadata(5, 6, 0), stateser.encode_state_parts({"v": 1})
        )
        orphan = part_hash(b"dead-part")
        storage._write_segment(orphan, b"x" * 8)
        _age_segments(str(tmp_path))
        assert storage.gc_segments() >= 1
        assert not storage.has_segment(orphan)
        # referenced segments of the committed snapshot survived the sweep
        state, _ = SnapshotController(storage).recover(log_last_position=100)
        assert state == {"v": 1}

    def test_segment_referenced_only_by_newest_manifest_survives(self, tmp_path):
        """Mid-delta-chain safety: a segment first referenced by the NEWEST
        manifest (a delta's fresh family) is never collected, however old
        the file is."""
        controller = self._controller(tmp_path)
        controller.take({"v": 1}, SnapshotMetadata(5, 6, 0))
        controller.take({"v": 2}, SnapshotMetadata(9, 11, 0))
        _age_segments(str(tmp_path))
        controller.storage.gc_segments()
        state, meta = controller.recover(log_last_position=100)
        assert state == {"v": 2}
        assert meta == SnapshotMetadata(9, 11, 0)


# ---------------------------------------------------------------------------
# crash mid-delta-commit (invariant 6) + recovery skip accounting
# ---------------------------------------------------------------------------


class TestCrashMidDeltaCommit:
    @pytest.mark.parametrize("point", [
        DiskFaults.CRASH_SEGMENTS_WRITTEN,
        DiskFaults.CRASH_TMP_WRITTEN,
        DiskFaults.CRASH_OLD_ASIDE,
        DiskFaults.CRASH_SWAPPED,
    ])
    def test_previous_snapshot_survives_crash_and_gc(self, tmp_path, point):
        """Whatever instant a delta commit dies at, the PREVIOUS snapshot's
        referenced segments survive the restart sweep + GC and it restores
        bit-identically."""
        storage = SnapshotStorage(str(tmp_path))
        controller = SnapshotController(storage)
        base_state = {"v": 1, "bulk": "x" * 4096}
        controller.take(base_state, SnapshotMetadata(5, 6, 0))
        base_parts = storage.read_parts(SnapshotMetadata(5, 6, 0))

        delta_parts = stateser.encode_state_parts({"v": 2, "bulk": "y" * 4096})
        DiskFaults.crash_manifest_commit(
            storage, SnapshotMetadata(9, 11, 0), delta_parts, [], point
        )

        # restart: open sweeps orphans, then GC past the grace window
        reopened = SnapshotStorage(str(tmp_path))
        _age_segments(str(tmp_path))
        reopened.gc_segments()
        state, meta = SnapshotController(reopened).recover(log_last_position=100)
        if point in (DiskFaults.CRASH_SEGMENTS_WRITTEN, DiskFaults.CRASH_TMP_WRITTEN):
            # the delta never committed: the base must be fully intact
            assert meta == SnapshotMetadata(5, 6, 0)
            assert state == base_state
            assert reopened.read_parts(SnapshotMetadata(5, 6, 0)) == base_parts
        else:
            # CRASH_OLD_ASIDE restores the set-aside base; CRASH_SWAPPED
            # committed the delta — either way recovery converges on a
            # complete snapshot with no missing segments
            assert state in (base_state, {"v": 2, "bulk": "y" * 4096})
            assert meta in (SnapshotMetadata(5, 6, 0), SnapshotMetadata(9, 11, 0))


class TestRecoverSkipAccounting:
    def test_skipped_snapshot_warns_and_counts(self, tmp_path, caplog):
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        controller.take({"v": 1}, SnapshotMetadata(5, 6, 0))
        # corrupt a NEWER manifest snapshot: delete one of its segments
        newer = SnapshotMetadata(9, 11, 0)
        controller.storage.write_parts(
            newer, stateser.encode_state_parts({"v": 2})
        )
        seg_dir = os.path.join(str(tmp_path), _SEGMENTS_DIR)
        older_hashes = {
            e["h"] for e in controller.storage.manifest(SnapshotMetadata(5, 6, 0))
        }
        unique = [
            e for e in controller.storage.manifest(newer)
            if e["h"] not in older_hashes
        ]
        os.unlink(os.path.join(seg_dir, unique[0]["h"] + ".seg"))

        before = event_count("snapshot_recover_skipped")
        import logging

        with caplog.at_level(logging.WARNING, logger="zeebe_tpu.log.snapshot"):
            state, meta = controller.recover(log_last_position=100)
        assert state == {"v": 1}
        assert event_count("snapshot_recover_skipped") == before + 1
        assert any(
            newer.dirname in rec.getMessage() for rec in caplog.records
        ), "the warn log must NAME the skipped snapshot"


# ---------------------------------------------------------------------------
# snapshot-while-serving (cluster path)
# ---------------------------------------------------------------------------


class TestSnapshotWhileServing:
    def _boot(self, tmp_path):
        from zeebe_tpu.testing.chaos import ChaosHarness

        harness = ChaosHarness(str(tmp_path), n_brokers=1)
        harness.await_leaders()
        client = harness.client()
        client.deploy_model(order_process_model())
        done = []
        worker = client.open_job_worker(
            "payment-service", lambda pid, rec: done.append(rec.key) or {"ok": 1}
        )
        return harness, client, worker, done

    def test_wave_drain_completes_while_take_in_flight(self, tmp_path):
        """Acceptance pin: serving continues during encode/commit — a
        workflow completes end-to-end while a snapshot commit is wedged on
        its worker thread; the capture pause stays bounded; at most one
        take is in flight (the overlapping one is skipped + counted)."""
        import threading

        from tests.test_raft import wait_until
        from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY

        harness, client, worker, done = self._boot(tmp_path)
        try:
            broker = harness.brokers["b0"]
            server = broker.partitions[0]
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 1, timeout=30)
            broker.snapshot_all()  # full base (synchronous, commits joined)

            # dirty some families, then wedge the next commit's segment
            # write so the take stays in flight
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 2, timeout=30)
            gate = threading.Event()
            entered = threading.Event()
            storage = server.snapshots.storage
            real_write = storage._write_segment

            def slow_write(h, compressed):
                entered.set()
                assert gate.wait(30), "test gate never released"
                real_write(h, compressed)

            storage._write_segment = slow_write
            try:
                thread = broker.actor.call(server.snapshot).join(10)
                assert thread is not None
                assert entered.wait(10), "commit never reached the storage"
                assert server._snapshot_inflight

                # serving continues while the take is in flight: a fresh
                # workflow must complete end-to-end
                client.create_instance("order-process")
                assert wait_until(lambda: len(done) >= 3, timeout=30)

                # the guard: a second take while one is in flight is
                # skipped and counted
                before = event_count("snapshot_skipped_inflight")
                assert broker.actor.call(server.snapshot).join(10) is None
                assert event_count("snapshot_skipped_inflight") == before + 1
            finally:
                gate.set()
            thread.join(20)
            assert not thread.is_alive()
            storage._write_segment = real_write
            assert not server._snapshot_inflight

            # the in-flight take committed; capture pause was reported and
            # bounded (the wedged 30s gate was commit-side, not capture)
            pause = GLOBAL_REGISTRY.gauge("snapshot_capture_pause_seconds").value
            assert 0 < pause < 5.0
            stats = server.snapshots.last_take_stats
            assert stats["reused_parts"] > 0  # it was a delta take
        finally:
            worker.close()
            client.close()
            harness.close()

    def test_partition_take_failure_is_isolated(self, tmp_path):
        """Satellite: a raising take on one partition must not abort
        _snapshot_all_on_actor for the rest (break_fsync-style storage
        failure on partition 0; partition 1 still checkpoints)."""
        from tests.test_raft import wait_until
        from zeebe_tpu.testing.chaos import ChaosHarness

        harness = ChaosHarness(str(tmp_path), n_brokers=1, partitions=2)
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process_model())
            done = []
            worker = client.open_job_worker(
                "payment-service", lambda pid, rec: done.append(rec.key) or {}
            )
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 1, timeout=30)
            worker.close()

            broker = harness.brokers["b0"]
            p0 = broker.partitions[0]

            def boom(*a, **k):
                raise OSError("injected fsync failure")

            p0.snapshots.storage._write_segment = boom
            failures_before = event_count("snapshot_take_failures")
            broker.snapshot_all()  # must not raise
            assert wait_until(
                lambda: event_count("snapshot_take_failures") > failures_before,
                timeout=10,
            )
            # the OTHER partition still checkpointed
            assert broker.partitions[1].snapshots.storage.list()
        finally:
            if client is not None:
                client.close()
            harness.close()

    def test_delta_chain_crash_restore_parity(self, tmp_path):
        """Chaos invariant 5 (cluster form): crash-stop after a chain of
        delta takes; the restarted broker restores from the delta-chain
        snapshot and its state matches the replay oracle exactly."""
        from tests.test_chaos import _assert_oracle_parity
        from tests.test_raft import wait_until

        harness, client, worker, done = self._boot(tmp_path)
        try:
            broker = harness.brokers["b0"]
            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 1, timeout=30)
            broker.snapshot_all()  # full base

            client.create_instance("order-process")
            assert wait_until(lambda: len(done) >= 2, timeout=30)
            broker.snapshot_all()  # delta take
            server = broker.partitions[0]
            assert server.snapshots.last_take_stats["reused_parts"] > 0

            client.close()
            client = None
            worker.close()
            worker = None
            harness.crash("b0")
            harness.restart("b0")
            harness.await_leaders()

            # recovered broker serves new traffic on the restored state
            client = harness.client()
            done2 = []
            worker = client.open_job_worker(
                "payment-service", lambda pid, rec: done2.append(rec.key) or {}
            )
            client.create_instance("order-process")
            assert wait_until(lambda: len(done2) >= 1, timeout=30)
            _assert_oracle_parity(harness)
        finally:
            if worker is not None:
                worker.close()
            if client is not None:
                client.close()
            harness.close()


# ---------------------------------------------------------------------------
# million-instance-scale lifecycle sweep (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestLargeResidentStateSweep:
    """ROADMAP item 5 at scale: snapshot/restore + crash sweeps under LARGE
    resident device state (≥100k instances). Slow tier; the same paths run
    tier-1 at small scale above."""

    N = 1 << 17  # 131072 rows ≥ 100k instances

    def _big_engine(self):
        import jax.numpy as jnp

        from zeebe_tpu.protocol.intents import JobIntent as JI
        from zeebe_tpu.tpu.engine import TpuPartitionEngine

        eng = TpuPartitionEngine(capacity=self.N, sub_capacity=8)
        s = eng.state
        n = self.N - 8  # a few free slots so backlog ticks stay cheap
        rows = np.arange(n)
        ei_i32 = np.asarray(s.ei_i32).copy()
        ei_i64 = np.asarray(s.ei_i64).copy()
        ei_i32[:n, 0] = 3            # elem
        ei_i32[:n, 1] = 2            # lifecycle state
        ei_i64[:n, 0] = 100 + 5 * rows   # key
        ei_i64[:n, 1] = 100 + 5 * rows   # workflowInstanceKey
        tid = eng.interns.intern("work")
        job_i32 = np.asarray(s.job_i32).copy()
        job_i64 = np.asarray(s.job_i64).copy()
        job_i32[:n, 0] = int(JI.CREATED)
        job_i32[:n, 3] = tid
        job_i32[:n, 4] = 3
        job_i64[:n, 0] = 102 + 5 * rows
        job_i64[:n, 1] = 100 + 5 * rows
        sub_key = np.asarray(s.sub_key).copy()
        sub_type = np.asarray(s.sub_type).copy()
        sub_credits = np.asarray(s.sub_credits).copy()
        sub_timeout = np.asarray(s.sub_timeout).copy()
        sub_valid = np.asarray(s.sub_valid).copy()
        sub_key[0], sub_type[0] = 1, tid
        sub_credits[0], sub_timeout[0], sub_valid[0] = 64, 1000, True
        eng.state = dataclasses.replace(
            s,
            ei_i32=jnp.asarray(ei_i32), ei_i64=jnp.asarray(ei_i64),
            job_i32=jnp.asarray(job_i32), job_i64=jnp.asarray(job_i64),
            sub_key=jnp.asarray(sub_key), sub_type=jnp.asarray(sub_type),
            sub_credits=jnp.asarray(sub_credits),
            sub_timeout=jnp.asarray(sub_timeout),
            sub_valid=jnp.asarray(sub_valid),
        )
        return eng

    def test_delta_take_and_bounded_restore_at_scale(self, tmp_path):
        import time as _time

        eng = self._big_engine()
        controller = SnapshotController(SnapshotStorage(str(tmp_path)))
        t0 = _time.perf_counter()
        controller.take_engine(eng, SnapshotMetadata(10, 12, 1))
        full_seconds = _time.perf_counter() - t0
        full = dict(controller.last_take_stats)
        assert full["total_bytes"] > 10 * self.N  # the state is actually big

        # a tick-sized mutation, then the delta take: cost tracks the
        # DELTA, not the ~100k-instance resident state
        out = eng.device_backlog_activations()
        assert out
        t0 = _time.perf_counter()
        controller.take_engine(eng, SnapshotMetadata(20, 22, 1))
        delta_seconds = _time.perf_counter() - t0
        delta = dict(controller.last_take_stats)
        assert delta["total_bytes"] == full["total_bytes"]
        assert delta["new_bytes"] < full["total_bytes"] // 50
        assert set(eng.last_snapshot_readback) <= {
            "sub_key", "sub_type", "sub_worker", "sub_credits",
            "sub_timeout", "sub_valid", "sub_rr",
        }
        # delta takes must not be slower than full ones at scale
        assert delta_seconds < max(full_seconds, 1.0)

        # bounded restore: streamed decode reassembles the exact bytes
        t0 = _time.perf_counter()
        state, meta = controller.recover(log_last_position=100)
        restore_seconds = _time.perf_counter() - t0
        assert meta == SnapshotMetadata(20, 22, 1)
        on_disk = controller.storage.read_parts(meta)
        assert dict(stateser.encode_state_parts(state)) == on_disk
        assert restore_seconds < 120  # bounded, reported via the gauge

    @pytest.mark.parametrize("point", [
        DiskFaults.CRASH_SEGMENTS_WRITTEN,
        DiskFaults.CRASH_SWAPPED,
    ])
    def test_crash_mid_delta_commit_at_scale(self, tmp_path, point):
        eng = self._big_engine()
        storage = SnapshotStorage(str(tmp_path))
        controller = SnapshotController(storage)
        controller.take_engine(eng, SnapshotMetadata(10, 12, 1))
        base_parts = storage.read_parts(SnapshotMetadata(10, 12, 1))

        eng.device_backlog_activations()
        pending = controller.capture(eng, SnapshotMetadata(20, 22, 1))
        DiskFaults.crash_manifest_commit(
            storage, pending.metadata, pending.parts, pending.reused, point
        )
        reopened = SnapshotStorage(str(tmp_path))
        _age_segments(str(tmp_path))
        reopened.gc_segments()
        state, meta = SnapshotController(reopened).recover(log_last_position=100)
        assert state is not None
        if point == DiskFaults.CRASH_SEGMENTS_WRITTEN:
            assert meta == SnapshotMetadata(10, 12, 1)
            assert reopened.read_parts(meta) == base_parts
        else:
            assert meta == SnapshotMetadata(20, 22, 1)
        # whichever snapshot won, every referenced segment survived GC
        assert dict(stateser.encode_state_parts(state)) == reopened.read_parts(meta)


# ---------------------------------------------------------------------------
# scenario storms (ROADMAP item 5): message-TTL + incident create/resolve
# chaos sweeps — tier-1 at small scale, slow tier larger
# ---------------------------------------------------------------------------


def _ttl_storm(broker_harness_client, n_messages, ttl_ms=400):
    harness, client = broker_harness_client
    for i in range(n_messages):
        client.publish_message(
            "storm-evt", f"corr-{i}", {"i": i}, time_to_live_ms=ttl_ms
        )
    return harness.leader_of(0)


class TestScenarioStorms:
    def _messages_alive(self, harness):
        leader = harness.leader_of(0)
        if leader is None:
            return -1
        server = leader.partitions[0]
        if server.engine is None:
            return -1
        return len(server.engine.messages)

    def _run_ttl_storm(self, tmp_path, n_messages):
        """Publish a burst of short-TTL messages with no subscriptions,
        snapshot mid-storm, crash-stop the broker, and require: the TTL
        sweep drains the store to empty on the restarted broker, and replay
        parity holds (expiry DELETEs are ordinary committed records)."""
        from tests.test_chaos import _assert_oracle_parity
        from tests.test_raft import wait_until
        from zeebe_tpu.testing.chaos import ChaosHarness

        harness = ChaosHarness(str(tmp_path), n_brokers=1)
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_process_model())
            _ttl_storm((harness, client), n_messages)
            broker = harness.brokers["b0"]
            broker.snapshot_all()  # mid-storm take (messages family dirty)
            stats = broker.partitions[0].snapshots.last_take_stats
            assert stats["new_bytes"] > 0

            client.close()
            client = None
            harness.crash("b0")
            harness.restart("b0")
            harness.await_leaders()
            # the restored broker's TTL sweep must expire the storm fully
            assert wait_until(
                lambda: self._messages_alive(harness) == 0, timeout=60
            ), f"{self._messages_alive(harness)} messages never expired"
            _assert_oracle_parity(harness)
        finally:
            if client is not None:
                client.close()
            harness.close()

    def _run_incident_storm(self, tmp_path, n_instances):
        """Create a wave of instances that all raise CONDITION_ERROR
        incidents (missing variable), snapshot under open incidents, crash,
        restart, then resolve every incident via payload update — every
        instance must complete, and replay parity holds."""
        from tests.test_chaos import _assert_oracle_parity
        from tests.test_raft import wait_until
        from zeebe_tpu.models.bpmn.builder import Bpmn
        from zeebe_tpu.protocol.enums import RecordType, ValueType
        from zeebe_tpu.protocol.intents import IncidentIntent
        from zeebe_tpu.testing.chaos import ChaosHarness

        b = (
            Bpmn.create_process("storm-flow")
            .start_event("s")
            .exclusive_gateway("split")
        )
        b.branch("$.orderValue >= 100").service_task(
            "insured", type="insured-t"
        ).end_event("e1")
        b.branch(default=True).service_task(
            "plain", type="plain-t"
        ).end_event("e2")
        model = b.done()

        harness = ChaosHarness(str(tmp_path), n_brokers=1)
        client = None
        workers = []
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(model)
            done = []
            for jt in ("insured-t", "plain-t"):
                workers.append(client.open_job_worker(
                    jt, lambda pid, rec: done.append(rec.key) or {}
                ))
            instances = [
                client.create_instance("storm-flow", {})  # missing variable
                for _ in range(n_instances)
            ]

            def created_incidents():
                leader = harness.leader_of(0)
                if leader is None or leader.partitions[0].engine is None:
                    return []
                return [
                    r for r in leader.partitions[0].log.reader(0).read_committed()
                    if r.metadata.value_type == ValueType.INCIDENT
                    and r.metadata.record_type == RecordType.EVENT
                    and r.metadata.intent == int(IncidentIntent.CREATED)
                ]

            assert wait_until(
                lambda: len(created_incidents()) >= n_instances, timeout=60
            )
            broker = harness.brokers["b0"]
            broker.snapshot_all()  # take under open incidents

            client.close()
            client = None
            for w in workers:
                w.close()
            workers = []
            harness.crash("b0")
            harness.restart("b0")
            harness.await_leaders()

            client = harness.client()
            for jt in ("insured-t", "plain-t"):
                workers.append(client.open_job_worker(
                    jt, lambda pid, rec: done.append(rec.key) or {}
                ))
            # resolve the storm: payload update at each failed token
            for inc in created_incidents():
                client.update_payload(
                    0, inc.value.workflow_instance_key,
                    {"orderValue": 500},
                    activity_instance_key=inc.value.activity_instance_key,
                )
            assert wait_until(
                lambda: len(done) >= n_instances, timeout=90
            ), f"only {len(done)}/{n_instances} storm instances completed"
            _assert_oracle_parity(harness)
        finally:
            for w in workers:
                w.close()
            if client is not None:
                client.close()
            harness.close()

    def test_message_ttl_storm_small(self, tmp_path):
        self._run_ttl_storm(tmp_path, n_messages=24)

    def test_incident_storm_small(self, tmp_path):
        self._run_incident_storm(tmp_path, n_instances=8)

    @pytest.mark.slow
    def test_message_ttl_storm_large(self, tmp_path):
        self._run_ttl_storm(tmp_path, n_messages=512)

    @pytest.mark.slow
    def test_incident_storm_large(self, tmp_path):
        self._run_incident_storm(tmp_path, n_instances=128)
