"""Continuous-batching wave scheduler + gateway admission control.

The scheduler is a PACKING change, not a semantics change — so the pins
are structural (DRR fairness, backpressure bounds, shared fill) plus the
hard contract: every partition's log stays BIT-IDENTICAL to the
per-partition baseline drain, for both engines. Admission is pinned at
the unit level (bounds, release, close cleanup) and end-to-end (a shed
command is retryable and eventually lands).
"""

import itertools
import threading
import time

import pytest

from zeebe_tpu.protocol import codec
from zeebe_tpu.runtime import Broker, ControlledClock
from zeebe_tpu.runtime.metrics import GLOBAL_REGISTRY, event_count
from zeebe_tpu.scheduler import (
    AdmissionConfig,
    AdmissionController,
    PartitionFeed,
    WaveScheduler,
)


# ---------------------------------------------------------------------------
# unit level: DRR packing, backpressure, rewind
# ---------------------------------------------------------------------------


class _Rec:
    __slots__ = ("position", "pid")

    def __init__(self, position, pid):
        self.position = position
        self.pid = pid


class FakeFeed(PartitionFeed):
    """A queue-backed feed; dispatch collects per-wave history so the
    packing itself is assertable."""

    def __init__(self, pid, n, pipelined=False, fail_dispatch=False):
        self.partition_id = pid
        self.cursor = 0
        self.limit_n = n
        self.pipelined = pipelined
        self.fail_dispatch = fail_dispatch
        self.dispatched = []  # list of lists (per segment)
        self.collected = []
        self.rewound_to = None

    def backlog(self):
        return self.limit_n - self.cursor

    def take(self, limit):
        take = min(limit, self.limit_n - self.cursor)
        if take <= 0:
            return []
        out = [_Rec(self.cursor + i, self.partition_id) for i in range(take)]
        self.cursor += take
        return out

    def dispatch(self, records):
        if self.fail_dispatch:
            raise RuntimeError("engine exploded")
        self.dispatched.append(list(records))
        if self.pipelined:
            return list(records), 0.0, 0.0
        return None, 0.0, 0.0

    def collect(self, pending):
        self.collected.append(list(pending))
        return 0.0, 0.0

    def rewind(self, position):
        self.rewound_to = position
        self.cursor = min(self.cursor, position)


class TestWavePacking:
    def test_shared_wave_packs_all_sparse_partitions(self):
        """Four sparse partitions → ONE shared wave, not four tiny ones
        (the whole point: fill at any traffic mix)."""
        ws = WaveScheduler(wave_size=512)
        feeds = [FakeFeed(pid, 16) for pid in range(4)]
        for f in feeds:
            ws.register(f)
        shared_before = GLOBAL_REGISTRY.counter(
            "scheduler_shared_waves_total"
        ).value
        total = ws.drain()
        assert total == 64
        for f in feeds:
            assert len(f.dispatched) == 1  # one segment per feed
            assert len(f.dispatched[0]) == 16
        assert (
            GLOBAL_REGISTRY.counter("scheduler_shared_waves_total").value
            - shared_before
            == 1
        )
        # the traffic-mix gauge saw all four sources
        assert GLOBAL_REGISTRY.gauge("serving_wave_sources").value == 4

    def test_drr_fairness_deep_backlog_cannot_starve_sparse_feeds(self):
        """A 10k-record partition shares every wave with the 10-record
        ones: the sparse feeds fully drain within the first wave."""
        ws = WaveScheduler(wave_size=256, quantum=32)
        big = FakeFeed(0, 10_000)
        smalls = [FakeFeed(pid, 10) for pid in (1, 2, 3)]
        ws.register(big)
        for f in smalls:
            ws.register(f)
        ws.drain(max_records=256)
        for f in smalls:
            assert f.cursor == 10, "sparse feed starved by the deep backlog"
        # and the big feed got the remaining room, not the whole wave
        assert 0 < big.cursor < 256

    def test_per_partition_order_is_cursor_order(self):
        ws = WaveScheduler(wave_size=64, quantum=8)
        feeds = [FakeFeed(pid, 100) for pid in range(3)]
        for f in feeds:
            ws.register(f)
        ws.drain()
        for f in feeds:
            seen = [r.position for seg in f.dispatched for r in seg]
            assert seen == sorted(seen) == list(range(100))

    def test_backpressure_skips_and_resumes(self):
        """A pipelined feed at its in-flight cap is skipped (counted) but
        drains fully once collects catch up."""
        ws = WaveScheduler(wave_size=16, quantum=16, backpressure_limit=16)
        feed = FakeFeed(0, 100, pipelined=True)
        ws.register(feed)
        skips_before = event_count("scheduler_backpressure_skips")
        ws.drain()
        assert feed.cursor == 100
        assert sum(len(c) for c in feed.collected) == 100
        assert event_count("scheduler_backpressure_skips") > skips_before

    def test_backpressure_bounds_records_within_one_wave(self):
        """Records packed into the wave BEING BUILT count against the
        in-flight cap: DRR revisits across rounds must not assemble a
        segment larger than the configured apply-side bound."""
        ws = WaveScheduler(wave_size=512, quantum=64, backpressure_limit=64)
        feed = FakeFeed(0, 10_000, pipelined=True)
        ws.register(feed)
        ws.drain(max_records=64)
        assert feed.dispatched, "nothing dispatched"
        assert max(len(seg) for seg in feed.dispatched) <= 64

    def test_dispatch_failure_rewinds_and_collects_inflight(self):
        """A raising dispatch rewinds that segment's cursor (records
        re-drain) and still collects the previously dispatched wave."""
        ws = WaveScheduler(wave_size=8, quantum=8)
        ok = FakeFeed(0, 8, pipelined=True)
        bad = FakeFeed(1, 8)
        bad.fail_dispatch = True
        ws.register(ok)
        ws.register(bad)
        with pytest.raises(RuntimeError, match="engine exploded"):
            ws.drain()
        assert bad.rewound_to == 0
        assert bad.cursor == 0  # records not lost: they re-drain
        # the ok feed's dispatched wave was still collected (finally path)
        assert sum(len(c) for c in ok.collected) == len(
            [r for seg in ok.dispatched for r in seg]
        )

    def test_unregister_mid_stream(self):
        ws = WaveScheduler(wave_size=32)
        a, b = FakeFeed(0, 40), FakeFeed(1, 40)
        ws.register(a)
        ws.register(b)
        ws.drain(max_records=32)
        ws.unregister(0)
        ws.drain()
        assert b.cursor == 40
        assert a.cursor < 40  # stopped feeding after unregister


# ---------------------------------------------------------------------------
# in-process broker: shared waves vs per-partition baseline, bit-identical
# ---------------------------------------------------------------------------


def _skewed_workload(data_dir, use_scheduler, partitions=4):
    """Deterministic multi-partition workload (Zipf-ish skew via explicit
    partition targeting); returns per-partition frame bytes."""
    from zeebe_tpu.gateway import JobWorker, ZeebeClient
    from zeebe_tpu.gateway import workers as workers_mod
    from zeebe_tpu.models.bpmn.builder import Bpmn

    workers_mod._subscriber_keys = itertools.count(1)
    clock = ControlledClock(start_ms=1_000_000)
    broker = Broker(num_partitions=partitions, data_dir=data_dir, clock=clock)
    broker.use_scheduler = use_scheduler
    broker.wave_size = 256
    try:
        client = ZeebeClient(broker)
        model = (
            Bpmn.create_process("mt-process")
            .start_event("start")
            .service_task("work", type="mt-service")
            .end_event("end")
            .done()
        )
        client.deploy_model(model)
        JobWorker(broker, "mt-service", lambda ctx: {"ok": True})
        # skewed mix: partition 0 heavy, the rest sparse (the regime where
        # per-partition waves collapse)
        mix = [0] * 24 + [1] * 6 + [2] * 3 + [3] * 2
        for i, pid in enumerate(mix):
            broker.write_command(
                pid,
                _create_value("mt-process", {"i": i}),
                _create_intent(),
            )
        broker.run_until_idle()
        return [
            [codec.encode_record(r) for r in broker.records(pid)]
            for pid in range(partitions)
        ]
    finally:
        broker.close()


def _create_value(process_id, payload):
    from zeebe_tpu.protocol.records import WorkflowInstanceRecord

    return WorkflowInstanceRecord(bpmn_process_id=process_id, payload=payload)


def _create_intent():
    from zeebe_tpu.protocol.intents import WorkflowInstanceIntent

    return WorkflowInstanceIntent.CREATE


class TestSharedWaveParity:
    def test_per_partition_logs_bit_identical_to_baseline(self, tmp_path):
        frames_shared = _skewed_workload(str(tmp_path / "s"), True)
        frames_base = _skewed_workload(str(tmp_path / "b"), False)
        assert sum(len(f) for f in frames_shared) > 100
        for pid, (a, b) in enumerate(zip(frames_shared, frames_base)):
            assert a == b, f"partition {pid} log diverged under scheduling"

    def test_shared_fill_beats_per_partition_baseline(self, tmp_path):
        """The acceptance metric at test scale: identical skewed offered
        load, mean wave fill of the shared drain ≥ 2× the per-partition
        baseline's."""
        c_waves = GLOBAL_REGISTRY.counter("serving_waves_total")
        c_recs = GLOBAL_REGISTRY.counter("serving_wave_records_total")

        def fill(run):
            w0, r0 = c_waves.value, c_recs.value
            run()
            dw = c_waves.value - w0
            dr = c_recs.value - r0
            assert dw > 0
            return dr / dw

        # trickle mode: several small drains (each run_until_idle is one
        # arrival burst) — the baseline pays one wave per partition per
        # burst, the scheduler packs them
        fill_shared = fill(
            lambda: _skewed_workload(str(tmp_path / "s"), True)
        )
        fill_base = fill(
            lambda: _skewed_workload(str(tmp_path / "b"), False)
        )
        assert fill_shared >= 2 * fill_base, (
            f"shared fill {fill_shared:.1f} vs baseline {fill_base:.1f}"
        )


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_per_connection_inflight_bound(self):
        ctl = AdmissionController(
            AdmissionConfig(max_inflight_per_connection=2)
        )
        assert ctl.try_admit(1) is None
        assert ctl.try_admit(1) is None
        assert ctl.try_admit(1) == "CONNECTION_INFLIGHT"
        assert ctl.try_admit(2) is None  # other connections unaffected
        ctl.release(1)
        assert ctl.try_admit(1) is None
        assert ctl.inflight(1) == 2

    def test_queue_depth_watermark_sheds(self):
        depth = [0]
        ctl = AdmissionController(
            AdmissionConfig(queue_depth_high=10),
            queue_depth_probe=lambda: depth[0],
        )
        assert ctl.try_admit(1) is None
        depth[0] = 10
        assert ctl.try_admit(1) == "QUEUE_DEPTH"
        depth[0] = 9
        assert ctl.try_admit(1) is None
        assert GLOBAL_REGISTRY.gauge("gateway_queue_depth").value == 9

    def test_forget_connection_drops_accounting(self):
        ctl = AdmissionController(
            AdmissionConfig(max_inflight_per_connection=2)
        )
        ctl.try_admit(7)
        ctl.try_admit(7)
        ctl.forget_connection(7)
        assert ctl.inflight(7) == 0
        assert ctl.try_admit(7) is None

    def test_release_unknown_connection_is_noop(self):
        ctl = AdmissionController(AdmissionConfig())
        ctl.release(42)  # never admitted: must not go negative
        assert ctl.inflight(42) == 0

    def test_disabled_admits_everything(self):
        ctl = AdmissionController(
            AdmissionConfig(enabled=False, max_inflight_per_connection=1)
        )
        for _ in range(10):
            assert ctl.try_admit(1) is None

    def test_rejection_body_is_retryable(self):
        ctl = AdmissionController(AdmissionConfig(retry_after_ms=25))
        body = ctl.rejection_body("QUEUE_DEPTH")
        assert body["code"] == "RESOURCE_EXHAUSTED"
        assert body["retry_ms"] == 25


# ---------------------------------------------------------------------------
# cluster end-to-end: shared waves serve multiple partitions; shed+retry
# ---------------------------------------------------------------------------


def _boot_cluster_broker(tmp_path, partitions=2, cfg_tweak=None):
    import os

    from zeebe_tpu.runtime.cluster_broker import ClusterBroker
    from zeebe_tpu.runtime.config import BrokerCfg

    cfg = BrokerCfg()
    cfg.network.client_port = 0
    cfg.network.management_port = 0
    cfg.network.subscription_port = 0
    cfg.metrics.port = 0
    cfg.metrics.enabled = False
    cfg.cluster.partitions = partitions
    if cfg_tweak is not None:
        cfg_tweak(cfg)
    broker = ClusterBroker(cfg, os.path.join(str(tmp_path), "b0"))
    for pid in range(partitions):
        broker.open_partition(pid).join(10)
        broker.bootstrap_partition(pid, {})
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not all(
        broker.partitions[pid].is_leader for pid in range(partitions)
    ):
        time.sleep(0.02)
    assert all(broker.partitions[pid].is_leader for pid in range(partitions))
    return broker


class TestClusterScheduler:
    def test_shared_waves_serve_all_partitions(self, tmp_path):
        from zeebe_tpu.gateway.cluster_client import ClusterClient
        from zeebe_tpu.models.bpmn.builder import Bpmn

        broker = _boot_cluster_broker(tmp_path, partitions=2)
        client = None
        try:
            assert broker.wave_scheduler is not None
            client = ClusterClient(
                [broker.client_address], num_partitions=2,
                request_timeout_ms=30_000,
            )
            model = (
                Bpmn.create_process("sched-process")
                .start_event("s")
                .service_task("work", type="sched-service")
                .end_event("e")
                .done()
            )
            client.deploy_model(model)
            done = []
            lock = threading.Lock()

            def on_job(pid, rec):
                with lock:
                    done.append(pid)
                return {}

            worker = client.open_job_worker("sched-service", on_job)
            for i in range(6):
                client.create_instance("sched-process", partition_id=i % 2)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and len(done) < 6:
                time.sleep(0.02)
            worker.close()
            assert len(done) >= 6
            assert set(done) == {0, 1}  # both partitions served
            assert (
                GLOBAL_REGISTRY.counter(
                    "scheduler_shared_waves_total"
                ).value > 0
            )
        finally:
            if client is not None:
                client.close()
            broker.close()

    def test_parked_partition_does_not_stall_the_other(self, tmp_path):
        """A partition waiting on a workflow fetch (CREATE for an unknown
        process parks its feed) must not stop the OTHER partition's waves
        — the backpressure/park isolation contract."""
        from zeebe_tpu.gateway.client import ClientException
        from zeebe_tpu.gateway.cluster_client import ClusterClient
        from zeebe_tpu.models.bpmn.builder import Bpmn

        broker = _boot_cluster_broker(tmp_path, partitions=2)
        client = None
        try:
            client = ClusterClient(
                [broker.client_address], num_partitions=2,
                request_timeout_ms=30_000,
            )
            model = (
                Bpmn.create_process("real-process")
                .start_event("s")
                .end_event("e")
                .done()
            )
            client.deploy_model(model)

            # ghost CREATE on partition 1: parks the feed, fetch finds
            # nothing, the engine rejects — asynchronously
            ghost_error = []

            def ghost():
                try:
                    client.create_instance("ghost-process", partition_id=1)
                except ClientException as e:
                    ghost_error.append(e)

            t = threading.Thread(target=ghost, daemon=True)
            t.start()
            # meanwhile partition 0 keeps serving
            for _ in range(3):
                rsp = client.create_instance(
                    "real-process", partition_id=0
                )
                assert rsp.value.workflow_instance_key > 0
            t.join(30)
            assert not t.is_alive()
            assert ghost_error, "ghost create should be rejected"
        finally:
            if client is not None:
                client.close()
            broker.close()

    def test_overload_sheds_retryably(self, tmp_path):
        """Synthetic overload against a 1-command in-flight bound: sheds
        fire (counted) but every command eventually lands via the
        client's retry — shed-before-collapse, not reject-forever."""
        from zeebe_tpu.gateway.cluster_client import ClusterClient
        from zeebe_tpu.models.bpmn.builder import Bpmn

        def tweak(cfg):
            cfg.admission.max_inflight_per_connection = 1

        broker = _boot_cluster_broker(tmp_path, partitions=1, cfg_tweak=tweak)
        client = None
        try:
            client = ClusterClient(
                [broker.client_address], num_partitions=1,
                request_timeout_ms=60_000,
            )
            model = (
                Bpmn.create_process("ovl-process")
                .start_event("s")
                .end_event("e")
                .done()
            )
            client.deploy_model(model)
            shed = GLOBAL_REGISTRY.counter(
                "gateway_commands_shed", reason="CONNECTION_INFLIGHT"
            )
            shed_before = shed.value
            errors = []
            keys = []
            lock = threading.Lock()

            def pump():
                try:
                    rsp = client.create_instance("ovl-process")
                    with lock:
                        keys.append(rsp.value.workflow_instance_key)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            threads = [
                threading.Thread(target=pump, daemon=True)
                for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60)
            assert not errors, errors
            assert len(keys) == 8
            assert len(set(keys)) == 8
            assert shed.value > shed_before, "overload never shed"
        finally:
            if client is not None:
                client.close()
            broker.close()


# ---------------------------------------------------------------------------
# lazy columnar emissions (device wave path)
# ---------------------------------------------------------------------------


def _device_workload(data_dir, lazy):
    """Device-engine serving workload; returns (frames, materialized
    delta, column-staged delta). The counter deltas cover the RUN only —
    reading the frames at the end deliberately materializes every lazy
    tail entry and must not pollute the measurement."""
    from zeebe_tpu.engine.interpreter import WorkflowRepository
    from zeebe_tpu.gateway import JobWorker, ZeebeClient
    from zeebe_tpu.gateway import workers as workers_mod
    from zeebe_tpu.models.bpmn.builder import Bpmn
    from zeebe_tpu.protocol.columnar import rows_materialized_total
    from zeebe_tpu.tpu import TpuPartitionEngine

    workers_mod._subscriber_keys = itertools.count(1)
    clock = ControlledClock(start_ms=1_000_000)
    repo = WorkflowRepository()

    def factory(pid):
        engine = TpuPartitionEngine(pid, 1, repository=repo, clock=clock)
        engine.lazy_emissions = lazy
        return engine

    broker = Broker(
        num_partitions=1, data_dir=data_dir, clock=clock,
        engine_factory=factory,
    )
    broker.wave_size = 256
    staged = GLOBAL_REGISTRY.counter("serving_rows_staged_columnar_total")
    m0, s0 = rows_materialized_total(), staged.value
    try:
        client = ZeebeClient(broker)
        model = (
            Bpmn.create_process("lazy-process")
            .start_event("start")
            .service_task("work", type="lazy-service")
            .end_event("end")
            .done()
        )
        client.deploy_model(model)
        JobWorker(broker, "lazy-service", lambda ctx: {"done": True})
        for i in range(12):
            client.create_instance("lazy-process", {"n": i})
        clock.advance(1_000)
        broker.tick()
        broker.run_until_idle()
        mat, stg = rows_materialized_total() - m0, staged.value - s0
        frames = [codec.encode_record(r) for r in broker.records(0)]
        return frames, mat, stg
    finally:
        broker.close()


def _raw_log_bytes(data_dir):
    import os

    pdir = os.path.join(data_dir, "partition-0")
    out = []
    for name in sorted(os.listdir(pdir)):
        if name.endswith(".data") or name.startswith("segment"):
            with open(os.path.join(pdir, name), "rb") as f:
                out.append(f.read())
    return out


class TestLazyEmissions:
    def test_lazy_log_bit_identical_to_eager(self, tmp_path):
        """The columns-encode + column-staging path produces EXACTLY the
        log the materialized-row path produces (frames AND downstream
        state transitions — a staging divergence would change follow-up
        records, not just bytes). Pinned on the in-memory frames AND the
        raw on-disk segment bytes."""
        frames_lazy, _, _ = _device_workload(str(tmp_path / "l"), True)
        frames_eager, _, _ = _device_workload(str(tmp_path / "e"), False)
        assert len(frames_lazy) > 100
        assert frames_lazy == frames_eager
        raw_lazy = _raw_log_bytes(str(tmp_path / "l"))
        raw_eager = _raw_log_bytes(str(tmp_path / "e"))
        assert raw_lazy and raw_lazy == raw_eager

    def test_lazy_path_materializes_fewer_rows_and_stages_columnar(
        self, tmp_path
    ):
        """The satellite pin: lazy emissions materialize strictly FEWER
        Record objects during the drain than the eager path, and a
        healthy share of device rows re-stage straight from columns."""
        _, eager_mat, eager_staged = _device_workload(
            str(tmp_path / "e"), False
        )
        assert eager_staged == 0, "eager mode must not column-stage"
        _, lazy_mat, lazy_staged = _device_workload(
            str(tmp_path / "l"), True
        )
        assert lazy_staged > 0, "no rows staged straight from columns"
        assert lazy_mat < eager_mat, (
            f"lazy path should materialize fewer rows "
            f"({lazy_mat} vs {eager_mat})"
        )
