"""Config system + metrics tests.

Reference parity: ``broker-core`` configuration tests (TOML parse, env
override, port offset) and ``util`` metrics tests (registry allocate +
prometheus dump; MetricsFileWriter flush).
"""

import pytest

from zeebe_tpu.runtime.actors import ControlledActorScheduler
from zeebe_tpu.runtime.clock import ControlledClock
from zeebe_tpu.runtime.config import BrokerCfg, load_config
from zeebe_tpu.runtime.metrics import MetricsFileWriter, MetricsRegistry


class TestConfig:
    def test_defaults(self):
        cfg = load_config(env={})
        assert cfg.network.client_port == 26501
        assert cfg.cluster.partitions == 1
        assert cfg.threads.cpu_thread_count == 2

    def test_parse_sections_camel_case(self):
        cfg = load_config(
            toml_text="""
[network]
host = "10.0.0.5"
portOffset = 2

[cluster]
nodeId = "broker-7"
initialContactPoints = ["10.0.0.1:26502"]

[[topics]]
name = "orders"
partitions = 4
replicationFactor = 3
""",
            env={},
        )
        assert cfg.network.host == "10.0.0.5"
        # port offset shifts every binding by offset * 10
        assert cfg.network.client_port == 26501 + 20
        assert cfg.network.gateway_port == 26500 + 20
        assert cfg.cluster.node_id == "broker-7"
        assert cfg.cluster.initial_contact_points == ["10.0.0.1:26502"]
        assert len(cfg.topics) == 1
        assert cfg.topics[0].partitions == 4

    def test_env_overrides_win(self):
        cfg = load_config(
            toml_text="[cluster]\nnodeId = 'from-file'\n",
            env={
                "ZEEBE_NODE_ID": "from-env",
                "ZEEBE_PORT_OFFSET": "1",
                "ZEEBE_CONTACT_POINTS": "a:1, b:2",
            },
        )
        assert cfg.cluster.node_id == "from-env"
        assert cfg.network.client_port == 26511
        assert cfg.cluster.initial_contact_points == ["a:1", "b:2"]

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown config key"):
            load_config(toml_text="[network]\nbogusKnob = 1\n", env={})

    def test_unknown_section_rejected(self):
        with pytest.raises(ValueError, match="unknown config section"):
            load_config(toml_text="[nonsense]\nx = 1\n", env={})

    def test_default_config_file_parses(self):
        import os

        path = os.path.join(os.path.dirname(__file__), "..", "dist", "zeebe.cfg.toml")
        cfg = load_config(path=path, env={})
        assert isinstance(cfg, BrokerCfg)
        assert cfg.data.segment_size_bytes == 64 * 1024 * 1024


class TestMetrics:
    def test_counter_and_dump(self):
        reg = MetricsRegistry()
        c = reg.counter("records_processed", "Records processed", partition="0")
        c.inc()
        c.inc(2)
        out = reg.dump(now_ms=123)
        assert "# HELP zb_records_processed Records processed" in out
        assert "# TYPE zb_records_processed counter" in out
        assert 'zb_records_processed{partition="0"} 3 123' in out

    def test_same_name_labels_reuses_metric(self):
        reg = MetricsRegistry()
        a = reg.counter("x", partition="0")
        b = reg.counter("x", partition="0")
        c = reg.counter("x", partition="1")
        assert a is b and a is not c
        a.inc()
        assert b.value == 1

    def test_gauge_set(self):
        reg = MetricsRegistry()
        g = reg.gauge("backlog", "")
        g.set(17)
        assert "zb_backlog 17" in reg.dump(now_ms=1)

    def test_file_writer_flushes_atomically(self, tmp_path):
        clock = ControlledClock()
        scheduler = ControlledActorScheduler(clock=clock).start()
        reg = MetricsRegistry()
        reg.counter("up").inc()
        path = str(tmp_path / "metrics" / "zeebe.prom")
        writer = MetricsFileWriter(reg, path, scheduler, flush_period_ms=5000)
        scheduler.work_until_done()
        clock.advance(5000)
        scheduler.work_until_done()
        with open(path) as f:
            assert "zb_up 1" in f.read()


class TestGlobalEventCounters:
    """Chaos-relevant counters from layers with no broker registry in reach
    (transport, log storage, snapshot storage, raft) count into the
    process-global registry and ride along every metrics surface."""

    CHAOS_COUNTERS = (
        "raft_elections_started",
        "raft_elections_won",
        "transport_reconnects",
        "transport_pending_expired",
        "log_torn_tail_truncations",
        "snapshot_salvage_events",
    )

    def test_count_event_merges_into_any_registry_dump(self):
        from zeebe_tpu.runtime import metrics as m

        m.count_event("chaos_test_evt", "a test event")
        out = m.render_with_global(MetricsRegistry(), now_ms=1)
        assert "zb_chaos_test_evt" in out
        # the global registry itself is not duplicated
        dump = m.render_with_global(m.GLOBAL_REGISTRY, now_ms=1)
        series = [
            line for line in dump.splitlines()
            if line.startswith("zb_chaos_test_evt ")
        ]
        assert len(series) == 1

    def test_chaos_counters_exposed_through_metrics_endpoint(self):
        import urllib.request

        from zeebe_tpu.runtime import metrics as m
        from zeebe_tpu.runtime.metrics import MetricsHttpServer

        for name in self.CHAOS_COUNTERS:
            m.count_event(name, delta=0.0)  # allocate without bumping
        reg = MetricsRegistry()
        reg.counter("up").inc()
        server = MetricsHttpServer(reg, host="127.0.0.1", port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5
            ).read().decode()
        finally:
            server.close()
        assert "zb_up 1" in body
        for name in self.CHAOS_COUNTERS:
            assert f"zb_{name}" in body, name

    def test_raft_election_counters_count_real_elections(self, tmp_path):
        import os

        from zeebe_tpu.cluster import Raft, RaftState
        from zeebe_tpu.log import LogStream, SegmentedLogStorage
        from zeebe_tpu.runtime import metrics as m
        from zeebe_tpu.runtime.actors import ActorScheduler

        started0 = m.event_count("raft_elections_started")
        won0 = m.event_count("raft_elections_won")
        scheduler = ActorScheduler(cpu_threads=2, io_threads=2).start()
        log = LogStream(
            SegmentedLogStorage(str(tmp_path / "log")), recover_commit=False
        )
        raft = Raft(
            "m0", log, scheduler,
            storage_path=os.path.join(str(tmp_path), "raft.meta"),
        )
        try:
            raft.bootstrap({"m0": raft.address})
            import time as _t

            deadline = _t.monotonic() + 10
            while _t.monotonic() < deadline and raft.state != RaftState.LEADER:
                _t.sleep(0.02)
            assert raft.state == RaftState.LEADER
            assert m.event_count("raft_elections_started") > started0
            assert m.event_count("raft_elections_won") > won0
        finally:
            raft.close()
            scheduler.stop()

    def test_file_writer_includes_global_counters(self, tmp_path):
        from zeebe_tpu.runtime import metrics as m

        m.count_event("chaos_file_evt")
        clock = ControlledClock()
        scheduler = ControlledActorScheduler(clock=clock).start()
        reg = MetricsRegistry()
        path = str(tmp_path / "metrics" / "zeebe.prom")
        MetricsFileWriter(reg, path, scheduler, flush_period_ms=5000)
        scheduler.work_until_done()
        clock.advance(5000)
        scheduler.work_until_done()
        with open(path) as f:
            assert "zb_chaos_file_evt" in f.read()


class TestWorkflowRepositoryQueries:
    """Reference WorkflowRepositoryService: list-workflows / get-workflow
    resource requests (gateway newWorkflowRequest / newResourceRequest)."""

    def test_in_process_list_and_get(self, tmp_path):
        from zeebe_tpu.gateway import ZeebeClient
        from zeebe_tpu.models.bpmn.builder import Bpmn
        from zeebe_tpu.models.bpmn.xml import read_model
        from zeebe_tpu.runtime import Broker

        broker = Broker(num_partitions=1, data_dir=str(tmp_path / "d"))
        try:
            client = ZeebeClient(broker)
            model = (Bpmn.create_process("repo-proc").start_event()
                     .service_task("t", type="x").end_event().done())
            client.deploy_model(model)
            client.deploy_model(model)  # version 2

            all_wfs = client.list_workflows()
            assert len(all_wfs) == 2
            assert {w["version"] for w in all_wfs} == {1, 2}

            latest = client.get_workflow(bpmn_process_id="repo-proc")
            assert latest["version"] == 2
            assert read_model(latest["resource"]).processes[0].id == "repo-proc"

            v1 = client.get_workflow(bpmn_process_id="repo-proc", version=1)
            assert v1["version"] == 1
            by_key = client.get_workflow(workflow_key=v1["workflow_key"])
            assert by_key["version"] == 1
        finally:
            broker.close()

    def test_cluster_list_and_get_over_the_wire(self, tmp_path):
        import time as _t

        from zeebe_tpu.gateway.cluster_client import ClusterClient
        from zeebe_tpu.models.bpmn.builder import Bpmn
        from zeebe_tpu.runtime.cluster_broker import ClusterBroker
        from zeebe_tpu.runtime.config import BrokerCfg

        cfg = BrokerCfg()

        cfg.network.client_port = 0

        cfg.network.management_port = 0

        cfg.network.subscription_port = 0

        cfg.metrics.port = 0
        cfg.cluster.node_id = "repo-broker"
        cfg.raft.heartbeat_interval_ms = 30
        cfg.raft.election_timeout_ms = 200
        cfg.metrics.enabled = False
        broker = ClusterBroker(cfg, str(tmp_path / "b"))
        try:
            broker.open_partition(0).join(10)
            broker.bootstrap_partition(0, {})
            deadline = _t.monotonic() + 20
            while _t.monotonic() < deadline and not broker.partitions[0].is_leader:
                _t.sleep(0.02)
            client = ClusterClient([broker.client_address])
            try:
                model = (Bpmn.create_process("wire-proc").start_event()
                         .service_task("t", type="x").end_event().done())
                client.deploy_model(model)
                wfs = client.list_workflows("wire-proc")
                assert len(wfs) == 1 and wfs[0]["version"] == 1
                got = client.get_workflow(bpmn_process_id="wire-proc")
                assert got["resource"].startswith(b"<?xml")
            finally:
                client.close()
        finally:
            broker.close()
