"""Multi-device sharded engine tests (virtual 8-device CPU mesh).

Reference parity: partitions are the reference's horizontal shards — each
an independent ordered log + state machine, with hash-routed
cross-partition commands over the subscription transport
(``docs/src/basics/clustering.md``, ``SubscriptionCommandSender.java:96-108``,
``qa/integration-tests/.../clustering/ClusteringRule.java``). Here
partitions are mesh shards: the step kernel runs under ``shard_map``, the
subscription-transport hop is an ``all_to_all`` over the mesh axis, and
global control aggregates (quiescence, processed counts) are ``psum``s.

conftest.py forces JAX_PLATFORMS=cpu with 8 virtual devices.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from zeebe_tpu.engine import keyspace
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.models.transform.transformer import transform_model
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI
from zeebe_tpu.tpu import batch as rb
from zeebe_tpu.tpu import drive, graph as graph_mod, shard, state as state_mod
from zeebe_tpu.tpu.conditions import VT_NUM

N_DEV = 8
CAP = 256
NUM_VARS = 8
BATCH = 64


@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    if len(devices) < N_DEV:
        pytest.skip(f"need {N_DEV} devices, have {len(devices)}")
    return Mesh(np.asarray(devices[:N_DEV]), ("partitions",))


@pytest.fixture(scope="module")
def compiled():
    model = (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("collect-money", type="payment-service")
        .end_event("end")
        .done()
    )
    workflows = transform_model(model)
    for wf in workflows:
        wf.key = 9
        wf.version = 1
    graph, meta = graph_mod.compile_graph(workflows)
    num_vars = max(graph.num_vars, NUM_VARS)
    graph = dataclasses.replace(graph, num_vars=num_vars)
    return graph, meta, num_vars


def _subscribed_state(num_partitions, meta, num_vars):
    """Partitioned state with one synthetic worker subscription per shard
    (the bench's instant worker, so instances run to completion)."""
    st = shard.make_partitioned_state(
        num_partitions, capacity=CAP, num_vars=num_vars, sub_capacity=8
    )
    type_id = meta.interns.intern("payment-service")
    worker_id = meta.interns.intern("w")
    return dataclasses.replace(
        st,
        sub_key=st.sub_key.at[:, 0].set(1),
        sub_type=st.sub_type.at[:, 0].set(type_id),
        sub_worker=st.sub_worker.at[:, 0].set(worker_id),
        sub_credits=st.sub_credits.at[:, 0].set(np.int32(2**30)),
        sub_timeout=st.sub_timeout.at[:, 0].set(300_000),
        sub_valid=st.sub_valid.at[:, 0].set(True),
    )


def _creates(meta, size, count, num_vars, value=99.0):
    b = rb.empty(size, num_vars)
    col = meta.varspace.column("orderValue")
    v_vt = np.zeros((size, num_vars), np.int8)
    v_num = np.zeros((size, num_vars), np.float32)
    v_vt[:count, col] = VT_NUM
    v_num[:count, col] = value
    return dataclasses.replace(
        b,
        valid=jnp.asarray(np.arange(size) < count),
        rtype=jnp.full((size,), int(RecordType.COMMAND), jnp.int32),
        vtype=jnp.full((size,), int(ValueType.WORKFLOW_INSTANCE), jnp.int32),
        intent=jnp.full((size,), int(WI.CREATE), jnp.int32),
        wf=jnp.zeros((size,), jnp.int32),
        v_vt=jnp.asarray(v_vt),
        v_num=jnp.asarray(v_num),
    )


def _stack(batches):
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *batches)


class TestPartitionedKeyspace:
    def test_key_bases_partition_disjoint(self, compiled):
        graph, meta, num_vars = compiled
        st = shard.make_partitioned_state(N_DEV, capacity=64, num_vars=num_vars)
        bases = np.asarray(st.next_wf_key)
        assert len(set(int(b) >> shard.PARTITION_KEY_SHIFT for b in bases)) == N_DEV
        job_bases = np.asarray(st.next_job_key)
        for p, base in enumerate(bases):
            assert int(base) >> shard.PARTITION_KEY_SHIFT == p
            # families stay stride-disjoint WITHIN a partition (keys are
            # partition-scoped — reference KeyGenerator.java:23)
            assert int(job_bases[p]) - int(base) == (
                keyspace.JOB_OFFSET - keyspace.WF_OFFSET
            )

    def test_allocated_keys_stay_disjoint_after_processing(self, mesh, compiled):
        graph, meta, num_vars = compiled
        state = _subscribed_state(N_DEV, meta, num_vars)
        queue = shard.make_partitioned_queue(N_DEV, 8 * BATCH, num_vars)
        creates = _stack([_creates(meta, BATCH, 16, num_vars) for _ in range(N_DEV)])
        enq = jax.jit(jax.vmap(drive.enqueue))
        queue = enq(queue, creates)
        run = shard.build_sharded_drive(mesh, BATCH, synthetic_workers=True)
        state, queue, totals = run(graph, state, queue, jnp.asarray(0, jnp.int64))
        keys = np.asarray(state.ei_i64[:, :, 0])  # [P, cap] allocated keys
        for p in range(N_DEV):
            used = keys[p][keys[p] >= 0]
            # every key this shard ever allocated carries its partition id
            nk = int(np.asarray(state.next_wf_key)[p])
            assert nk >> shard.PARTITION_KEY_SHIFT == p
            assert all(int(k) >> shard.PARTITION_KEY_SHIFT == p for k in used)


class TestExchange:
    def test_all_to_all_delivers_rows_with_payload(self, mesh, compiled):
        graph, meta, num_vars = compiled
        slots = 8
        sends = shard.make_exchange(N_DEV, slots=slots, num_vars=num_vars)
        # source p addresses destination q with a recognizable key p*100+q
        key_mat = np.full((N_DEV, N_DEV, slots), -1, np.int64)
        valid = np.zeros((N_DEV, N_DEV, slots), bool)
        num = np.zeros((N_DEV, N_DEV, slots, num_vars), np.float32)
        for p in range(N_DEV):
            for q in range(N_DEV):
                key_mat[p, q, 0] = p * 100 + q
                valid[p, q, 0] = True
                num[p, q, 0, 0] = float(p * 1000 + q)
        sends = dataclasses.replace(
            sends,
            key=jnp.asarray(key_mat),
            valid=jnp.asarray(valid),
            v_num=jnp.asarray(num),
        )
        state = _subscribed_state(N_DEV, meta, num_vars)
        batch = _stack([rb.empty(BATCH, num_vars) for _ in range(N_DEV)])
        step_fn, _ = shard.build_sharded_step(mesh)
        _, _, sends_in, _, _ = step_fn(
            graph, state, batch, sends, jnp.asarray(0, jnp.int64)
        )
        got = np.asarray(sends_in.key)  # [P(dest), P(src), slots]
        gnum = np.asarray(sends_in.v_num)
        for q in range(N_DEV):
            for p in range(N_DEV):
                assert got[q, p, 0] == p * 100 + q, (q, p, got[q, p, 0])
                assert gnum[q, p, 0, 0] == float(p * 1000 + q)

    def test_exchange_output_compacts_for_enqueue(self, compiled):
        graph, meta, num_vars = compiled
        # interleaved valid rows (what all_to_all delivers, grouped by
        # source shard) must compact into a contiguous prefix, preserving
        # relative order — drive.enqueue's precondition
        b = rb.empty(16, num_vars)
        valid = np.zeros(16, bool)
        valid[[1, 5, 6, 11]] = True
        keys = np.full(16, -1, np.int64)
        keys[[1, 5, 6, 11]] = [10, 20, 30, 40]
        b = dataclasses.replace(
            b, valid=jnp.asarray(valid), key=jnp.asarray(keys)
        )
        c = rb.compact(b)
        assert np.asarray(c.valid)[:4].all() and not np.asarray(c.valid)[4:].any()
        assert list(np.asarray(c.key)[:4]) == [10, 20, 30, 40]


class TestShardedDrive:
    def test_all_partitions_drive_to_quiescence(self, mesh, compiled):
        graph, meta, num_vars = compiled
        state = _subscribed_state(N_DEV, meta, num_vars)
        queue = shard.make_partitioned_queue(N_DEV, 8 * BATCH, num_vars)
        per_part = [4, 8, 12, 16, 2, 6, 10, 14]  # uneven load per shard
        creates = _stack(
            [_creates(meta, BATCH, n, num_vars) for n in per_part]
        )
        queue = jax.jit(jax.vmap(drive.enqueue))(queue, creates)
        run = shard.build_sharded_drive(mesh, BATCH, synthetic_workers=True)
        state, queue, totals = run(graph, state, queue, jnp.asarray(0, jnp.int64))
        t = jax.device_get(totals)
        assert not t["overflow"].any()
        assert list(t["completed_roots"]) == per_part
        assert np.asarray(queue.count).sum() == 0
        # uneven shards quiesce together (lockstep rounds)
        assert len(set(int(r) for r in t["rounds"])) == 1

    def test_multi_wave_sharded_drive(self, mesh, compiled):
        graph, meta, num_vars = compiled
        state = _subscribed_state(N_DEV, meta, num_vars)
        queue = shard.make_partitioned_queue(N_DEV, 8 * BATCH, num_vars)
        run = shard.build_sharded_drive(mesh, BATCH, synthetic_workers=True)
        enq = jax.jit(jax.vmap(drive.enqueue))
        waves = 3
        completed = np.zeros(N_DEV, np.int64)
        for _ in range(waves):
            creates = _stack(
                [_creates(meta, BATCH, 8, num_vars) for _ in range(N_DEV)]
            )
            queue = enq(queue, creates)
            state, queue, totals = run(
                graph, state, queue, jnp.asarray(0, jnp.int64)
            )
            t = jax.device_get(totals)
            assert not t["overflow"].any()
            completed += np.asarray(t["completed_roots"])
        assert list(completed) == [8 * waves] * N_DEV
        # instances completed → element-instance tables fully freed
        assert (np.asarray(state.ei_i32[:, :, 1]) == -1).all()

    def test_sharded_matches_independent_partitions(self, mesh, compiled):
        """Record-level parity: the 8-partition sharded drive leaves every
        shard in EXACTLY the state an independent single-partition run with
        the same commands produces (partitions are independent ordered
        logs — the sharding must be semantically invisible)."""
        graph, meta, num_vars = compiled
        state = _subscribed_state(N_DEV, meta, num_vars)
        queue = shard.make_partitioned_queue(N_DEV, 8 * BATCH, num_vars)
        per_part = [3, 7, 1, 9, 5, 0, 8, 4]
        creates_list = [
            _creates(meta, BATCH, n, num_vars, value=float(10 + n))
            for n in per_part
        ]
        queue = jax.jit(jax.vmap(drive.enqueue))(queue, _stack(creates_list))
        run = shard.build_sharded_drive(mesh, BATCH, synthetic_workers=True)
        state, queue, totals = run(graph, state, queue, jnp.asarray(0, jnp.int64))

        for p in range(N_DEV):
            # independent single-partition reference run, same key base
            ref = state_mod.make_state(
                capacity=CAP, num_vars=num_vars, sub_capacity=8
            )
            base = jnp.int64(p) << shard.PARTITION_KEY_SHIFT
            ref = dataclasses.replace(
                ref,
                next_wf_key=base + keyspace.WF_OFFSET,
                next_job_key=base + keyspace.JOB_OFFSET,
                sub_key=ref.sub_key.at[0].set(1),
                sub_type=ref.sub_type.at[0].set(
                    meta.interns.intern("payment-service")
                ),
                sub_worker=ref.sub_worker.at[0].set(meta.interns.intern("w")),
                sub_credits=ref.sub_credits.at[0].set(np.int32(2**30)),
                sub_timeout=ref.sub_timeout.at[0].set(300_000),
                sub_valid=ref.sub_valid.at[0].set(True),
            )
            rqueue = drive.make_queue(8 * BATCH, num_vars)
            rqueue = drive.enqueue(rqueue, creates_list[p])
            ref, rqueue, rtot = drive.run_to_quiescence(
                graph, ref, rqueue, 0, BATCH, synthetic_workers=True
            )
            assert rtot["completed_roots"] == per_part[p]
            sharded_shard = jax.tree.map(lambda a: a[p], state)
            for f in dataclasses.fields(ref):
                a = getattr(ref, f.name)
                b = getattr(sharded_shard, f.name)
                if hasattr(a, "keys"):
                    np.testing.assert_array_equal(
                        np.asarray(a.keys), np.asarray(b.keys),
                        err_msg=f"{f.name}.keys partition {p}",
                    )
                else:
                    np.testing.assert_array_equal(
                        np.asarray(a), np.asarray(b),
                        err_msg=f"{f.name} partition {p}",
                    )

    def test_cross_partition_commands_via_exchange(self, mesh, compiled):
        """Hash-routed command distribution: partition 0 addresses CREATE
        commands to every partition through the all_to_all exchange (the
        SubscriptionCommandSender hop over ICI); each destination then
        drives its inbound commands to completion."""
        graph, meta, num_vars = compiled
        slots = 8
        sends = shard.make_exchange(N_DEV, slots=slots, num_vars=num_vars)
        # partition 0 sends 2 CREATEs to every destination
        v = jax.tree.map(lambda a: np.asarray(a).copy(), sends)
        col = meta.varspace.column("orderValue")
        for q in range(N_DEV):
            for s in (0, 1):
                v.valid[0, q, s] = True
                v.rtype[0, q, s] = int(RecordType.COMMAND)
                v.vtype[0, q, s] = int(ValueType.WORKFLOW_INSTANCE)
                v.intent[0, q, s] = int(WI.CREATE)
                v.wf[0, q, s] = 0
                v.v_vt[0, q, s, col] = VT_NUM
                v.v_num[0, q, s, col] = 50.0
        sends = jax.tree.map(jnp.asarray, v)
        state = _subscribed_state(N_DEV, meta, num_vars)
        batch = _stack([rb.empty(BATCH, num_vars) for _ in range(N_DEV)])
        step_fn, _ = shard.build_sharded_step(mesh)
        state, _out, sends_in, _, _ = step_fn(
            graph, state, batch, sends, jnp.asarray(0, jnp.int64)
        )
        # deliver each shard its inbound rows: flatten [P(src), S] → rows,
        # compact to a prefix, enqueue, drive
        queue = shard.make_partitioned_queue(N_DEV, 8 * BATCH, num_vars)
        inbound = jax.tree.map(
            lambda a: a.reshape((N_DEV, -1) + a.shape[3:]), sends_in
        )
        inbound = jax.jit(jax.vmap(rb.compact))(inbound)
        queue = jax.jit(jax.vmap(drive.enqueue))(queue, inbound)
        run = shard.build_sharded_drive(mesh, BATCH, synthetic_workers=True)
        state, queue, totals = run(graph, state, queue, jnp.asarray(0, jnp.int64))
        t = jax.device_get(totals)
        assert list(t["completed_roots"]) == [2] * N_DEV

    def test_overflow_anywhere_aborts_everywhere(self, mesh, compiled):
        graph, meta, num_vars = compiled
        state = _subscribed_state(N_DEV, meta, num_vars)
        # partition 3 gets more instances than its element-instance table
        # can hold → its overflow must stop the whole mesh (lockstep abort)
        tiny = shard.make_partitioned_state(
            N_DEV, capacity=16, num_vars=num_vars, sub_capacity=8
        )
        tiny = dataclasses.replace(
            tiny,
            sub_key=state.sub_key, sub_type=state.sub_type,
            sub_worker=state.sub_worker, sub_credits=state.sub_credits,
            sub_timeout=state.sub_timeout, sub_valid=state.sub_valid,
        )
        queue = shard.make_partitioned_queue(N_DEV, 8 * BATCH, num_vars)
        counts = [1, 1, 1, 60, 1, 1, 1, 1]  # 60 > capacity 16
        creates = _stack([_creates(meta, BATCH, n, num_vars) for n in counts])
        queue = jax.jit(jax.vmap(drive.enqueue))(queue, creates)
        run = shard.build_sharded_drive(mesh, BATCH, synthetic_workers=True)
        _, _, totals = run(graph, tiny, queue, jnp.asarray(0, jnp.int64))
        t = jax.device_get(totals)
        assert t["overflow"].all(), "overflow must propagate to all shards"


class TestShardedMessageCorrelation:
    """Round 4: cross-partition message correlation rides the drive loop's
    all_to_all exchange — OPEN routes to the correlation-key's hash
    partition, CORRELATE back to the instance's partition, CLOSE to the
    message partition (reference SubscriptionCommandSender.java:96-108 as
    a mesh collective). Single-partition bit-for-bit parity with the
    oracle is pinned in test_tpu_parity; here the MESH semantics are
    validated: every instance completes, stores drain, no overflow."""

    @pytest.fixture(scope="class")
    def msg_compiled(self):
        model = (
            Bpmn.create_process("msgflow")
            .start_event("start")
            .receive_task("wait", message_name="paid", correlation_key="$.oid")
            .end_event("done")
            .done()
        )
        workflows = transform_model(model)
        for wf in workflows:
            wf.key = 9
            wf.version = 1
        graph, meta = graph_mod.compile_graph(workflows)
        num_vars = max(graph.num_vars, NUM_VARS)
        graph = dataclasses.replace(graph, num_vars=num_vars)
        return graph, meta, num_vars

    def _route_of(self, meta, corr: str) -> int:
        """Host mirror of shard.correlation_route's hash for staging
        publishes at their owner partition."""
        from zeebe_tpu.tpu.conditions import VT_STR

        name_id = meta.interns.intern("paid")
        sid = meta.interns.intern(corr)
        ckey = (name_id << 35) | (int(VT_STR) << 32) | (sid & 0xFFFFFFFF)
        h = ((ckey * -7046029254386353131) & (2**64 - 1)) % 2**64
        h = ((h >> 33) & 0x7FFFFFFF)
        return int(h % N_DEV)

    def _creates_msg(self, meta, size, oids, num_vars):
        from zeebe_tpu.tpu.conditions import VT_STR

        b = rb.empty(size, num_vars)
        col = meta.varspace.column("oid")
        v_vt = np.zeros((size, num_vars), np.int8)
        v_str = np.zeros((size, num_vars), np.int32)
        for i, oid in enumerate(oids):
            v_vt[i, col] = VT_STR
            v_str[i, col] = meta.interns.intern(oid)
        return dataclasses.replace(
            b,
            valid=jnp.asarray(np.arange(size) < len(oids)),
            rtype=jnp.full((size,), int(RecordType.COMMAND), jnp.int32),
            vtype=jnp.full((size,), int(ValueType.WORKFLOW_INSTANCE), jnp.int32),
            intent=jnp.full((size,), int(WI.CREATE), jnp.int32),
            wf=jnp.zeros((size,), jnp.int32),
            v_vt=jnp.asarray(v_vt),
            v_str=jnp.asarray(v_str),
        )

    def _publishes(self, meta, size, corrs, num_vars):
        from zeebe_tpu.protocol.intents import MessageIntent as MI
        from zeebe_tpu.tpu.conditions import VT_BOOL, VT_STR

        b = rb.empty(size, num_vars)
        paid_col = meta.varspace.column("paid")
        v_vt = np.zeros((size, num_vars), np.int8)
        v_num = np.zeros((size, num_vars), np.float32)
        type_id = np.zeros((size,), np.int32)
        retries = np.zeros((size,), np.int32)
        worker = np.zeros((size,), np.int32)
        for i, corr in enumerate(corrs):
            v_vt[i, paid_col] = VT_BOOL
            v_num[i, paid_col] = 1.0
            type_id[i] = meta.interns.intern("paid")
            retries[i] = int(VT_STR)
            worker[i] = meta.interns.intern(corr)
        return dataclasses.replace(
            b,
            valid=jnp.asarray(np.arange(size) < len(corrs)),
            rtype=jnp.full((size,), int(RecordType.COMMAND), jnp.int32),
            vtype=jnp.full((size,), int(ValueType.MESSAGE), jnp.int32),
            intent=jnp.full((size,), int(MI.PUBLISH), jnp.int32),
            v_vt=jnp.asarray(v_vt),
            v_num=jnp.asarray(v_num),
            type_id=jnp.asarray(type_id),
            retries=jnp.asarray(retries),
            worker=jnp.asarray(worker),
        )

    def test_cross_partition_correlation_completes_all(self, mesh, msg_compiled):
        graph, meta, num_vars = msg_compiled
        assert graph.has_messages
        st = shard.make_partitioned_state(
            N_DEV, capacity=CAP, num_vars=num_vars
        )
        # headroom: batch*emit_width local + nparts*exchange_slots arrivals
        # per round (see build_sharded_drive queue-sizing note)
        queue = shard.make_partitioned_queue(N_DEV, 32 * BATCH, num_vars)
        run = shard.build_sharded_drive(mesh, BATCH, exchange_slots=BATCH)

        # 3 instances per partition, each with a distinct correlation key
        n_per = 3
        oid_by_part = {
            p: [f"o-{p}-{i}" for i in range(n_per)] for p in range(N_DEV)
        }
        create_batches = [
            self._creates_msg(meta, BATCH, oid_by_part[p], num_vars)
            for p in range(N_DEV)
        ]
        queue = jax.jit(
            lambda q, b: jax.vmap(drive.enqueue)(q, b)
        )(queue, _stack(create_batches))
        st, queue, totals = run(graph, st, queue, jnp.int64(1_000))
        assert not bool(np.asarray(totals["overflow"]).any())
        # every instance waits at its receive task; subs live on their
        # hash partitions
        assert int(np.asarray(totals["completed_roots"]).sum()) == 0
        live_subs = int((np.asarray(st.msub_ckey) >= 0).sum())
        assert live_subs == N_DEV * n_per

        # publish each key AT its owner partition (hash-consistent staging,
        # exactly how the gateway routes publishes by correlation key)
        pubs_by_part = {p: [] for p in range(N_DEV)}
        for p in range(N_DEV):
            for oid in oid_by_part[p]:
                pubs_by_part[self._route_of(meta, oid)].append(oid)
        assert len({p for p, v in pubs_by_part.items() if v}) > 1, (
            "test needs keys hashing to multiple partitions"
        )
        pub_batches = [
            self._publishes(meta, BATCH, pubs_by_part[p], num_vars)
            for p in range(N_DEV)
        ]
        queue = jax.jit(
            lambda q, b: jax.vmap(drive.enqueue)(q, b)
        )(queue, _stack(pub_batches))
        st, queue, totals = run(graph, st, queue, jnp.int64(2_000))
        assert not bool(np.asarray(totals["overflow"]).any())
        # every instance correlated and completed; stores drained
        assert int(np.asarray(totals["completed_roots"]).sum()) == N_DEV * n_per
        assert int((np.asarray(st.msub_ckey) >= 0).sum()) == 0
        assert int((np.asarray(st.msg_key) >= 0).sum()) == 0
