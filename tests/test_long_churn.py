"""Long-running delete-churn regression tests for the device engine.

A parallel fork-join workflow inserts AND deletes a join-map entry per
instance; sustained waves once filled the map with tombstones until
inserts silently failed (hashmap.insert claimed only EMPTY buckets),
arrivals were lost, and stuck instances eventually overflowed the table
— observed as a ~4% completion loss in bench config 3 at wave 11+.
Inserts now claim tombstones (standard open addressing) and the wave
rebuild compacts every map; this pins both.
"""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from zeebe_tpu.tpu import drive, hashmap, state as state_mod


class TestHashmapTombstoneReuse:
    def test_insert_claims_tombstones(self):
        t = hashmap.make(64)
        keys = jnp.arange(1, 33, dtype=jnp.int64)
        vals = jnp.arange(32, dtype=jnp.int32)
        ones = jnp.ones((32,), bool)
        # churn the same table far past its capacity in EMPTY buckets
        for gen in range(8):
            t, ok = hashmap.insert(t, keys + 100 * gen, vals, ones)
            assert bool(ok.all()), f"insert failed at generation {gen}"
            found, _ = hashmap.lookup(t, keys + 100 * gen, ones)
            assert bool(found.all())
            t = hashmap.delete(t, keys + 100 * gen, ones)

    def test_fill_counts_reflect_churn(self):
        t = hashmap.make(64)
        keys = jnp.arange(1, 17, dtype=jnp.int64)
        ones = jnp.ones((16,), bool)
        t, _ = hashmap.insert(t, keys, jnp.arange(16, dtype=jnp.int32), ones)
        t = hashmap.delete(t, keys[:8], ones[:8])
        live, dead = hashmap.fill_counts(t)
        assert int(live) == 8


class TestForkJoinChurn:
    @pytest.mark.slow
    def test_sustained_fork_join_waves_complete_exactly(self):
        """12 waves of parallel fork-join instances through the drive
        loop: every root must complete (bench config-3 regression)."""
        graph, meta = bench.build_graph_forkjoin()
        num_vars = max(graph.num_vars, 8)
        graph = dc.replace(graph, num_vars=num_vars)
        wave = 1 << 7
        state = state_mod.make_state(
            capacity=4 * wave, num_vars=num_vars, job_capacity=4 * wave,
            join_capacity=wave, max_join_in=max(graph.max_join_in, 2),
            sub_capacity=8,
        )
        state = dc.replace(
            state,
            sub_key=state.sub_key.at[0].set(1),
            sub_type=state.sub_type.at[0].set(
                meta.interns.intern("payment-service")
            ),
            sub_worker=state.sub_worker.at[0].set(
                meta.interns.intern("bench-worker")
            ),
            sub_credits=state.sub_credits.at[0].set(np.int32(2**31 - 1)),
            sub_timeout=state.sub_timeout.at[0].set(300_000),
            sub_valid=state.sub_valid.at[0].set(True),
        )
        queue = drive.make_queue(4 * wave * max(2, graph.emit_width), num_vars)
        creates = bench.stage_creates(meta, wave, num_vars, meta.interns)
        enqueue_jit = jax.jit(drive.enqueue, donate_argnums=(0,))
        rebuild_jit = jax.jit(
            state_mod.rebuild_lookup_state, donate_argnums=(0,)
        )
        completed = 0
        waves = 12
        for i in range(waves):
            queue = enqueue_jit(queue, creates)
            state, queue, tot = drive.run_to_quiescence(
                graph, state, queue, 0, wave, synthetic_workers=True,
                sync=True,
            )
            completed += tot["completed_roots"]
            if (i + 1) % 3 == 0:
                state = rebuild_jit(state)
            assert completed == (i + 1) * wave, (
                f"wave {i}: {completed} != {(i + 1) * wave} — "
                "fork-join instances lost to table churn"
            )
        assert int((np.asarray(state.ei_state) >= 0).sum()) == 0
