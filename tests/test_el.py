"""Condition language tests.

Reference parity: json-el test suite (JsonConditionParserTest /
JsonConditionInterpreterTest semantics).
"""

import pytest

from zeebe_tpu.models.el import (
    Comparison,
    ConditionEvalError,
    ConditionParseError,
    Conjunction,
    Disjunction,
    JsonPathLiteral,
    Literal,
    evaluate_condition,
    parse_condition,
)
from zeebe_tpu.models.el.ast import query_json_path


class TestParser:
    def test_simple_comparison(self):
        cond = parse_condition("$.foo == 'bar'")
        assert cond == Comparison("==", JsonPathLiteral("$.foo"), Literal("bar"))

    def test_all_operators(self):
        for op in ("==", "!=", "<", "<=", ">", ">="):
            cond = parse_condition(f"$.x {op} 3")
            assert cond.op == op

    def test_number_literals(self):
        assert parse_condition("1 == 1.5").right == Literal(1.5)
        assert parse_condition("$.x == -2").right == Literal(-2)
        assert parse_condition("$.x == 2.0e3").right == Literal(2000.0)
        # reference grammar: exponent floats require a decimal point
        with pytest.raises(ConditionParseError):
            parse_condition("$.x == 2e3")

    def test_bool_null_literals(self):
        assert parse_condition("$.x == true").right == Literal(True)
        assert parse_condition("$.x == false").right == Literal(False)
        assert parse_condition("$.x == null").right == Literal(None)

    def test_double_and_single_quoted_strings(self):
        assert parse_condition('$.x == "a b"').right == Literal("a b")
        assert parse_condition("$.x == 'a b'").right == Literal("a b")

    def test_conjunction_disjunction_precedence(self):
        # a || b && c parses as a || (b && c)
        cond = parse_condition("$.a == 1 || $.b == 2 && $.c == 3")
        assert isinstance(cond, Disjunction)
        assert isinstance(cond.right, Conjunction)

    def test_parentheses(self):
        cond = parse_condition("($.a == 1 || $.b == 2) && $.c == 3")
        assert isinstance(cond, Conjunction)
        assert isinstance(cond.left, Disjunction)

    def test_ordering_rejects_string_literal(self):
        with pytest.raises(ConditionParseError):
            parse_condition("$.x < 'foo'")

    def test_ordering_rejects_bool(self):
        with pytest.raises(ConditionParseError):
            parse_condition("$.x >= true")

    def test_rejects_garbage(self):
        with pytest.raises(ConditionParseError):
            parse_condition("foo == 21")
        with pytest.raises(ConditionParseError):
            parse_condition("$.x == 1 extra")
        with pytest.raises(ConditionParseError):
            parse_condition("$.x ==")


class TestJsonPath:
    def test_top_level(self):
        assert query_json_path({"a": 1}, "$.a") == (True, 1)

    def test_nested(self):
        assert query_json_path({"a": {"b": 2}}, "$.a.b") == (True, 2)

    def test_array_index(self):
        assert query_json_path({"a": [10, 20]}, "$.a[1]") == (True, 20)

    def test_bracket_name(self):
        assert query_json_path({"a b": 3}, "$['a b']") == (True, 3)

    def test_root(self):
        assert query_json_path({"a": 1}, "$") == (True, {"a": 1})

    def test_missing(self):
        assert query_json_path({"a": 1}, "$.b") == (False, None)


class TestInterpreter:
    def test_equality(self):
        assert evaluate_condition(parse_condition("$.x == 1"), {"x": 1})
        assert not evaluate_condition(parse_condition("$.x == 1"), {"x": 2})
        assert evaluate_condition(parse_condition("$.x != 1"), {"x": 2})

    def test_string_equality(self):
        assert evaluate_condition(parse_condition("$.x == 'foo'"), {"x": "foo"})

    def test_bool_equality(self):
        assert evaluate_condition(parse_condition("$.paid == true"), {"paid": True})

    def test_null(self):
        assert evaluate_condition(parse_condition("$.x == null"), {"x": None})
        assert not evaluate_condition(parse_condition("$.x == null"), {"x": 1})
        assert evaluate_condition(parse_condition("$.x != null"), {"x": 1})

    def test_ordering(self):
        assert evaluate_condition(parse_condition("$.x < 10"), {"x": 5})
        assert evaluate_condition(parse_condition("$.x >= 10"), {"x": 10})
        assert not evaluate_condition(parse_condition("$.x > 10"), {"x": 10})

    def test_int_float_widening(self):
        # Reference ensureSameType widens INTEGER to FLOAT
        assert evaluate_condition(parse_condition("$.x == 1.0"), {"x": 1})
        assert evaluate_condition(parse_condition("$.x < 2.5"), {"x": 2})

    def test_type_mismatch_raises(self):
        with pytest.raises(ConditionEvalError):
            evaluate_condition(parse_condition("$.x == 'foo'"), {"x": 1})
        with pytest.raises(ConditionEvalError):
            evaluate_condition(parse_condition("$.x > 1"), {"x": "foo"})

    def test_missing_path_raises(self):
        # Reference: "JSON path '...' has no result."
        with pytest.raises(ConditionEvalError):
            evaluate_condition(parse_condition("$.missing == 1"), {"x": 1})

    def test_conjunction_disjunction(self):
        payload = {"a": 1, "b": 2}
        assert evaluate_condition(
            parse_condition("$.a == 1 && $.b == 2"), payload
        )
        assert evaluate_condition(
            parse_condition("$.a == 9 || $.b == 2"), payload
        )
        assert not evaluate_condition(
            parse_condition("$.a == 9 && $.b == 2"), payload
        )

    def test_path_to_path_comparison(self):
        assert evaluate_condition(parse_condition("$.a == $.b"), {"a": 3, "b": 3})

    def test_or_short_circuits_before_error(self):
        # reference: || evaluates left first; only raises if needed
        assert evaluate_condition(
            parse_condition("$.a == 1 || $.missing == 1"), {"a": 1}
        )
        with pytest.raises(ConditionEvalError):
            evaluate_condition(
                parse_condition("$.missing == 1 || $.a == 1"), {"a": 1}
            )
