"""Trace plane tests: span completeness on both engines, deterministic
sampling, ring wraparound, dump-on-invariant-failure, and the
tracing-disabled fast path (ISSUE 10 coverage satellite)."""

import json
import os

import pytest

from zeebe_tpu import tracing
from zeebe_tpu.gateway import JobWorker, ZeebeClient
from zeebe_tpu.models.bpmn.builder import Bpmn
from zeebe_tpu.runtime import Broker
from zeebe_tpu.runtime.config import ExporterCfg
from zeebe_tpu.tracing.recorder import (
    FlightRecorder,
    read_flight_dump,
)

# the single-writer (in-process) lifecycle; the cluster adds the raft hops
HOST_LIFECYCLE = [
    tracing.GATEWAY_RECV,
    tracing.COMMIT,
    tracing.FEED_TAKE,
    tracing.WAVE_DISPATCH,
    tracing.APPLY,
    tracing.RESPONSE,
    tracing.EXPORT_DISPATCH,
    tracing.EXPORT_ACK,
]


@pytest.fixture
def tracer():
    """A rate-1.0 tracer installed for the test, uninstalled after."""
    t = tracing.install(tracing.RecordTracer(sample_rate=1.0, seed=42))
    yield t
    tracing.install(None)


def order_model():
    return (
        Bpmn.create_process("order-process")
        .start_event("start")
        .service_task("work", type="payment-service")
        .end_event("end")
        .done()
    )


def _run_traced_workload(data_dir, engine_factory=None, exporters=True):
    broker = Broker(
        num_partitions=1,
        data_dir=data_dir,
        engine_factory=engine_factory,
        exporters=(
            [ExporterCfg(id="trace-mem", type="memory")] if exporters else None
        ),
    )
    try:
        client = ZeebeClient(broker)
        client.deploy_model(order_model())
        JobWorker(broker, "payment-service", lambda ctx: {"paid": True})
        for i in range(4):
            client.create_instance("order-process", {"orderId": i})
        broker.run_until_idle()
    finally:
        broker.close()


def _complete_spans(tracer):
    return [
        span for span in tracer.spans()
        if tracing.RESPONSE in span.stage_names()
    ]


class TestSpanCompleteness:
    def test_host_engine_full_lifecycle(self, tracer, tmp_path):
        from zeebe_tpu.exporter import InMemoryExporter

        InMemoryExporter.reset()
        _run_traced_workload(str(tmp_path / "host"))
        spans = _complete_spans(tracer)
        assert len(spans) >= 4  # the four CREATE commands at minimum
        for span in spans:
            names = span.stage_names()
            missing = [s for s in HOST_LIFECYCLE if s not in names]
            assert not missing, (span.trace_id, names, missing)
            ts = [t for _n, t, _f in span.stages]
            assert ts == sorted(ts), list(zip(names, ts))
            assert span.position >= 0

    def test_device_engine_full_lifecycle(self, tracer, tmp_path):
        from zeebe_tpu.engine.interpreter import WorkflowRepository
        from zeebe_tpu.exporter import InMemoryExporter
        from zeebe_tpu.tpu import TpuPartitionEngine

        InMemoryExporter.reset()
        repo = WorkflowRepository()
        _run_traced_workload(
            str(tmp_path / "device"),
            engine_factory=lambda pid: TpuPartitionEngine(
                pid, 1, repository=repo
            ),
        )
        spans = _complete_spans(tracer)
        assert len(spans) >= 4
        for span in spans:
            names = span.stage_names()
            missing = [s for s in HOST_LIFECYCLE if s not in names]
            assert not missing, (span.trace_id, names, missing)
            ts = [t for _n, t, _f in span.stages]
            assert ts == sorted(ts), list(zip(names, ts))

    def test_cluster_lifecycle_includes_raft_hops(self, tmp_path):
        """One-broker cluster: the sampled span additionally carries
        admission, actor-enqueue and the raft queue/fsync/commit hops."""
        from zeebe_tpu.testing.chaos import ChaosHarness

        tracer = tracing.install(
            tracing.RecordTracer(sample_rate=1.0, seed=3)
        )
        harness = ChaosHarness(str(tmp_path / "cluster"), n_brokers=1)
        client = None
        try:
            harness.await_leaders()
            client = harness.client()
            client.deploy_model(order_model())
            worker = client.open_job_worker(
                "payment-service", lambda pid, rec: {"paid": True}
            )
            client.create_instance(
                "order-process", {"orderId": 1}, partition_id=0
            )
            import time

            deadline = time.monotonic() + 20
            want = {
                tracing.GATEWAY_RECV, tracing.ADMISSION,
                tracing.ACTOR_ENQUEUE, tracing.RAFT_QUEUE,
                tracing.RAFT_FSYNC, tracing.COMMIT, tracing.FEED_TAKE,
                tracing.WAVE_DISPATCH, tracing.APPLY, tracing.RESPONSE,
            }
            full = None
            while time.monotonic() < deadline and full is None:
                for span in tracer.spans():
                    if want.issubset(set(span.stage_names())):
                        full = span
                        break
                time.sleep(0.1)
            assert full is not None, [
                (s.trace_id, s.stage_names()) for s in tracer.spans()
            ]
            ts = [t for _n, t, _f in full.stages]
            assert ts == sorted(ts)
            worker.close()
        finally:
            if client is not None:
                client.close()
            harness.close()
            tracing.install(None)


    def test_scheduler_collect_stamps_device_collect_before_apply(
        self, tracer
    ):
        """The pipelined scheduler feed must order DEVICE_COLLECT before
        APPLY, matching the baseline drain (_collect_chunk) — a span's
        apply->device_collect gap would otherwise contain the apply work
        and the two drive modes would contradict each other."""
        from types import SimpleNamespace

        from zeebe_tpu.runtime.cluster_broker import PartitionServer

        span = tracer.maybe_sample(0)
        tracer.bind_position(span, 0, 7, committed=True)

        stub = SimpleNamespace(partition_id=0, device_index=3)
        stub.engine = SimpleNamespace(collect_wave=lambda pending: [])

        def apply_chunk(records, merged):
            # the real _apply_chunk stamps APPLY at its top
            tracer.stamp_positions(
                0, tracing.positions_of(records), tracing.APPLY
            )

        stub._apply_chunk = apply_chunk
        pending = SimpleNamespace(
            records=[SimpleNamespace(position=7)],
            host_seconds=0.0, device_seconds=0.0,
        )
        host_s, device_s = PartitionServer.collect(stub, pending)
        assert (host_s, device_s) == (0.0, 0.0)
        names = span.stage_names()
        assert tracing.DEVICE_COLLECT in names and tracing.APPLY in names
        assert names.index(tracing.DEVICE_COLLECT) < names.index(
            tracing.APPLY
        )
        fields = {n: f for n, _t, f in span.stages}
        assert fields[tracing.DEVICE_COLLECT]["device"] == 3


class TestDeterministicSampling:
    def test_same_seed_same_schedule(self):
        a = tracing.RecordTracer(sample_rate=0.31, seed=9)
        b = tracing.RecordTracer(sample_rate=0.31, seed=9)
        picks_a = [a.maybe_sample(0) is not None for _ in range(500)]
        picks_b = [b.maybe_sample(0) is not None for _ in range(500)]
        assert picks_a == picks_b
        assert abs(sum(picks_a) - 155) <= 2  # accumulator tracks the rate

    def test_different_seed_different_phase(self):
        picks = {}
        for seed in (1, 2, 3, 4, 5, 6):
            t = tracing.RecordTracer(sample_rate=0.5, seed=seed)
            picks[seed] = tuple(
                t.maybe_sample(0) is not None for _ in range(40)
            )
        assert len(set(picks.values())) > 1  # the seed shifts the phase

    def test_rate_one_samples_everything_rate_zero_nothing(self):
        t1 = tracing.RecordTracer(sample_rate=1.0)
        assert all(t1.maybe_sample(0) is not None for _ in range(50))
        t0 = tracing.RecordTracer(sample_rate=0.0)
        assert all(t0.maybe_sample(0) is None for _ in range(50))

    def test_partitions_sample_independently(self):
        t = tracing.RecordTracer(sample_rate=0.25, seed=7)
        for _ in range(100):
            t.maybe_sample(0)
        before = [t.maybe_sample(1) is not None for _ in range(100)]
        fresh = tracing.RecordTracer(sample_rate=0.25, seed=7)
        alone = [fresh.maybe_sample(1) is not None for _ in range(100)]
        assert before == alone  # partition 0 traffic cannot shift p1


class TestSpanBudget:
    def test_overflow_evicts_oldest_to_finished(self):
        t = tracing.RecordTracer(sample_rate=1.0, per_partition_budget=8)
        spans = [t.maybe_sample(0) for _ in range(20)]
        stats = t.stats()
        assert stats["live"] == 8
        assert stats["dropped"] == 12
        # the oldest spans were evicted (finished), newest are live
        live_ids = {
            s.trace_id for s in t.spans() if not s.finished
        }
        assert live_ids == {s.trace_id for s in spans[-8:]}
        # eviction drops the position index entries too
        t2 = tracing.RecordTracer(sample_rate=1.0, per_partition_budget=2)
        s1 = t2.maybe_sample(0)
        t2.bind_position(s1, 0, 10, committed=True)
        assert (0, 10) in t2.by_position
        t2.maybe_sample(0)
        t2.maybe_sample(0)  # budget 2: s1 evicts here
        assert s1.finished
        assert (0, 10) not in t2.by_position

    def test_leadership_uninstall_orphans_live_spans(self):
        """A step-down strands the partition's live spans on this node
        (drain/apply/response/export are leader-side): the uninstall
        sweep must finish them, or they pin every per-record stamp path
        hot until budget eviction."""
        t = tracing.RecordTracer(sample_rate=1.0)
        s1 = t.maybe_sample(0)
        t.bind_position(s1, 0, 4, committed=True)
        other = t.maybe_sample(1)
        t.bind_position(other, 1, 4, committed=True)
        t.finish_partition_spans(0, "leader uninstalled")
        assert s1.finished
        assert "orphaned" in s1.stage_names()
        assert not other.finished  # other partitions untouched
        assert (0, 4) not in t.by_position

    def test_truncation_finishes_bound_spans(self):
        """A new leader truncating the log from P must finish every span
        bound at >= P: those positions get REUSED, and a later commit
        covering them must not stamp COMMIT onto a command that failed."""
        t = tracing.RecordTracer(sample_rate=1.0)
        spans = []
        for pos in (5, 6, 7):
            s = t.maybe_sample(0)
            t.bind_position(s, 0, pos)  # awaiting commit
            spans.append(s)
        t.truncate_positions_from(0, 6)
        assert not spans[0].finished
        assert spans[1].finished and spans[2].finished
        assert "truncated" in spans[1].stage_names()
        t.on_commit(0, 10)  # covers the reused positions
        assert tracing.COMMIT in spans[0].stage_names()
        assert tracing.COMMIT not in spans[1].stage_names()
        assert tracing.COMMIT not in spans[2].stage_names()


class TestFlightRecorder:
    def test_ring_overflow_wraparound(self):
        ring = FlightRecorder(capacity=64)
        for i in range(200):
            ring.record("test", f"event-{i}", i=i)
        events = ring.snapshot()
        assert len(events) == 64
        # oldest dropped, newest kept, order preserved
        seqs = [e["seq"] for e in events]
        assert seqs == sorted(seqs)
        assert seqs[0] == 200 - 64 and seqs[-1] == 199
        assert events[-1]["msg"] == "event-199"

    def test_dump_and_read_back(self, tmp_path):
        ring = FlightRecorder(capacity=32)
        for i in range(10):
            ring.record("raft", "state -> leader", term=i)
        path = ring.dump(
            path=str(tmp_path / "flight.jsonl"), reason="unit-test"
        )
        events = read_flight_dump(path)
        assert len(events) == 10
        assert events[0]["cat"] == "raft"
        assert events[3]["fields"]["term"] == 3

    def test_invariant_failure_dumps_to_disk(self, tmp_path, monkeypatch):
        from zeebe_tpu.testing import chaos
        from zeebe_tpu.tracing.recorder import FLIGHT

        monkeypatch.setenv("ZB_FLIGHT_DIR", str(tmp_path))
        FLIGHT.record("test", "before the failure", marker=1)
        chaos.invariant(True, "fine")  # no dump on success
        assert not [p for p in os.listdir(tmp_path) if "flight" in p]
        with pytest.raises(AssertionError) as err:
            chaos.invariant(False, "injected invariant failure")
        msg = str(err.value)
        assert "injected invariant failure" in msg
        assert "flight recorder dump:" in msg
        dump_path = msg.split("flight recorder dump: ")[1].split("]")[0]
        events = read_flight_dump(dump_path)
        assert any(e["msg"] == "before the failure" for e in events)

    def test_slice_formatting(self):
        ring = FlightRecorder(capacity=32)
        ring.record("scheduler", "backpressure skip", partition=2)
        text = ring.format_slice(last=5)
        assert "backpressure skip" in text and "'partition': 2" in text

    def test_rate_limited_events_cannot_wrap_the_ring(self):
        """Per-record-rate events (admission sheds, mesh fallbacks) must
        not evict the control-plane history: within the window only ONE
        ring entry lands, and the next one carries the suppressed count."""
        from zeebe_tpu.tracing import recorder
        from zeebe_tpu.tracing.recorder import RateLimitedEvent

        before = next(recorder.FLIGHT._seq)
        ev = RateLimitedEvent("admission", "command shed", interval_s=60.0)
        for _ in range(1000):
            ev.record(reason="queue_depth", depth=9)
        ev._last_t = 0.0  # window elapsed
        ev.record(reason="queue_depth", depth=9)
        after = next(recorder.FLIGHT._seq)
        assert after - before - 1 == 2  # one per window, not 1001
        shed = [
            e for e in recorder.FLIGHT.snapshot()
            if e["msg"] == "command shed" and e["seq"] > before
        ]
        assert shed[-1]["fields"]["suppressed_in_window"] == 999


class TestDisabledFastPath:
    def test_no_tracer_no_spans_no_allocation(self, tmp_path):
        """With the tracer explicitly uninstalled the hot paths must not
        allocate spans, wave timelines, or sampling state — and a broker
        boot must NOT silently re-install a default tracer (the sticky
        uninstall the ≤2% overhead gate's OFF leg rests on)."""
        tracing.install(None)
        probe = tracing.RecordTracer(sample_rate=1.0)
        # a probe tracer NOT installed must stay untouched by a workload
        _run_traced_workload(str(tmp_path / "off"), exporters=False)
        assert tracing.TRACER is None  # Broker boot respected the off
        assert probe.stats() == {
            "sampled": 0, "dropped": 0, "live": 0, "finished": 0,
        }
        assert not probe.waves.snapshot()
        assert not probe._acc  # sampling state never consulted

    def test_disabled_config_uninstalls(self):
        from zeebe_tpu.runtime.config import TracingCfg

        tracing.install(tracing.RecordTracer())
        cfg = TracingCfg(enabled=False)
        assert tracing.ensure_tracer(cfg) is None
        assert tracing.TRACER is None

    def test_stamp_sites_guard_on_empty_index(self):
        """stamp_positions with no live spans is one truthiness check."""
        t = tracing.RecordTracer(sample_rate=0.0)
        assert not t.tracking()
        t.stamp_positions(0, range(512), tracing.APPLY)  # no-op, no error
        assert t.stats()["sampled"] == 0


class TestDumpAndReport:
    def test_dump_converts_to_chrome_trace(self, tracer, tmp_path):
        import importlib
        import sys

        sys.path.insert(
            0,
            os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools"),
        )
        try:
            trace_report = importlib.import_module("trace_report")
        finally:
            sys.path.pop(0)
        _run_traced_workload(str(tmp_path / "dump"))
        dump_path = str(tmp_path / "dump.json")
        tracer.dump(dump_path)
        with open(dump_path) as f:
            doc = json.load(f)
        assert doc["format"] == "zeebe-tpu-trace-v1"
        assert doc["spans"] and doc["waves"]
        chrome = trace_report.convert(doc)
        events = chrome["traceEvents"]
        assert any(e["pid"] == "records" and e["ph"] == "X" for e in events)
        assert any(e["pid"] == "devices" for e in events)
        assert all(e["dur"] >= 0 for e in events if e["ph"] == "X")
