"""Gossip cluster tests: join, dissemination, failure detection, refutation.

Reference parity: ``gossip/src/test`` — GossipJoinTest,
GossipFailureDetectionTest, custom-event dissemination tests, all running N
real gossip actors over real loopback transport in one process
(GossipClusterRule; SURVEY.md §4).
"""

import time

import pytest

from zeebe_tpu.cluster import Gossip, GossipConfig, MemberStatus
from zeebe_tpu.runtime.actors import ActorScheduler

FAST = GossipConfig(
    probe_interval_ms=30,
    probe_timeout_ms=120,
    probe_indirect_timeout_ms=240,
    suspicion_multiplier=3,
    sync_interval_ms=300,
)


def wait_until(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def scheduler():
    s = ActorScheduler(cpu_threads=2, io_threads=2).start()
    yield s
    s.stop()


@pytest.fixture
def cluster(scheduler):
    nodes = []

    def make(n):
        for i in range(n):
            nodes.append(Gossip(f"node-{i}", scheduler, config=FAST))
        # all join via node-0 (the contact point)
        for node in nodes[1:]:
            node.join([nodes[0].address]).join(5)
        return nodes

    yield make
    for node in nodes:
        node.close()


class TestJoin:
    def test_three_nodes_converge(self, cluster):
        nodes = cluster(3)
        expect = sorted(n.member_id for n in nodes)
        assert wait_until(
            lambda: all(n.alive_members() == expect for n in nodes)
        ), [n.alive_members() for n in nodes]

    def test_late_joiner_learns_members_and_is_learned(self, cluster, scheduler):
        nodes = cluster(3)
        late = Gossip("node-late", scheduler, config=FAST)
        try:
            late.join([nodes[1].address]).join(5)
            expect = sorted([n.member_id for n in nodes] + ["node-late"])
            assert wait_until(
                lambda: late.alive_members() == expect
                and all(n.alive_members() == expect for n in nodes)
            )
        finally:
            late.close()

    def test_join_falls_back_to_reachable_contact_point(self, cluster, scheduler):
        nodes = cluster(2)
        from zeebe_tpu.transport import RemoteAddress

        late = Gossip("node-x", scheduler, config=FAST)
        try:
            late.join([RemoteAddress("127.0.0.1", 1), nodes[0].address]).join(5)
            assert wait_until(lambda: "node-x" in nodes[0].alive_members())
        finally:
            late.close()

    def test_join_no_contact_point_fails(self, scheduler):
        from zeebe_tpu.transport import RemoteAddress

        node = Gossip("lonely", scheduler, config=FAST)
        try:
            with pytest.raises(RuntimeError):
                node.join([RemoteAddress("127.0.0.1", 1)]).join(5)
        finally:
            node.close()


class TestFailureDetection:
    def test_dead_node_is_confirmed_dead(self, cluster):
        nodes = cluster(3)
        expect = sorted(n.member_id for n in nodes)
        assert wait_until(lambda: all(n.alive_members() == expect for n in nodes))
        victim = nodes[2]
        victim.close()  # hard kill: no leave broadcast
        survivors = nodes[:2]
        assert wait_until(
            lambda: all(
                n.members["node-2"].status == MemberStatus.DEAD for n in survivors
            ),
            timeout=20,
        ), [
            (n.member_id, {m.member_id: m.status for m in n.members.values()})
            for n in survivors
        ]

    def test_graceful_leave_spreads(self, cluster):
        nodes = cluster(3)
        expect = sorted(n.member_id for n in nodes)
        assert wait_until(lambda: all(n.alive_members() == expect for n in nodes))
        nodes[2].leave()
        time.sleep(0.1)  # let the leave event piggyback out
        nodes[2].close()
        assert wait_until(
            lambda: all(
                "node-2" not in n.alive_members() for n in nodes[:2]
            ),
            timeout=10,
        )


class TestCustomEvents:
    def test_custom_event_reaches_all_nodes_once(self, cluster):
        nodes = cluster(3)
        expect = sorted(n.member_id for n in nodes)
        assert wait_until(lambda: all(n.alive_members() == expect for n in nodes))
        received = {n.member_id: [] for n in nodes}
        for n in nodes:
            n.on_custom_event(
                "topology",
                lambda sender, payload, nid=n.member_id: received[nid].append(
                    (sender, payload)
                ),
            )
        nodes[0].publish_custom_event("topology", {"partitions": [0, 1]})
        assert wait_until(
            lambda: all(len(v) >= 1 for v in received.values()), timeout=10
        ), received
        time.sleep(0.3)  # give duplicates a chance to appear
        for node_id, events in received.items():
            assert events == [("node-0", {"partitions": [0, 1]})], (node_id, events)

    def test_custom_events_ordered_per_sender(self, cluster):
        nodes = cluster(2)
        assert wait_until(
            lambda: len(nodes[1].alive_members()) == 2
        )
        got = []
        nodes[1].on_custom_event("seq", lambda s, p: got.append(p))
        for i in range(5):
            nodes[0].publish_custom_event("seq", i)
        assert wait_until(lambda: len(got) == 5, timeout=10), got
        assert got == sorted(got)
