"""Clustered broker: gossip topology + raft partitions + leader processing.

Reference parity (broker-core clustering base + orchestration):
- ``ClusterComponent``: gossip service + join, topology manager aggregating
  partition/leader info from gossip custom events
  (``TopologyManagerImpl``, ``GossipCustomEventEncoding``).
- ``PartitionInstallService``: per partition, install log + raft; when this
  node becomes raft leader, install the leader partition services (stream
  processor + client command handling); on follower, just replicate
  (``PartitionInstallService.onStateChange:213-264``).
- ``BootstrapExpectNodes`` / ``BootstrapSystemTopic`` /
  ``BootstrapDefaultTopicsService``: await the configured node count, then
  create the system partition (0) and configured topics.
- Topic orchestration: partition creation requests sent to selected nodes
  over the management API (``TopicCreationService``, ``NodeSelector`` by
  load, ``CreatePartitionRequest`` → ``ManagementApiRequestHandler``).
- Client API: commands appended to the leader partition's log with request
  metadata; responses sent after processing (``ClientApiMessageHandler``).
- Cross-partition subscription commands routed to the target partition's
  leader over the subscription transport
  (``SubscriptionApiCommandMessageHandler``).

Processing model: the raft leader runs the engine. On leadership it
recovers (snapshot + replay with suppressed side effects, exactly like the
single-node broker), then processes newly committed records, appending
follow-ups through raft. Wire messages are msgpack maps; records travel as
codec frames.
"""

from __future__ import annotations

import logging
import os
import random
import zlib
import threading
from typing import Callable, Dict, List, Optional, Tuple

from zeebe_tpu.cluster.gossip import Gossip, GossipConfig
from zeebe_tpu.cluster.raft import Raft, RaftConfig, RaftState
from zeebe_tpu.engine.interpreter import JobSubscription, PartitionEngine, WorkflowRepository
from zeebe_tpu.log import LogStream, SegmentedLogStorage
from zeebe_tpu.log import stateser
from zeebe_tpu.log.snapshot import SnapshotController, SnapshotMetadata, SnapshotStorage
from zeebe_tpu.protocol import codec, msgpack
from zeebe_tpu.protocol.records import Record
from zeebe_tpu.runtime.actors import Actor, ActorFuture, ActorScheduler
from zeebe_tpu.runtime.clock import SystemClock
from zeebe_tpu.runtime.config import BrokerCfg
from zeebe_tpu.runtime.metrics import (
    MetricsFileWriter,
    MetricsRegistry,
    count_event,
)
from zeebe_tpu.transport import ClientTransport, RemoteAddress, ServerTransport
from zeebe_tpu import tracing
from zeebe_tpu.tracing.recorder import FLIGHT, record_event

logger = logging.getLogger(__name__)


def observe_append(
    future: ActorFuture, what: str, partition_id: int
) -> None:
    """Attach a loss observer to a fire-and-forget raft append.

    Since acked-means-committed (PR 10) a failed append future means the
    records were DROPPED — deposed leader, truncated tail — and the only
    trace is this future. Callers that are re-driven elsewhere (ticks,
    sweeps, backlog probes) still route through here so the loss rate is
    measurable instead of invisible.
    """

    def _done(f: ActorFuture) -> None:
        exc = getattr(f, "_exception", None)
        if exc is None:
            return
        count_event(
            "raft_append_losses",
            "Fire-and-forget raft appends whose future failed (records "
            "dropped on leadership change or truncation)",
        )
        logger.warning(
            "fire-and-forget append of %s on partition %d was lost: %r",
            what, partition_id, exc,
        )

    future.on_complete(_done)


class _AppendFailed(Exception):
    """Raft append failed (deposed mid-request); maps to NOT_LEADER."""


class Topology:
    """Queryable cluster view (reference ``Topology`` aggregated by the
    topology manager from gossip custom events)."""

    def __init__(self):
        self._lock = threading.Lock()
        # partition id → (leader node id, client addr [h,p], sub addr [h,p], term)
        self.partition_leaders: Dict[int, Tuple[str, list, list, int]] = {}
        # node id → management address
        self.members: Dict[str, list] = {}

    def update_leader(
        self, partition: int, node_id: str, addr: list, sub_addr: list, term: int
    ) -> None:
        with self._lock:
            current = self.partition_leaders.get(partition)
            if current is None or term >= current[3]:
                self.partition_leaders[partition] = (node_id, addr, sub_addr, term)

    def leader_address(self, partition: int) -> Optional[RemoteAddress]:
        with self._lock:
            entry = self.partition_leaders.get(partition)
        if entry is None:
            return None
        return RemoteAddress(entry[1][0], int(entry[1][1]))

    def leader_subscription_address(self, partition: int) -> Optional[RemoteAddress]:
        with self._lock:
            entry = self.partition_leaders.get(partition)
        if entry is None:
            return None
        return RemoteAddress(entry[2][0], int(entry[2][1]))

    def leader_node(self, partition: int) -> Optional[str]:
        with self._lock:
            entry = self.partition_leaders.get(partition)
        return entry[0] if entry else None

    def partitions(self) -> List[int]:
        with self._lock:
            return sorted(self.partition_leaders)


class PartitionServer:
    """One partition on one broker: log + raft + (on leadership) engine."""

    def __init__(self, broker: "ClusterBroker", partition_id: int):
        self.broker = broker
        self.partition_id = partition_id
        pdir = os.path.join(broker.data_dir, f"partition-{partition_id}")
        self.storage = SegmentedLogStorage(
            pdir,
            segment_size=broker.cfg.data.segment_size_bytes,
            native=broker.cfg.data.native_storage,
        )
        self.log = LogStream(
            self.storage,
            partition_id=partition_id,
            clock=broker.clock,
            recover_commit=False,
        )
        self.snapshots = SnapshotController(
            SnapshotStorage(os.path.join(pdir, "snapshots"))
        )
        self.raft = Raft(
            broker.node_id,
            self.log,
            broker.scheduler,
            config=RaftConfig(
                heartbeat_interval_ms=broker.cfg.raft.heartbeat_interval_ms,
                election_timeout_ms=broker.cfg.raft.election_timeout_ms,
                election_jitter_ms=broker.cfg.raft.election_timeout_ms,
                # the [tracing] watchdog threshold drives the raft-side
                # commit-latency watchdog too (it is sampling-independent
                # but the same operator knob)
                commit_stall_ms=broker.cfg.tracing.commit_stall_ms,
            ),
            host=broker.cfg.network.host,
            storage_path=os.path.join(pdir, "raft.meta"),
        )
        self.engine: Optional[PartitionEngine] = None
        self.next_read_position = 0
        # subscriber_key → topic-subscription pusher state (leader-local;
        # clients reopen on leader change and resume from logged acks)
        self.topic_pushers: Dict[int, dict] = {}
        # exporter plane (leader-local like the stream processor; resumes
        # from the replicated acked positions on any leader)
        self.exporter_director = None
        self.is_leader = False
        self._processing_scheduled = False
        self._fetch_attempted = False  # one fetch try per parked record
        # wave-scheduler feed state: parked while a workflow fetch is in
        # flight (take() yields nothing; the other partitions keep
        # draining — the whole point of per-partition backpressure)
        self._parked = False
        self._fetch_candidate = None  # head record awaiting a fetch check
        self._due_probe = None  # in-flight async deadline probe (device)
        # snapshot-while-serving: at most ONE take in flight per partition
        # (capture happens on the broker actor; commit on a worker thread)
        self._snapshot_inflight = False
        self._snapshot_thread: Optional[threading.Thread] = None
        self.raft.on_state_change(self._on_raft_state_change)
        self.log.on_commit(self._on_commit)

    def _on_commit(self, position: int) -> None:
        tracer = tracing.TRACER
        if tracer is not None:
            # stamp COMMIT on sampled spans the advance covered
            tracer.on_commit(self.partition_id, position)
        self._schedule_processing()

    # -- leadership transitions (reference PartitionInstallService) --------
    def _on_raft_state_change(self, state: RaftState, term: int) -> None:
        if state == RaftState.LEADER:
            self.broker.actor_control.run(lambda: self._install_leader(term))
        elif self.is_leader:
            self.broker.actor_control.run(self._uninstall_leader)

    def _install_leader(self, term: int, _boundary: Optional[int] = None) -> None:
        if self.raft.state != RaftState.LEADER or self.raft.term != term:
            # deposed (or re-elected at a higher term) since this install
            # was queued or deferred: installing now would serve on a
            # FOLLOWER in parallel with the real leader. The state-change
            # event that owns the CURRENT term schedules its own install.
            return
        # Replay can only read COMMITTED records, and a fresh leader's
        # commit catch-up (the §5.4.2 no-op quorum round; on restart the
        # log recovers with commit at -1) may still be in flight — raft
        # fires the LEADER state change BEFORE that round lands.
        # Installing early would replay NOTHING and leave the cursor at
        # the front, so the drain would later reprocess records whose
        # follow-ups are already in the log WITH side effects (observed
        # as duplicate CREATED events after a crash-restart under load).
        # The boundary check depends only on the log, so it runs BEFORE
        # the expensive engine build + snapshot recovery; the scanned
        # boundary is carried across deferral retries (source positions
        # only grow through PROCESSING, which cannot start before the
        # install — commands are rejected NOT_LEADER until then), so the
        # 10ms retries never rescan the log.
        last_source = _boundary
        if last_source is None:
            last_source = -1
            for record in self.log.reader(0):
                if record.source_record_position > last_source:
                    last_source = record.source_record_position
        if self.log.commit_position < last_source:
            if (
                not self.broker._closing
                and self.raft.state == RaftState.LEADER
                and self.raft.term == term
            ):
                count_event(
                    "leader_install_deferred_uncommitted",
                    "Leader installs deferred until the raft commit "
                    "position covered the replay boundary",
                )
                record_event(
                    "leadership", "install deferred (commit < boundary)",
                    node=self.broker.node_id, partition=self.partition_id,
                    term=term, commit=self.log.commit_position,
                    boundary=last_source,
                )
                self.broker.actor_control.run_delayed(
                    10, lambda: self._install_leader(term, last_source)
                )
            return
        # the engine is the partition's stream processor — installed on
        # leadership like the reference's PartitionInstallService installing
        # TypedStreamProcessors (:106-291). Which engine (host oracle or
        # TPU device engine) is the broker's engine_factory's choice.
        self.engine = self.broker._new_engine(self.partition_id)
        # position-based re-reads (incident resolution) serve from the
        # LOG behind the hot cache window — eviction then needs no spill
        # copy, and recovery needs no cache pre-fill
        cache = getattr(self.engine, "records_by_position", None)
        log_backed = hasattr(cache, "set_log_lookup")
        if log_backed:
            cache.set_log_lookup(self.log.record_at)
        # recovery: snapshot + replay of the committed log, side effects
        # suppressed (same contract as the single-node broker). Parts are
        # decoded + installed streamed per family; recover() reports the
        # read+decode time as snapshot_restore_seconds, and this span —
        # which additionally includes the engine state install — bounds
        # what failover time the snapshot contributes (replay is separate).
        import time as _time

        t0 = _time.perf_counter()
        state, meta = self.snapshots.recover(self.log.next_position - 1)
        self.next_read_position = 0
        if state is not None:
            self.engine.restore_state(state)
            self.next_read_position = meta.last_processed_position + 1
            from zeebe_tpu._events import set_gauge

            set_gauge(
                "snapshot_install_seconds", _time.perf_counter() - t0,
                "Duration of the last snapshot recovery INCLUDING the "
                "engine state install (excludes log replay)",
            )
        if not log_backed:  # no log behind the cache: pre-fill it
            for record in self.log.reader(0):
                self.engine.records_by_position[record.position] = record
        # replay bounded by the last source event position: tail records
        # (appended by the old leader but never processed) are handled by
        # the normal loop below, with side effects — else their follow-ups
        # are lost and the instances wedge (reference
        # StreamProcessorController:189-279 lastSourceEventPosition)
        reader = self.log.reader(self.next_read_position)
        for record in reader.read_committed():
            if record.position > last_source:
                break
            self.engine.process(record)
            self.next_read_position = record.position + 1
        self.is_leader = True
        record_event(
            "leadership", "leader installed", node=self.broker.node_id,
            partition=self.partition_id, term=term,
            replayed_to=self.next_read_position - 1,
        )
        if self.broker.wave_scheduler is not None:
            # this partition's committed tail now feeds the broker's
            # shared waves (the scheduler is the single place waves form)
            self.broker.wave_scheduler.register(self)
        self._install_exporters()
        self.broker.on_partition_leader(self.partition_id, term)
        if self.partition_id == 0:
            # topics caught mid-creation by the failover: resume
            # orchestration (reference: pending topic tracking re-drives
            # partition creation on the new system-partition leader)
            from zeebe_tpu.protocol.metadata import RecordMetadata

            for name, topic in self.engine.topics.items():
                if topic["state"] == "CREATING":
                    self.broker.start_topic_orchestration(
                        Record(metadata=RecordMetadata(), value=topic["record"])
                    )
        self._schedule_processing()

    def _uninstall_leader(self, orphan_spans: bool = True) -> None:
        """``orphan_spans=False`` is for same-node reinstalls (mesh
        rebalance fallback): leadership never leaves this broker, so its
        live spans will still be applied/responded/exported here."""
        if self.is_leader:
            record_event(
                "leadership", "leader uninstalled",
                node=self.broker.node_id, partition=self.partition_id,
            )
        self.is_leader = False
        self.engine = None
        if self.broker.wave_scheduler is not None:
            self.broker.wave_scheduler.unregister(self.partition_id)
        if self.broker.device_plan is not None:
            # leadership left: free the mesh slot so the next install
            # (this partition or another) rebalances onto the emptiest
            # device
            self.broker.device_plan.release(self.partition_id)
        self._parked = False
        self._fetch_candidate = None
        self._due_probe = None
        # topic pushers are LEADER-LOCAL services (reference: push
        # processors are installed/removed with leadership); a pusher
        # surviving a leadership flap raced the new leader's pusher and
        # delivered records out of order (round-4 flake root cause)
        self.topic_pushers.clear()
        # exporters likewise: close on step-down (the new leader's
        # director resumes from the replicated acked positions)
        if self.exporter_director is not None:
            self.exporter_director.close()
            self.exporter_director = None
        tracer = tracing.TRACER
        if orphan_spans and tracer is not None and tracer.by_position:
            # spans stranded by the step-down can never progress on this
            # node (drain/apply/response/export are all leader-side):
            # finish them or they pin every per-record stamp path hot
            # until budget eviction. (Process-global-tracer caveat: in an
            # in-process multi-broker harness this also closes the NEW
            # leader's in-flight spans for the partition — position keys
            # carry no broker identity; see docs/operations/tracing.md.)
            tracer.finish_partition_spans(
                self.partition_id, "leader uninstalled"
            )

    def _install_exporters(self) -> None:
        """Leader-only exporter plane (reference: the exporter stream
        processor installs with leadership). Positions come from the
        recovered engine state, so the new leader resumes the old leader's
        progress without gaps; acks append through raft."""
        if self.exporter_director is not None:
            # re-election without an intervening step-down: replace the
            # old install (its positions live in engine state, not in the
            # director, so nothing is lost)
            self.exporter_director.close()
            self.exporter_director = None
        if self.engine is None:
            return
        from zeebe_tpu.exporter import (
            ExporterDirector,
            ExporterDirectorActor,
            build_exporter,
        )
        from zeebe_tpu.exporter.director import (
            fold_tail_acks,
            remove_stale_positions,
        )

        if not self.broker.cfg.exporters:
            # no director to install, but recovered positions of
            # previously configured exporters must still be swept
            # (REMOVE) or the last-removed exporter's stale entry pins
            # the compaction floor forever
            try:
                stale = remove_stale_positions(
                    fold_tail_acks(
                        self.engine.exporter_positions, self.log,
                        self.next_read_position,
                    ),
                    (),
                )
                if stale:
                    observe_append(
                        self.raft.append(stale),
                        "stale exporter-position sweep", self.partition_id,
                    )
            except Exception as e:  # noqa: BLE001 - sweep must never
                # wedge the leadership install; the pin merely persists
                # until a later leader's sweep lands
                logger.warning(
                    "stale exporter-position sweep failed on partition "
                    "%d (floor stays pinned until a later sweep): %r",
                    self.partition_id, e,
                )
            return

        # belt over the boot-time validation: an install failure must
        # never wedge the leadership install (the partition would report
        # itself leader but never process a record)
        try:
            pairs = [build_exporter(spec) for spec in self.broker.cfg.exporters]
            director = ExporterDirector(
                self.partition_id,
                self.log,
                pairs,
                append_fn=self.raft.append,
                clock=self.broker.clock,
                node_label=self.broker.node_id,
            )
            director.open(fold_tail_acks(
                self.engine.exporter_positions, self.log,
                self.next_read_position,
            ))
            self.exporter_director = ExporterDirectorActor(
                director, self.broker.scheduler
            )
        except Exception as e:  # noqa: BLE001 - exporters are isolated
            self.exporter_director = None
            count_event(
                "exporter_install_failures",
                "Leadership exporter installs that raised",
            )
            logger.error(
                "exporter install failed on partition %d (partition keeps "
                "processing WITHOUT exporters; compaction is not gated): %r",
                self.partition_id, e,
            )

    # -- the processing loop (StreamProcessorController hot loop) ----------
    def _schedule_processing(self) -> None:
        if not self.is_leader:
            return
        if self.broker.wave_scheduler is not None:
            # shared-wave mode: one drain job per broker packs ALL leader
            # partitions' committed tails (zeebe_tpu/scheduler/)
            self.broker._schedule_drain()
            return
        if self._processing_scheduled:
            return
        self._processing_scheduled = True
        self.broker.actor_control.run(self._process_committed)

    # -- wave-scheduler feed surface (scheduler.PartitionFeed) -------------
    # The scheduler packs this partition's committed tail into SHARED
    # waves: take() consumes at the cursor (one-lock committed_view span),
    # dispatch/collect ride the engine's existing double-buffered wave
    # pipeline, and apply stays per partition — the log is bit-identical
    # to the per-partition drain (tests/test_scheduler.py pins it).
    @property
    def device_index(self) -> int:
        """The mesh device this partition's engine is placed on (per-device
        wave metrics label; -1 = unplaced/host engine)."""
        if self.engine is None:
            return -1
        return getattr(self.engine, "device_index", -1)

    @property
    def device_indices(self):
        """Every plan index this partition occupies — the span of a
        sharded-state engine, else empty (scheduler falls back to
        ``device_index``)."""
        if self.engine is None:
            return ()
        return tuple(getattr(self.engine, "device_indices", ()) or ())

    @property
    def shard_fill(self):
        """Per-shard staged-row counts of the engine's last dispatched
        wave (sharded-state v2 fill accounting); empty otherwise."""
        if self.engine is None:
            return ()
        return tuple(getattr(self.engine, "last_shard_fill", ()) or ())

    def backlog(self) -> int:
        if not self.is_leader:
            return 0
        return max(0, self.log.commit_position - self.next_read_position + 1)

    def take(self, limit: int):
        from zeebe_tpu.protocol.enums import RecordType, ValueType
        from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI

        if not self.is_leader or self.engine is None or self._parked:
            return []
        view = self.log.committed_view(self.next_read_position, limit)
        n = len(view)
        if not n:
            return []
        # the one-fetch-per-parked-record latch exempts EXACTLY the head
        # record (the one it parked on — consumed unconditionally so the
        # engine can reject it); records behind it still get their own
        # fetch scan, matching the old per-record latch reset
        start = 0
        if self._fetch_attempted:
            self._fetch_attempted = False
            start = 1
        cut = n
        if self.partition_id != 0:
            # workflow-fetch scan over the COLUMNS: only WI CREATE
            # commands can park, and those are client-born real rows —
            # nothing lazy materializes here
            vts = view.value_types()
            rts = view.record_types()
            its = view.intents()
            wi = int(ValueType.WORKFLOW_INSTANCE)
            cmd = int(RecordType.COMMAND)
            create = int(WI.CREATE)
            for i in range(start, n):
                if vts[i] == wi and rts[i] == cmd and its[i] == create:
                    record = view[i]
                    if self._needs_workflow_fetch(record):
                        # stop BEFORE the parking record; the prefix
                        # still packs (a DEPLOYMENT inside it may provide
                        # the workflow — re-checked after the drain)
                        cut = i
                        self._fetch_candidate = record
                        break
        if cut == 0:
            return []
        positions = view.positions()
        self.next_read_position = positions[cut - 1] + 1
        tracer = tracing.TRACER
        if tracer is not None and tracer.by_position:
            tracer.stamp_positions(
                self.partition_id, positions[:cut], tracing.FEED_TAKE
            )
        if cut == n:
            return view
        return view.select(list(range(cut)))

    def dispatch(self, records):
        """Feed one shared-wave segment to the engine. Pipelined engines
        return the pending wave (collected later while the device computes
        the next one); synchronous engines process AND apply inline."""
        import time as _time

        dispatch = getattr(self.engine, "dispatch_wave", None)
        if dispatch is None:
            t0 = _time.perf_counter()
            result = self.engine.process_batch(records)
            self._apply_chunk(records, result)
            return None, _time.perf_counter() - t0, 0.0
        return dispatch(records), 0.0, 0.0

    def collect(self, pending):
        from zeebe_tpu.engine.interpreter import ProcessingResult

        merged = ProcessingResult.merged(self.engine.collect_wave(pending))
        tracer = tracing.TRACER
        if tracer is not None and tracer.by_position:
            tracer.stamp_positions(
                self.partition_id, tracing.positions_of(pending.records),
                tracing.DEVICE_COLLECT, device=self.device_index,
            )
        self._apply_chunk(pending.records, merged)
        return pending.host_seconds, pending.device_seconds

    def rewind(self, position: int) -> None:
        if position >= 0:
            self.next_read_position = min(self.next_read_position, position)

    def maybe_start_fetch(self) -> None:
        """After a drain settles: if take() stopped on a record whose
        workflow is still unknown, park this feed and fetch — the other
        partitions keep packing waves meanwhile."""
        record = self._fetch_candidate
        if record is None:
            return
        self._fetch_candidate = None
        if not self.is_leader:
            return
        if not self._needs_workflow_fetch(record):
            # a deployment drained in the prefix provided it meanwhile
            self.broker._schedule_drain()
            return
        self._parked = True
        self.broker.fetch_workflow(
            record.value.bpmn_process_id,
            record.value.workflow_key,
            on_done=self._resume_after_fetch,
        )

    def _resume_after_fetch(self) -> None:
        # one attempt per parked record: if the fetch produced nothing the
        # engine now processes the command and rejects it (workflow not
        # found), instead of fetch-looping forever
        self._fetch_attempted = True
        self._parked = False
        self.broker._schedule_drain()

    def tick(self) -> None:
        """Deadline/TTL sweep for this partition (reference periodic actor
        jobs). Engines exposing an async due-probe are polled WITHOUT
        blocking: the tick only pays the device sweep when a ready probe
        says something is due; host-oracle deadlines are cheap dict scans
        swept unconditionally. The resulting commands append through raft
        and re-enter the shared waves as committed records."""
        if not self.is_leader or self.engine is None:
            return
        from zeebe_tpu.tpu.engine import PROBE_DEADLINES, PROBE_JOB_BACKLOG

        engine = self.engine
        commands: List[Record] = []
        probe_fn = getattr(engine, "deadlines_due_probe", None)
        if probe_fn is not None:
            commands += engine.host_deadline_commands()
            commands += engine.backlog_activations()
            pending = self._due_probe
            mask = 0
            if pending is None:
                self._due_probe = probe_fn()
            elif pending.is_ready():
                mask = int(pending)
                self._due_probe = probe_fn()
            if mask & PROBE_DEADLINES:
                commands += engine.device_deadline_commands()
            if mask & PROBE_JOB_BACKLOG:
                commands += engine.device_backlog_activations()
        else:
            commands += (
                engine.check_job_deadlines()
                + engine.check_timer_deadlines()
                + engine.check_message_ttls()
                + engine.backlog_activations()
            )
        if commands:
            # re-driven by the next tick if lost, but the loss must count
            observe_append(
                self.raft.append(commands), "tick commands", self.partition_id
            )

    # committed records drain into the engine in batches: the device
    # engine's throughput comes from SIMD batches (one kernel dispatch per
    # segment, not per record — reference: StreamProcessorController is
    # per-record, the TPU redesign's whole point is that this isn't)
    _DRAIN_BATCH = 512

    def _process_committed(self) -> None:
        self._processing_scheduled = False
        if not self.is_leader or self.engine is None:
            return
        reader = self.log.reader(self.next_read_position)
        batch: list = []
        pending = None  # dispatched-but-uncollected wave (device engine)
        parked = False
        try:
            for record in reader.read_committed():
                if self._needs_workflow_fetch(record):
                    # a DEPLOYMENT earlier in this very drain may provide
                    # the workflow: process the collected prefix FIRST,
                    # then re-check before parking (the per-record loop got
                    # this ordering for free)
                    if batch:
                        prev, pending = pending, self._dispatch_chunk(batch)
                        batch = []
                        if prev is not None:
                            self._collect_chunk(prev)
                    if pending is not None:
                        self._collect_chunk(pending)
                        pending = None
                    if self._needs_workflow_fetch(record):
                        # park processing; resume once the workflow arrives
                        # from the system partition (reference WorkflowCache
                        # async fetch — EventLifecycleContext.async
                        # restructured as pause/resume)
                        self.broker.fetch_workflow(
                            record.value.bpmn_process_id,
                            record.value.workflow_key,
                            on_done=self._schedule_processing_after_fetch,
                        )
                        parked = True
                        break
                # the one-fetch-per-parked-record latch applies to the record
                # it parked on, not to later records swept into this drain
                self._fetch_attempted = False
                batch.append(record)
                if len(batch) >= self._DRAIN_BATCH:
                    # the swap happens BEFORE collecting the previous wave,
                    # so even if that collect raises, the just-dispatched
                    # wave (whose records the cursor already passed) is
                    # still collected by the finally below — never lost
                    prev, pending = pending, self._dispatch_chunk(batch)
                    batch = []
                    if prev is not None:
                        self._collect_chunk(prev)
            if batch:
                prev, pending = pending, self._dispatch_chunk(batch)
                if prev is not None:
                    self._collect_chunk(prev)
        finally:
            # the in-flight wave's responses/appends must land even when a
            # dispatch or an earlier collect raises — its records are
            # already consumed into engine state and will not re-drain
            if pending is not None:
                self._collect_chunk(pending)
        if parked:
            return
        self.pump_topic_subscriptions()

    def _dispatch_chunk(self, records: list):
        """Process one drained chunk. Engines with the wave pipeline
        (``dispatch_wave``/``collect_wave`` — the device engine) only
        DISPATCH here and return the pending wave; the caller collects the
        PREVIOUS wave while the device computes this one (host staging/
        readback of waves N+1/N−1 overlap device compute of wave N — JAX
        async dispatch chains the state dependency on device). Synchronous
        engines process + apply inline and return None.

        NOTE on granularity: the chunk is the retry unit. If the engine
        raises mid-chunk (an engine bug — processing is non-throwing by
        contract), the whole chunk reprocesses on the next drain, same
        at-least-once hazard the per-record loop had, with a chunk-sized
        blast radius.
        """
        from zeebe_tpu.runtime.metrics import observe_wave

        tracer = tracing.TRACER
        if tracer is not None and tracer.by_position:
            tracer.stamp_positions(
                self.partition_id, tracing.positions_of(records),
                tracing.WAVE_DISPATCH, device=self.device_index,
            )
        dispatch = getattr(self.engine, "dispatch_wave", None)
        if dispatch is None:
            import time as _time

            t0 = _time.perf_counter()
            result = self.engine.process_batch(records)
            self.next_read_position = records[-1].position + 1
            self._apply_chunk(records, result)
            observe_wave(
                len(records), self._DRAIN_BATCH,
                host_seconds=_time.perf_counter() - t0,
            )
            return None
        wave = dispatch(records)
        # advance at dispatch: the records are consumed into device state
        self.next_read_position = records[-1].position + 1
        return wave

    def _collect_chunk(self, wave) -> None:
        """Materialize a dispatched wave's outputs and apply them (appends,
        responses, sends, pushes) in log order."""
        from zeebe_tpu.engine.interpreter import ProcessingResult
        from zeebe_tpu.runtime.metrics import observe_wave

        merged = ProcessingResult.merged(self.engine.collect_wave(wave))
        tracer = tracing.TRACER
        if tracer is not None and tracer.by_position:
            tracer.stamp_positions(
                self.partition_id, tracing.positions_of(wave.records),
                tracing.DEVICE_COLLECT, device=self.device_index,
            )
        self._apply_chunk(wave.records, merged)
        observe_wave(
            len(wave.records), self._DRAIN_BATCH,
            wave.host_seconds, wave.device_seconds,
        )

    def _apply_chunk(self, records: list, result) -> None:
        tracer = tracing.TRACER
        if tracer is not None and tracer.by_position:
            tracer.stamp_positions(
                self.partition_id, tracing.positions_of(records),
                tracing.APPLY,
            )
        if result.written:
            # every follow-up was source-stamped per record by the engine;
            # positions are assigned on the raft actor at append time, and
            # the records register into records_by_position when the
            # processing loop reads them back as committed. Device
            # emissions may ride as LAZY columnar refs — as_log_batch
            # keeps them lazy all the way into the log tail.
            from zeebe_tpu.protocol.columnar import as_log_batch

            observe_append(
                self.raft.append(as_log_batch(result.written)),
                "engine follow-up records", self.partition_id,
            )
        for response in result.responses:
            self.broker.send_client_response(response, server=self)
        for target_pid, send in result.sends:
            self.broker.route_send(self.partition_id, target_pid, send)
        for subscriber_key, push in result.pushes:
            self.broker.push_to_subscriber(subscriber_key, self.partition_id, push)
        self.broker.metrics_events_processed.inc(len(records))
        if self.partition_id == 0:
            # topic orchestration lives on the system partition only; the
            # guard also keeps lazy columnar rows on data partitions from
            # materializing just to be inspected and discarded
            for record in records:
                self._maybe_orchestrate_topic(record)

    def _maybe_orchestrate_topic(self, record) -> None:
        from zeebe_tpu.protocol.enums import RecordType, ValueType
        from zeebe_tpu.protocol.intents import TopicIntent

        if (
            self.partition_id == 0
            and record.metadata.value_type == ValueType.TOPIC
            and record.metadata.record_type == RecordType.EVENT
            and record.metadata.intent == int(TopicIntent.CREATING)
        ):
            self.broker.start_topic_orchestration(record)

    def pump_topic_subscriptions(self) -> None:
        """Deliver committed records to open topic subscriptions with credit
        flow control (reference TopicSubscriptionPushProcessor:36)."""
        from zeebe_tpu.protocol.enums import ValueType

        for key, pusher in list(self.topic_pushers.items()):
            while len(pusher["unacked"]) < pusher["capacity"]:
                batch = self.log.reader(pusher["cursor"]).read_committed()
                if not batch:
                    break
                advanced = False
                for record in batch:
                    if len(pusher["unacked"]) >= pusher["capacity"]:
                        break
                    pusher["cursor"] = record.position + 1
                    advanced = True
                    if record.metadata.value_type in (
                        ValueType.SUBSCRIBER, ValueType.SUBSCRIPTION,
                        ValueType.EXPORTER,
                    ):
                        continue
                    if not pusher["push"](record):
                        # dead connection: the close listener removes the
                        # pusher; stop delivering now
                        self.topic_pushers.pop(key, None)
                        advanced = False
                        break
                    pusher["unacked"].append(record.position)
                if not advanced:
                    break

    def _needs_workflow_fetch(self, record) -> bool:
        from zeebe_tpu.protocol.enums import RecordType, ValueType
        from zeebe_tpu.protocol.intents import WorkflowInstanceIntent as WI

        if self.partition_id == 0 or self._fetch_attempted:
            return False
        md = record.metadata
        if (
            md.value_type != ValueType.WORKFLOW_INSTANCE
            or md.record_type != RecordType.COMMAND
            or md.intent != int(WI.CREATE)
        ):
            return False
        repo = self.broker.repository
        value = record.value
        if value.workflow_key >= 0 and value.workflow_key in repo.by_key:
            return False
        if value.bpmn_process_id and repo.latest(value.bpmn_process_id) is not None:
            return False
        return True

    def _schedule_processing_after_fetch(self) -> None:
        # one attempt per parked record: if the fetch produced nothing the
        # engine now processes the command and rejects it (workflow not
        # found), instead of fetch-looping forever
        self._fetch_attempted = True
        self._schedule_processing()

    def snapshot(self) -> Optional[threading.Thread]:
        """Snapshot-while-serving: a brief fenced CAPTURE here on the
        broker actor (serialized with the wave drain, so it lands exactly
        at a wave boundary and grabs/encodes only the dirty state
        families), then the expensive hash/compress/fsync COMMIT on a
        worker thread — serving continues during it. At most one take is
        in flight per partition (an overlapping period tick is skipped and
        counted). Returns the commit thread, or None when nothing started.
        """
        if not self.is_leader or self.engine is None:
            return None
        if self._snapshot_inflight:
            count_event(
                "snapshot_skipped_inflight",
                "Snapshot ticks skipped because the partition's previous "
                "take was still committing",
            )
            return None
        meta = SnapshotMetadata(
            last_processed_position=self.next_read_position - 1,
            last_written_position=self.log.next_position - 1,
            term=self.raft.term,
        )
        record_event(
            "snapshot", "take started", node=self.broker.node_id,
            partition=self.partition_id,
            processed=meta.last_processed_position,
        )
        try:
            pending = self.snapshots.capture(self.engine, meta)
        except Exception as e:  # noqa: BLE001 - a failing capture must not
            # take down the snapshot loop for other partitions
            count_event(
                "snapshot_take_failures",
                "Snapshot takes that raised (capture or commit)",
            )
            logger.error(
                "snapshot capture failed on partition %d: %r",
                self.partition_id, e,
            )
            return None
        try:
            # compaction floor reads engine state — compute it inside the
            # fence, not on the worker thread
            pending.compaction_floor = min(
                meta.last_processed_position + 1,
                self.engine.compaction_floor(),
            )
            self._snapshot_inflight = True
            thread = threading.Thread(
                target=self._commit_snapshot,
                args=(pending,),
                name=f"zb-snapshot-commit-{self.partition_id}",
                daemon=True,
            )
            self._snapshot_thread = thread
            thread.start()
        except Exception as e:  # noqa: BLE001 - the capture fence already
            # reset the dirty tracking: merge the captured families back so
            # the next take re-captures them, and never leave the in-flight
            # guard stuck (e.g. a thread-spawn failure under resource
            # exhaustion would otherwise disable snapshots forever)
            self._snapshot_inflight = False
            count_event(
                "snapshot_take_failures",
                "Snapshot takes that raised (capture or commit)",
            )
            logger.error(
                "snapshot start failed on partition %d: %r",
                self.partition_id, e,
            )
            if self.engine is pending.engine and self.engine is not None:
                self.engine.snapshot_mark_dirty(pending.dirty)
            return None
        return thread

    def _commit_snapshot(self, pending) -> None:
        """Off-actor snapshot commit (hash + compress + fsync + manifest
        rename + purge). Touches only the captured parts and the snapshot
        storage — never live engine state."""
        try:
            self.snapshots.commit(pending)
        except Exception as e:  # noqa: BLE001 - isolate per partition
            count_event(
                "snapshot_take_failures",
                "Snapshot takes that raised (capture or commit)",
            )
            logger.error(
                "snapshot commit failed on partition %d (%s): %r",
                self.partition_id, pending.metadata.dirname, e,
            )
            dirty = pending.dirty

            def remark() -> None:
                # the captured families were never committed: re-mark them
                # so the next take re-captures (skip if the engine was
                # replaced — a fresh engine starts with cold tracking)
                if self.engine is not None and self.engine is pending.engine:
                    self.engine.snapshot_mark_dirty(dirty)

            try:
                self.broker.actor_control.run(remark)
            except Exception:  # noqa: BLE001 - broker closing
                pass
        else:
            # leader-side compaction below the snapshot (bounded by the
            # engine's incident/exporter floor, computed at capture).
            # Followers that fall below the new base catch up via snapshot
            # replication + log fast-forward.
            floor = pending.compaction_floor
            try:
                self.raft.actor.run(lambda: self.log.compact(floor))
            except Exception:  # noqa: BLE001 - broker closing
                pass
        finally:
            self._snapshot_inflight = False

    def close(self) -> None:
        if self.broker.wave_scheduler is not None:
            self.broker.wave_scheduler.unregister(self.partition_id)
        if self.broker.device_plan is not None:
            self.broker.device_plan.release(self.partition_id)
        if self.exporter_director is not None:
            self.exporter_director.close()
            self.exporter_director = None
        thread = self._snapshot_thread
        if thread is not None and thread.is_alive():
            # bounded: an in-flight commit interrupted here is exactly a
            # crash mid-commit, which the storage's salvage sweep handles
            thread.join(5)
        self.raft.close()
        self.storage.close()


class ClusterBroker(Actor):
    """A broker node: gossip + topology + partitions + client/management
    APIs. Create several in one process for a cluster (the reference's
    ClusteringRule runs 3 real brokers in one JVM)."""

    def __init__(
        self,
        cfg: BrokerCfg,
        data_dir: str,
        scheduler: Optional[ActorScheduler] = None,
        clock: Optional[Callable[[], int]] = None,
        engine_factory: Optional[
            Callable[[int, "ClusterBroker"], PartitionEngine]
        ] = None,
    ):
        super().__init__(f"broker-{cfg.cluster.node_id}")
        self.cfg = cfg
        # fail construction loudly on a misconfigured exporter (same
        # contract as the in-process Broker): deferred to the leadership
        # install, the error would fire inside an actor job and wedge the
        # partition as a leader that never processes
        if cfg.exporters:
            from zeebe_tpu.exporter import build_exporter

            seen_ids = set()
            for spec in cfg.exporters:
                if spec.id in seen_ids:
                    # shared replicated position entry: the faster
                    # exporter's ack would mask the slower one's gap
                    raise ValueError(f"duplicate exporter id {spec.id!r}")
                seen_ids.add(spec.id)
                build_exporter(spec)
        self._engine_factory = engine_factory
        self.node_id = cfg.cluster.node_id
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.clock = clock or SystemClock()
        self._own_scheduler = scheduler is None
        self._closing = False
        self._bootstrap_started = False
        self._default_topics_created = False
        self.scheduler = scheduler or ActorScheduler(
            cpu_threads=cfg.threads.cpu_thread_count,
            io_threads=cfg.threads.io_thread_count,
        ).start()

        self.metrics = MetricsRegistry()
        self.metrics_events_processed = self.metrics.counter(
            "stream_processor_events_processed", "Committed records processed"
        )
        # actor failures are escalated, never silently swallowed (reference
        # ActorTask failure handling; round-4 lesson — a NameError in the
        # broker tick survived 468 green tests): every failure counts into
        # metrics, repeated failures flip broker health.
        self.metrics_actor_failures = self.metrics.counter(
            "actor_failures", "Actor jobs that raised an exception"
        )
        self._unhealthy_reason: Optional[str] = None
        # only watch a scheduler this broker owns: on a SHARED scheduler
        # another broker's failures must not flip this broker's health
        # (and close() must not leave a bound-method listener behind)
        if self._own_scheduler:
            self.scheduler.on_actor_failure(self._on_actor_failure)
        self.metrics_http = None
        if cfg.metrics.enabled:
            self.metrics_writer = MetricsFileWriter(
                self.metrics,
                os.path.join(data_dir, cfg.metrics.file),
                self.scheduler,
                cfg.metrics.flush_period_ms,
            )
            if cfg.metrics.port:
                from zeebe_tpu.runtime.metrics import MetricsHttpServer

                try:
                    self.metrics_http = MetricsHttpServer(
                        self.metrics, host=cfg.network.host, port=cfg.metrics.port
                    )
                except OSError as e:
                    # a second broker on the host (no portOffset) or any
                    # process on the port must not make broker construction
                    # fail — metrics serving is best-effort, the file
                    # writer keeps running (round-3 advisor finding)
                    logger.warning(
                        "metrics endpoint bind failed on %s:%d (%s); "
                        "continuing without /metrics",
                        cfg.network.host, cfg.metrics.port, e,
                    )
                    self.metrics_http = None

        self.repository = WorkflowRepository()
        self.topology = Topology()
        # partition id → in-flight snapshot-replication fetch thread
        self._snapshot_fetches: Dict[int, threading.Thread] = {}
        self.partitions: Dict[int, PartitionServer] = {}
        self._pending_responses: Dict[int, ActorFuture] = {}
        # client-command dedup: cid → response future of the first append
        # (bounded FIFO; see _handle_command)
        self._cmd_dedup: Dict[str, ActorFuture] = {}

        # continuous-batching wave scheduler: ONE drain job per broker
        # packs committed records from ALL leader partitions into shared
        # device waves (cfg.scheduler.enabled=false restores the
        # per-partition drain — the bench's A/B baseline)
        from zeebe_tpu.scheduler import (
            AdmissionConfig,
            AdmissionController,
            WaveScheduler,
        )

        sc = cfg.scheduler
        self.wave_scheduler = (
            WaveScheduler(
                wave_size=sc.wave_size,
                quantum=sc.quantum or None,
                backpressure_limit=sc.backpressure_limit or None,
                # like the raft commit watchdog, the slow-wave threshold
                # is an operator knob independent of [tracing] enabled
                slow_wave_ms=cfg.tracing.slow_wave_ms,
            )
            if sc.enabled
            else None
        )
        self._drain_scheduled = False
        # mesh-sharded serving plane: leader partitions place across the
        # visible devices (scheduler/placement.DevicePlan) so different
        # partitions' wave segments compute on DIFFERENT devices within
        # one scheduling round. Built lazily on the first placement ask
        # (host-engine brokers never touch jax device init); cross-
        # partition command frames optionally ride the mesh's all_to_all
        # exchange instead of the host transport hop (route_send).
        self.device_plan = None
        self._mesh_exchange_obj = None
        self._mesh_exchange_failed = False
        # gateway admission: bounded in-flight per client connection +
        # queue-depth shed, checked on the transport IO thread BEFORE a
        # command touches the broker actor (shed-before-collapse)
        ad = cfg.admission
        self.admission = AdmissionController(
            AdmissionConfig(
                enabled=ad.enabled,
                max_inflight_per_connection=ad.max_inflight_per_connection,
                queue_depth_high=ad.queue_depth_high,
                retry_after_ms=ad.retry_after_ms,
            ),
            queue_depth_probe=self._queue_depth,
        )
        self._admission_conns: set = set()
        # request ids are stamped INTO replicated records and responses
        # are matched by id alone on whichever broker processes the
        # record — so the id space must not collide across brokers (a
        # failover can make broker B emit the response for a command
        # broker A appended, and a sequential id starting at 0 on every
        # broker then completes an UNRELATED pending request on B with
        # it: a deploy response surfacing from create_instance). A random
        # 47-bit base per broker incarnation makes overlap negligible
        # and also covers ids replayed across a restart.
        self._next_request_id = random.getrandbits(47)
        self._push_listeners: Dict[int, Callable[[int, Record], None]] = {}
        self._request_lock = threading.Lock()
        # bounded cache for chunked snapshot serving (avoids re-reading
        # and re-checksumming the file once per 256K chunk); keyed by
        # (partition, snapshot metadata), insertion-ordered for LRU drop
        self._snapshot_serve_cache: Dict[tuple, tuple] = {}

        # gossip (management-plane membership + topology dissemination)
        self.gossip = Gossip(
            self.node_id,
            self.scheduler,
            config=GossipConfig(
                probe_interval_ms=cfg.gossip.probe_interval_ms,
                probe_timeout_ms=cfg.gossip.probe_timeout_ms,
                probe_indirect_nodes=cfg.gossip.probe_indirect_nodes,
                suspicion_multiplier=cfg.gossip.suspicion_multiplier,
                sync_interval_ms=cfg.gossip.sync_interval_ms,
            ),
            host=cfg.network.host,
            port=cfg.network.management_port,
        )
        self.gossip.on_custom_event("partition-leader", self._on_leader_event)
        self.gossip.on_custom_event("node-info", self._on_node_info_event)

        # client + subscription servers on the configured socket bindings
        # (reference zeebe.cfg.toml [network.*]; tests set the ports to 0
        # for ephemeral binds, the reference EmbeddedBrokerRule pattern)
        self.client_server = ServerTransport(
            host=cfg.network.host,
            port=cfg.network.client_port,
            request_handler=self._on_client_request,
        )
        self.subscription_server = ServerTransport(
            host=cfg.network.host,
            port=cfg.network.subscription_port,
            request_handler=self._on_subscription_request,
            message_handler=self._on_subscription_message,
        )
        self.client_transport = ClientTransport()

        self.scheduler.submit_actor(self)  # zblint: disable=unobserved-actor-future (boot submit; start failures land in the scheduler failure ring)
        self.actor_control = None  # set in on_actor_started

        # periodic snapshotting (reference snapshotPeriod)
        self._snapshot_period_ms = cfg.data.snapshot_period_ms

        # record-lifecycle tracing: one span tracer per process (like the
        # global metrics registry); [tracing] enabled=false uninstalls it
        # and every stamp site degrades to a single read of tracing.TRACER
        tracing.ensure_tracer(cfg.tracing)
        # boot marker: restarts anchor every flight-recorder dump
        record_event(
            "broker", "broker started", node=self.node_id,
            partitions=cfg.cluster.partitions, engine=cfg.engine.type,
        )

    # -- lifecycle ---------------------------------------------------------
    def on_actor_started(self) -> None:
        self.actor_control = self.actor
        self.actor.run_at_fixed_rate(
            self._snapshot_period_ms, self._snapshot_all_on_actor
        )
        self.actor.run_at_fixed_rate(100, self._tick_engines)
        # disseminate this node's client endpoint so the topic orchestrator
        # can reach any member over the management plane (reference: local
        # node info broadcast via gossip custom events)
        self._publish_node_info()
        self.actor.run_at_fixed_rate(2000, self._publish_node_info)
        # followers poll partition leaders for snapshots (reference
        # snapshotReplicationPeriod, default 5m)
        self.actor.run_at_fixed_rate(
            self.cfg.data.snapshot_replication_period_ms, self._replicate_snapshots
        )
        # self-assembly (reference BootstrapExpectNodes/BootstrapSystemTopic/
        # BootstrapDefaultTopicsService): join configured contact points, and
        # once the expected node count gossips alive, the smallest node id
        # bootstraps the system partition, then the configured topics
        if self.cfg.cluster.initial_contact_points:
            self.join(
                [
                    RemoteAddress(hp.split(":")[0], int(hp.split(":")[1]))
                    for hp in self.cfg.cluster.initial_contact_points
                ]
            ).on_complete(self._on_join_result)
        self.actor.run_at_fixed_rate(500, self._maybe_bootstrap)

    def _publish_node_info(self) -> None:
        self.gossip.publish_custom_event(
            "node-info",
            {
                "node": self.node_id,
                "client": [self.client_address.host, self.client_address.port],
            },
        )

    def _on_node_info_event(self, _sender: str, payload) -> None:
        if isinstance(payload, dict) and payload.get("node"):
            self.topology.members[str(payload["node"])] = list(
                payload.get("client", ["", 0])
            )

    @property
    def gossip_address(self) -> RemoteAddress:
        return self.gossip.address

    @property
    def client_address(self) -> RemoteAddress:
        return self.client_server.address

    def join(self, contact_points: List[RemoteAddress]) -> ActorFuture:
        return self.gossip.join(contact_points)

    def _on_join_result(self, future: ActorFuture) -> None:
        """A node that exhausts its join retries is alive but invisible to
        the cluster — without this, the only symptom is a topology that
        never reaches the expected node count."""
        exc = getattr(future, "_exception", None)
        if exc is not None:
            count_event(
                "gossip_join_failures",
                "Boot-time gossip joins that exhausted their retries",
            )
            logger.error(
                "broker %s: join via configured contact points failed "
                "(node is up but not in the cluster topology): %r",
                self.node_id, exc,
            )

    def open_partition(self, partition_id: int) -> ActorFuture:
        """Create/open a partition (log + raft endpoint, not yet clustered);
        completes with the local raft address. Reference:
        CreatePartitionRequest → PartitionInstallService composite install."""

        def do():
            if partition_id not in self.partitions:
                self.partitions[partition_id] = PartitionServer(self, partition_id)
            return self.partitions[partition_id].raft.address

        return self.actor.call(do)

    def bootstrap_partition(
        self, partition_id: int, members: Dict[str, RemoteAddress]
    ) -> None:
        """Install the raft membership (self included) and start the
        election clock."""

        def do():
            server = self.partitions[partition_id]
            raft_members = dict(members)
            raft_members[self.node_id] = server.raft.address
            server.raft.bootstrap(raft_members)

        self.actor.run(do)

    def _new_engine(self, partition_id: int):
        """Build the stream-processing engine for a partition this node
        leads. Default is the host oracle engine; pass ``engine_factory``
        (e.g. ``TpuPartitionEngine``) to serve partitions from the device
        kernel — the factory is the cluster analogue of the single-node
        Broker's ``engine_factory``."""
        if self._engine_factory is not None:
            # fixed, documented signature: factory(partition_id, broker) —
            # the broker gives factories access to the shared repository
            # and clock without arity guessing
            return self._engine_factory(partition_id, self)
        return PartitionEngine(
            partition_id=partition_id,
            num_partitions=self.cfg.cluster.partitions,
            repository=self.repository,
            clock=self.clock,
        )

    # -- mesh placement (scheduler/placement.DevicePlan) --------------------
    def _mesh_plan(self):
        if not self.cfg.mesh.enabled:
            return None
        if self.device_plan is None:
            from zeebe_tpu.scheduler.placement import DevicePlan

            self.device_plan = DevicePlan(max_devices=self.cfg.mesh.devices)
        return self.device_plan

    def planned_device(self, partition_id: int):
        """(device, device index) for a leader partition — assigned sticky
        by the DevicePlan at engine install; (None, -1) when the mesh is
        disabled. Engine factories consult this (runtime/engines.py)."""
        plan = self._mesh_plan()
        if plan is None:
            return None, -1
        idx = plan.assign(partition_id)
        return plan.devices[idx], idx

    def planned_span(self, partition_id: int):
        """(devices, plan indices) for a SHARDED-state leader partition —
        a span of ``[mesh] shardedPartitions`` devices its row tables
        block-shard over. ([], []) when the mesh is disabled or sharding
        is off; the factory then falls back to ``planned_device``."""
        span = int(getattr(self.cfg.mesh, "sharded_partitions", 0))
        if span <= 1:
            return [], []
        plan = self._mesh_plan()
        if plan is None:
            return [], []
        indices = plan.assign_span(partition_id, span)
        return [plan.devices[i] for i in indices], indices

    def _mesh_exchange(self):
        """The all_to_all frame exchange, built once over the plan's
        devices; None when unavailable (single device, mesh disabled, or
        a build failure — counted, transport keeps working)."""
        if self._mesh_exchange_obj is not None:
            return self._mesh_exchange_obj
        if self._mesh_exchange_failed:
            return None
        plan = self.device_plan
        if plan is None or len(plan.devices) < 2:
            return None
        try:
            from zeebe_tpu.scheduler.placement import MeshExchange

            self._mesh_exchange_obj = MeshExchange(
                plan.devices,
                slots=self.cfg.mesh.exchange_slots,
                frame_bytes=self.cfg.mesh.exchange_frame_bytes,
            )
        except Exception as e:  # noqa: BLE001 - the transport hop is the
            # always-correct fallback; never wedge serving on the exchange
            self._mesh_exchange_failed = True
            record_event(
                "mesh", "exchange unavailable (transport fallback)",
                node=self.node_id, error=repr(e),
            )
            logger.error(
                "mesh frame exchange unavailable (falling back to the "
                "host transport hop): %r", e,
            )
        return self._mesh_exchange_obj

    def exclude_device(self, device_index: int) -> ActorFuture:
        """Operator/health entry: mark a mesh device dead. Its partitions
        rebalance onto the remaining healthy devices and their LIVE engine
        state migrates there (``place_on``). Runs on the broker actor —
        serialized with the wave drain, so no wave is in flight across the
        migration. Completes with {partition_id: new device index}."""

        def do():
            plan = self.device_plan
            if plan is None:
                return {}
            moves = plan.exclude(device_index)
            # the frame exchange spans ALL plan devices — a collective
            # over a dead chip hangs/fails, so cross-partition frames
            # fall back to the host transport hop from here on
            self._mesh_exchange_obj = None
            self._mesh_exchange_failed = True
            for pid, new_idx in moves.items():
                server = self.partitions.get(pid)
                if server is None or server.engine is None:
                    continue
                place = getattr(server.engine, "place_on", None)
                if place is None:
                    continue
                try:
                    place(plan.devices[new_idx], new_idx)
                except Exception:  # noqa: BLE001 - the chip is REALLY
                    # gone: its committed arrays are unreadable, so the
                    # state migrates the durable way instead — rebuild
                    # the engine from snapshot + committed-log replay
                    # (both host-side) via the normal leadership install,
                    # which places onto the rebalanced device
                    count_event(
                        "mesh_state_migration_failures",
                        "Live-state migrations off an excluded device "
                        "that failed (partition reinstalled from "
                        "snapshot + replay instead)",
                    )
                    logger.exception(
                        "live-state migration off device %d failed for "
                        "partition %d; reinstalling from snapshot+replay",
                        device_index, pid,
                    )
                    term = server.raft.term
                    # same-node reinstall: leadership stays here, so the
                    # partition's live spans are NOT orphaned
                    server._uninstall_leader(orphan_spans=False)
                    server._install_leader(term)
            if moves:
                record_event(
                    "mesh", "device excluded", node=self.node_id,
                    device=device_index, moves=dict(moves),
                )
                logger.warning(
                    "mesh device %d excluded; partitions rebalanced: %s",
                    device_index, moves,
                )
            return moves

        return self.actor.call(do)

    def readmit_device(self, device_index: int) -> ActorFuture:
        """Undo ``exclude_device`` once the device is healthy again: new
        placements may land on it, and the frame exchange (disabled at
        exclusion — its collective spans every plan device) rebuilds
        lazily on the next eligible send. Already-moved partitions stay
        where they are; leadership churn rebalances over time."""

        def do():
            plan = self.device_plan
            if plan is None:
                return
            plan.readmit(device_index)
            self._mesh_exchange_failed = False

        return self.actor.call(do)

    def route_send(self, source_partition: int, target_partition: int,
                   record: Record) -> None:
        """Cross-partition command routing: when BOTH partitions are
        device-resident leaders on this broker, the encoded frame rides
        the mesh's all_to_all exchange (flushed once per scheduling round
        in ``_drain_committed``) instead of the host transport hop;
        everything else takes ``send_subscription_command``."""
        if self._queue_mesh_send(source_partition, target_partition, record):
            return
        self.send_subscription_command(target_partition, record)

    def _queue_mesh_send(self, source_partition: int, target_partition: int,
                         record: Record) -> bool:
        if self.wave_scheduler is None or not self.cfg.mesh.exchange:
            return False
        plan = self.device_plan
        if plan is None:
            return False
        target = self.partitions.get(target_partition)
        if target is None or not target.is_leader or target.engine is None:
            return False
        src = plan.device_index(source_partition)
        dst = plan.device_index(target_partition)
        if src < 0 or dst < 0:
            return False
        if src == dst:
            # same device: there is no hop to ride (not even ICI) — the
            # direct local append is strictly cheaper
            return False
        exchange = self._mesh_exchange()
        if exchange is None:
            return False
        if exchange.queue(
            src, dst, target_partition, codec.encode_record(record)
        ):
            return True
        # refused (oversize / pair slots full): frames queued EARLIER in
        # this round must land first — flush them now, then let the
        # caller take the transport path, so per-destination command
        # order is preserved across the mixed routing (a CLOSE appended
        # before the OPEN it follows would strand a stale subscription)
        if exchange.pending():
            self._flush_mesh_exchange()
        return False

    def _flush_mesh_exchange(self) -> None:
        """One collective exchange for the scheduling round's queued
        frames; arrivals append at their destination partition exactly
        like transport arrivals would (decode → position/timestamp reset →
        raft append, deposed-leader failures re-entering the retry loop)."""
        exchange = self._mesh_exchange_obj
        if exchange is None or not exchange.pending():
            return
        try:
            exchange.flush(self._deliver_mesh_frame)
        except Exception as e:  # noqa: BLE001 - belt: flush handles
            # collective/delivery failures internally (direct host
            # delivery of the snapshot), so this only catches bugs in
            # the flush plumbing itself
            count_event(
                "mesh_exchange_flush_failures",
                "Mesh exchange frame deliveries that raised",
            )
            logger.error("mesh exchange flush failed: %r", e)

    def _deliver_mesh_frame(self, partition_id: int, frame: bytes) -> None:
        record, _ = codec.decode_record(bytes(frame))
        record.position = -1
        record.timestamp = -1
        # same append contract as the transport path (leadership may have
        # moved between queue and flush: send_subscription_command's
        # fast-path/retry split handles every case)
        self.send_subscription_command(partition_id, record)

    def _on_actor_failure(self, actor, exc: BaseException) -> None:
        """Scheduler failure listener: every swallowed actor exception is
        counted; 3+ during a broker's lifetime flip health to unhealthy
        (reference: actor failure escalates through ActorTask and fails
        the component's health check)."""
        if self._closing:
            return  # shutdown races (sockets closing under actors) don't
            # indict a live broker's health
        self.metrics_actor_failures.inc()
        if self.metrics_actor_failures.value >= 3 and self._unhealthy_reason is None:
            self._unhealthy_reason = f"repeated actor failures (last: {actor.name}: {exc!r})"
            logger.error(
                "broker %s marked UNHEALTHY: %s", self.node_id, self._unhealthy_reason
            )

    def healthy(self) -> bool:
        """False once repeated actor failures were observed; surfaced so
        harnesses/tests fail loudly instead of running on a broken tick."""
        return self._unhealthy_reason is None

    def close(self) -> None:
        self._closing = True
        record_event("broker", "broker closed", node=self.node_id)
        self.scheduler.remove_actor_failure_listener(self._on_actor_failure)
        if self.metrics_http is not None:
            self.metrics_http.close()
        for server in self.partitions.values():
            server.close()
        self.gossip.close()
        self.client_server.close()
        self.subscription_server.close()
        self.client_transport.close()
        if self._own_scheduler:
            self.scheduler.stop()

    # -- topology dissemination (gossip custom events) ----------------------
    def on_partition_leader(self, partition_id: int, term: int) -> None:
        """Called when THIS node becomes a partition's leader: update the
        local view and broadcast (reference: leadership broadcast as gossip
        custom event)."""
        addr = [self.client_address.host, self.client_address.port]
        sub = [self.subscription_server.address.host, self.subscription_server.address.port]
        self.topology.update_leader(partition_id, self.node_id, addr, sub, term)
        self.gossip.publish_custom_event(
            "partition-leader",
            {
                "partition": partition_id,
                "node": self.node_id,
                "addr": addr,
                "sub": sub,
                "term": term,
            },
        )

    def _on_leader_event(self, _sender: str, payload) -> None:
        if not isinstance(payload, dict):
            return
        self.topology.update_leader(
            int(payload.get("partition", -1)),
            payload.get("node", ""),
            payload.get("addr", ["", 0]),
            payload.get("sub", ["", 0]),
            int(payload.get("term", 0)),
        )

    # -- shared-wave drain (scheduler mode) ---------------------------------
    def _schedule_drain(self) -> None:
        """One drain job per burst of commits, broker-wide: every leader
        partition's committed tail packs into the same shared waves."""
        if self.wave_scheduler is None or self._drain_scheduled:
            return
        self._drain_scheduled = True
        self.actor_control.run(self._drain_committed)

    def _drain_committed(self) -> None:
        self._drain_scheduled = False
        if self.wave_scheduler is None:
            return
        try:
            self.wave_scheduler.drain()
        finally:
            # the round's cross-partition frames ride ONE collective over
            # the mesh (route_send queued them during the waves' applies)
            self._flush_mesh_exchange()
        for server in list(self.partitions.values()):
            if server.is_leader:
                # parked-record fetches start only once every in-flight
                # wave collected (a DEPLOYMENT inside the drain may have
                # provided the workflow)
                server.maybe_start_fetch()
                server.pump_topic_subscriptions()

    def _queue_depth(self) -> int:
        """Admission probe: committed records awaiting the drain (plus
        dispatched-but-unapplied, in scheduler mode) plus responses
        awaiting processing. Reads plain ints cross-thread — approximate
        by design (a watermark, not an invariant)."""
        depth = len(self._pending_responses)
        if self.wave_scheduler is not None:
            return depth + self.wave_scheduler.backlog()
        for server in list(self.partitions.values()):
            depth += server.backlog()
        return depth

    def _forget_admission(self, conn_key: int) -> None:
        self.admission.forget_connection(conn_key)
        self._admission_conns.discard(conn_key)

    # -- client API (reference ClientApiMessageHandler) ---------------------
    def _on_client_request(self, payload: bytes, conn):
        try:
            msg = msgpack.unpack(payload)
        except Exception:  # noqa: BLE001
            return None
        t = msg.get("t")
        if t == "command":
            # record-lifecycle tracing samples HERE — the earliest hop a
            # command is visible at (one global read when tracing is off)
            tracer = tracing.TRACER
            span = None
            if tracer is not None:
                span = tracer.maybe_sample(int(msg.get("partition", 0)))
            # admission runs HERE, on the transport thread, before the
            # command can queue behind the broker actor: overload is
            # answered with a retryable rejection in O(1), never with
            # queue time (shed-before-collapse)
            conn_key = getattr(conn, "key", None) if conn is not None else None
            if conn_key is not None:
                reason = self.admission.try_admit(conn_key)
                if reason is not None:
                    if span is not None:
                        # shed: the lifecycle ends here — finish the span
                        # so it never sits in the live budget
                        tracer.finish(
                            span, tracing.ADMISSION, verdict=reason
                        )
                    return msgpack.pack(self.admission.rejection_body(reason))
                if conn_key not in self._admission_conns:
                    self._admission_conns.add(conn_key)
                    conn.on_close(
                        lambda k=conn_key: self._forget_admission(k)
                    )
            if span is not None:
                tracer.stamp(span, tracing.ADMISSION, verdict="admitted")
                tracer.stamp(span, tracing.ACTOR_ENQUEUE)
                msg["_trace"] = span
            result = ActorFuture()
            if conn_key is not None:
                # the in-flight slot frees when the response (or error)
                # completes — every _handle_command path completes it
                result.on_complete(
                    lambda _f, k=conn_key: self.admission.release(k)
                )
            self.actor.run(lambda: self._handle_command(msg, result))
            return result
        if t == "topology":
            # answered inline on the transport thread: topology state has
            # its own lock, and the broker actor can be busy for the whole
            # duration of a cold device-kernel compile — every 2s-timeout
            # topology probe would fail, and clients see "no leader known"
            # while the leader is merely warming up
            return self._handle_topology_request()
        if t == "job-subscription":
            result = ActorFuture()
            self.actor.run(lambda: self._handle_job_subscription(msg, conn, result))
            return result
        if t == "topic-subscription":
            result = ActorFuture()
            self.actor.run(lambda: self._handle_topic_subscription(msg, conn, result))
            return result
        if t == "fetch-workflow":
            return self.actor.call(lambda: self._handle_fetch_workflow(msg))
        if t == "list-workflows":
            return self.actor.call(lambda: self._handle_list_workflows(msg))
        if t == "get-workflow":
            return self.actor.call(lambda: self._handle_get_workflow(msg))
        if t == "create-partition":
            return self._handle_create_partition(msg)
        if t == "bootstrap-partition":
            return self._handle_bootstrap_partition(msg)
        if t == "list-snapshots":
            return self._handle_list_snapshots(msg)
        if t == "fetch-snapshot-chunk":
            return self._handle_fetch_snapshot_chunk(msg)
        if t == "fetch-snapshot-manifest":
            return self._handle_fetch_snapshot_manifest(msg)
        if t == "fetch-snapshot-segment":
            return self._handle_fetch_snapshot_segment(msg)
        return None

    # -- snapshot replication (reference SnapshotReplicationService:55-128:
    # followers poll the leader and fetch snapshots chunk-wise so a
    # failover recovers from a snapshot instead of replaying the full log)
    def _handle_list_snapshots(self, msg: dict) -> bytes:
        server = self.partitions.get(int(msg.get("partition", 0)))
        if server is None:
            return msgpack.pack({"t": "ok", "snapshots": []})
        return msgpack.pack(
            {
                "t": "ok",
                "snapshots": [
                    {
                        "processed": m.last_processed_position,
                        "written": m.last_written_position,
                        "term": m.term,
                        # raft term OF the last-processed record: the
                        # fast-forwarded follower's last-entry term in
                        # elections (the leader's own term would inflate
                        # its log and let it depose better-logged peers)
                        "lp_term": server.log.term_at(
                            m.last_processed_position
                        ),
                    }
                    for m in server.snapshots.storage.list()
                ],
            }
        )

    def _handle_fetch_snapshot_chunk(self, msg: dict) -> bytes:
        from zeebe_tpu.log.snapshot import SnapshotMetadata

        server = self.partitions.get(int(msg.get("partition", 0)))
        if server is None:
            return msgpack.pack({"t": "error", "code": "NO_PARTITION"})
        meta = SnapshotMetadata(
            last_processed_position=int(msg.get("processed", -1)),
            last_written_position=int(msg.get("written", -1)),
            term=int(msg.get("term", 0)),
        )
        # serve ranged reads out of a small per-transfer cache — re-reading
        # and checksumming the whole snapshot per 256K chunk is quadratic
        # IO. Keyed per (partition, meta) so concurrent transfers (one
        # leader serving several follower partitions) don't thrash; bounded
        # LRU so completed transfers don't pin payloads forever.
        cache_key = (int(msg.get("partition", 0)), meta)
        cached = self._snapshot_serve_cache.get(cache_key)
        if cached is None:
            payload = server.snapshots.storage.read(meta)
            if payload is None:
                return msgpack.pack({"t": "error", "code": "NO_SNAPSHOT"})
            cached = (payload, zlib.crc32(payload))
            self._snapshot_serve_cache[cache_key] = cached
            while len(self._snapshot_serve_cache) > 4:
                self._snapshot_serve_cache.pop(
                    next(iter(self._snapshot_serve_cache))
                )
        payload, crc = cached
        offset = int(msg.get("offset", 0))
        length = min(max(int(msg.get("length", 1024 * 1024)), 0), 4 * 1024 * 1024)
        return msgpack.pack(
            {
                "t": "ok",
                "total": len(payload),
                "crc": crc,
                "chunk": payload[offset : offset + length],
            }
        )

    def _handle_fetch_snapshot_manifest(self, msg: dict) -> bytes:
        """Incremental replication: the part list of a manifest snapshot;
        the follower fetches only segments it does not already hold."""
        from zeebe_tpu.log.snapshot import SnapshotMetadata

        server = self.partitions.get(int(msg.get("partition", 0)))
        if server is None:
            return msgpack.pack({"t": "error", "code": "NO_PARTITION"})
        meta = SnapshotMetadata(
            last_processed_position=int(msg.get("processed", -1)),
            last_written_position=int(msg.get("written", -1)),
            term=int(msg.get("term", 0)),
        )
        entries = server.snapshots.storage.manifest(meta)
        if entries is None:
            # legacy single-blob snapshot (or gone): the follower falls
            # back to the ranged chunk fetch
            return msgpack.pack({"t": "error", "code": "NO_MANIFEST"})
        return msgpack.pack({"t": "ok", "parts": entries})

    def _handle_fetch_snapshot_segment(self, msg: dict) -> bytes:
        from zeebe_tpu.log.snapshot import SnapshotMetadata

        server = self.partitions.get(int(msg.get("partition", 0)))
        if server is None:
            return msgpack.pack({"t": "error", "code": "NO_PARTITION"})
        # the metadata keys scope the request to a live snapshot: segments
        # of purged snapshots may be GC'd mid-transfer, and the follower
        # restarts the transfer from list-snapshots in that case
        meta = SnapshotMetadata(
            last_processed_position=int(msg.get("processed", -1)),
            last_written_position=int(msg.get("written", -1)),
            term=int(msg.get("term", 0)),
        )
        entries = server.snapshots.storage.manifest(meta)
        if entries is None:
            return msgpack.pack({"t": "error", "code": "NO_MANIFEST"})
        h = str(msg.get("h", ""))
        if not any(e["h"] == h for e in entries):
            return msgpack.pack({"t": "error", "code": "NO_SEGMENT"})
        # ranged reads come 1MB at a time: serve from the bounded transfer
        # cache, not a full file re-read per chunk (quadratic IO on big
        # device-table segments — same fix as the legacy chunk handler)
        cache_key = (int(msg.get("partition", 0)), meta, h)
        cached = self._snapshot_serve_cache.get(cache_key)
        if cached is None:
            data = server.snapshots.storage.read_segment(h)
            if data is None:
                return msgpack.pack({"t": "error", "code": "NO_SEGMENT"})
            cached = (data, 0)
            self._snapshot_serve_cache[cache_key] = cached
            while len(self._snapshot_serve_cache) > 4:
                self._snapshot_serve_cache.pop(
                    next(iter(self._snapshot_serve_cache))
                )
        data = cached[0]
        offset = int(msg.get("offset", 0))
        length = min(max(int(msg.get("length", 1024 * 1024)), 0), 4 * 1024 * 1024)
        return msgpack.pack(
            {
                "t": "ok",
                "total": len(data),
                "chunk": data[offset : offset + length],
            }
        )

    def _replicate_snapshots(self) -> None:
        """Follower side: poll each partition's leader for new snapshots and
        fetch them chunk-wise (installed per follower partition —
        SnapshotReplicationInstallService parity). One in-flight fetch per
        partition: the poll period (can be 100s of ms in tests) must not
        pile up threads behind a slow leader — each fetch involves requests
        with multi-second timeouts."""
        for pid, server in list(self.partitions.items()):
            if server.is_leader:
                continue
            addr = self.topology.leader_address(pid)
            if addr is None:
                continue
            prev = self._snapshot_fetches.get(pid)
            if prev is not None and prev.is_alive():
                continue
            t = threading.Thread(
                target=self._fetch_snapshots_from_leader,
                args=(pid, server, addr),
                daemon=True,
                name=f"zb-snapshot-replication-{pid}",
            )
            self._snapshot_fetches[pid] = t
            t.start()

    def _fetch_snapshots_from_leader(self, pid: int, server, addr) -> None:
        from zeebe_tpu.log.snapshot import SnapshotMetadata

        try:
            rsp = msgpack.unpack(
                self.client_transport.send_request(
                    addr,
                    msgpack.pack({"t": "list-snapshots", "partition": pid}),
                    timeout_ms=3000,
                ).join(4)
            )
            if rsp.get("t") != "ok" or not rsp.get("snapshots"):
                return
            newest = max(rsp["snapshots"], key=lambda s: int(s["processed"]))
            meta = SnapshotMetadata(
                last_processed_position=int(newest["processed"]),
                last_written_position=int(newest["written"]),
                term=int(newest["term"]),
            )
            have = {
                (m.last_processed_position, m.last_written_position, m.term)
                for m in server.snapshots.storage.list()
            }
            key = (meta.last_processed_position, meta.last_written_position, meta.term)
            if key in have:
                return
            if not self._fetch_snapshot_into_storage(pid, server, addr, meta):
                return
            # snapshot catch-up ONLY when the leader told us we are below
            # its compaction floor (the snapshot_needed probe): a merely
            # lagging follower must keep receiving ordinary replication —
            # fast-forwarding it would discard records the snapshot does
            # not cover and mark them committed. The jump lands at the
            # snapshot's PROCESSED boundary; the tail (processed..written]
            # still exists on the leader (its floor never passes the
            # processed position) and replicates normally.
            if (
                server.raft.snapshot_needed
                and meta.last_processed_position >= server.log.next_position
            ):
                lp_term = int(newest.get("lp_term", -1))

                def _fast_forward():
                    server.log.fast_forward(
                        meta.last_processed_position + 1, term=lp_term
                    )
                    # the reset bypassed set_commit_position, so pending
                    # acked-means-committed futures (a deposed leader's)
                    # would never resolve — fail them so callers retry
                    server.raft.on_snapshot_fast_forward()

                server.raft.actor.run(_fast_forward)
        except Exception as e:  # noqa: BLE001 - next poll retries
            logger.debug(
                "snapshot replication fetch from %s for partition %d "
                "failed (next poll retries): %r", addr, pid, e,
            )

    def _fetch_snapshot_into_storage(self, pid: int, server, addr, meta) -> bool:
        """Transfer one snapshot from the leader into local storage.

        Incremental path first: fetch the manifest, then ONLY the segments
        this node does not already hold (unchanged tables from a prior
        checkpoint never re-cross the wire). Legacy single-blob snapshots
        fall back to the ranged chunk fetch."""
        man_rsp = msgpack.unpack(
            self.client_transport.send_request(
                addr,
                msgpack.pack({
                    "t": "fetch-snapshot-manifest",
                    "partition": pid,
                    "processed": meta.last_processed_position,
                    "written": meta.last_written_position,
                    "term": meta.term,
                }),
                timeout_ms=3000,
            ).join(4)
        )
        if man_rsp.get("t") == "ok":
            return self._fetch_snapshot_parts(
                pid, server, addr, meta, man_rsp.get("parts")
            )
        if man_rsp.get("code") == "NO_MANIFEST":
            return self._fetch_snapshot_legacy(pid, server, addr, meta)
        return False

    def _fetch_snapshot_parts(self, pid, server, addr, meta, entries) -> bool:
        from zeebe_tpu.log import snapshot as snapmod

        storage = server.snapshots.storage
        # validate the untrusted manifest before fetching anything
        if not isinstance(entries, list) or len(entries) > 10_000:
            return False
        clean = []
        total = 0
        for e in entries:
            try:
                name, h, length = str(e["n"]), str(e["h"]), int(e["l"])
            except (KeyError, TypeError, ValueError):
                return False
            if length < 0 or not snapmod._HASH_HEX_RE.match(h):
                return False
            total += length
            if total > stateser.MAX_SNAPSHOT_BYTES:
                return False
            clean.append({"n": name, "h": h, "l": length})
        parts: dict = {}
        for e in clean:
            h, length = e["h"], e["l"]
            data = None
            compressed = storage.read_segment(h) if storage.has_segment(h) else None
            if compressed is None:
                fetched = self._fetch_segment(pid, addr, meta, h)
                if fetched is None:
                    return False
                data = storage.install_segment(h, fetched, max_len=length)
                if data is None:
                    return False
            else:
                # local segment from a prior transfer: re-verify through
                # the shared check before the pre-install decode
                data = storage.verify_segment(h, compressed, length)
                if data is None:
                    return False
            if len(data) != length:
                return False
            parts[e["n"]] = data
        # the fetched snapshot must decode under the data-only codec before
        # it can ever be offered to recovery
        try:
            stateser.decode_state_parts(parts)
        except stateser.SnapshotFormatError:
            return False
        return storage.install_manifest(meta, clean)

    def _fetch_chunked(self, addr, body_base: dict):
        """Ranged fetch of one remote blob. Returns (payload, crc-or-None)
        or None on any protocol violation; the remote size field is never
        trusted blindly (bounded buffering, stable across chunks)."""
        chunks = []
        offset = 0
        expect_total = None
        expect_crc = None
        while True:
            rsp = msgpack.unpack(
                self.client_transport.send_request(
                    addr,
                    msgpack.pack({**body_base, "offset": offset}),
                    timeout_ms=5000,
                ).join(6)
            )
            if rsp.get("t") != "ok":
                return None
            total = int(rsp.get("total", 0))
            if total < 0 or total > stateser.MAX_SNAPSHOT_BYTES:
                return None
            if expect_total is None:
                expect_total = total
                expect_crc = rsp.get("crc")
            elif total != expect_total:
                return None
            chunk = bytes(rsp.get("chunk", b""))
            chunks.append(chunk)
            offset += len(chunk)
            if offset > expect_total:
                return None
            if offset >= expect_total or not chunk:
                break
        return b"".join(chunks), expect_crc

    def _fetch_segment(self, pid, addr, meta, h) -> "bytes | None":
        got = self._fetch_chunked(addr, {
            "t": "fetch-snapshot-segment",
            "partition": pid,
            "processed": meta.last_processed_position,
            "written": meta.last_written_position,
            "term": meta.term,
            "h": h,
        })
        return None if got is None else got[0]

    def _fetch_snapshot_legacy(self, pid, server, addr, meta) -> bool:
        got = self._fetch_chunked(addr, {
            "t": "fetch-snapshot-chunk",
            "partition": pid,
            "processed": meta.last_processed_position,
            "written": meta.last_written_position,
            "term": meta.term,
        })
        if got is None:
            return False
        payload, expect_crc = got
        # end-to-end integrity from the leader's serve cache, then a
        # full decode check: a fetched snapshot must be parseable by
        # the data-only codec before it can ever be offered to recovery
        if expect_crc is not None and zlib.crc32(payload) != int(expect_crc):
            return False
        try:
            stateser.decode_state(payload)
        except stateser.SnapshotFormatError:
            return False
        server.snapshots.storage.write(meta, payload)
        return True

    # -- topic subscriptions over the client API ----------------------------
    def _handle_topic_subscription(self, msg: dict, conn, result: ActorFuture) -> None:
        """reference: TopicSubscriptionManagementProcessor — SUBSCRIBE opens a
        per-subscriber push processor on the partition leader; ACKNOWLEDGE
        commands persist progress in the log so a reopen (same name) resumes
        where the consumer left off, on any future leader."""
        from zeebe_tpu.protocol.enums import RecordType
        from zeebe_tpu.protocol.intents import SubscriberIntent, SubscriptionIntent
        from zeebe_tpu.protocol.metadata import RecordMetadata
        from zeebe_tpu.protocol.records import (
            TopicSubscriberRecord,
            TopicSubscriptionRecord,
        )

        action = msg.get("action")
        partition_id = int(msg.get("partition", 0))
        server = self.partitions.get(partition_id)
        if server is None or not server.is_leader or server.engine is None:
            result.complete(msgpack.pack({"t": "error", "code": "NOT_LEADER"}))
            return
        name = str(msg.get("name", ""))
        subscriber_key = int(msg.get("subscriber_key", -1))
        if action == "open":
            start_position = int(msg.get("start_position", -1))
            force_start = bool(msg.get("force_start", False))
            acked = server.engine.topic_sub_acks.get(name)
            if acked is not None and not force_start:
                cursor = acked + 1
            elif start_position >= 0:
                cursor = start_position
            else:
                cursor = 0
            # durable audit record (+ ack reset on force_start)
            observe_append(server.raft.append([
                Record(
                    metadata=RecordMetadata(
                        record_type=RecordType.COMMAND,
                        value_type=TopicSubscriberRecord.VALUE_TYPE,
                        intent=int(SubscriberIntent.SUBSCRIBE),
                    ),
                    value=TopicSubscriberRecord(
                        name=name, start_position=start_position,
                        buffer_size=int(msg.get("credits", 32)),
                        force_start=force_start,
                    ),
                )
            ]), "topic-subscriber audit record", partition_id)
            if conn is not None:
                epoch = int(msg.get("epoch", -1))

                def push(record, _conn=conn, _key=subscriber_key,
                         _pid=partition_id, _epoch=epoch):
                    return _conn.push(
                        msgpack.pack(
                            {
                                "t": "pushed-record",
                                "partition": _pid,
                                "subscriber_key": _key,
                                "epoch": _epoch,
                                "frame": self._record_frame(record),
                            }
                        )
                    )

                logger.debug(
                    "broker %s: opening topic pusher %d (%r) on partition "
                    "%d at cursor %d", self.node_id, subscriber_key, name,
                    partition_id, cursor,
                )
                server.topic_pushers[subscriber_key] = {
                    "name": name,
                    "cursor": cursor,
                    "capacity": int(msg.get("credits", 32)),
                    "unacked": [],
                    "push": push,
                    "epoch": epoch,
                }
                conn.on_close(
                    lambda: self._drop_topic_subscription(partition_id, subscriber_key)
                )
                server.pump_topic_subscriptions()
        elif action == "ack":
            position = int(msg.get("position", -1))
            observe_append(server.raft.append([
                Record(
                    key=subscriber_key,
                    metadata=RecordMetadata(
                        record_type=RecordType.COMMAND,
                        value_type=TopicSubscriptionRecord.VALUE_TYPE,
                        intent=int(SubscriptionIntent.ACKNOWLEDGE),
                    ),
                    value=TopicSubscriptionRecord(name=name, ack_position=position),
                )
            ]), "topic-subscription ack", partition_id)
            pusher = server.topic_pushers.get(subscriber_key)
            if pusher is not None:
                pusher["unacked"] = [p for p in pusher["unacked"] if p > position]
                server.pump_topic_subscriptions()
        elif action == "close":
            self._drop_topic_subscription(partition_id, subscriber_key)
        elif action == "check":
            # subscription liveness probe: the client's monitor verifies
            # its pusher survived leadership churn (pushers are
            # leader-local and clear on uninstall — a same-address flap
            # would otherwise deafen the subscriber silently)
            pusher = server.topic_pushers.get(subscriber_key)
            result.complete(msgpack.pack({
                "t": "ok",
                "known": pusher is not None,
                "epoch": pusher.get("epoch", -1) if pusher else -1,
            }))
            return
        result.complete(msgpack.pack({"t": "ok"}))

    def _drop_topic_subscription(self, partition_id: int, subscriber_key: int) -> None:
        server = self.partitions.get(partition_id)
        if server is not None:
            if subscriber_key in server.topic_pushers:
                logger.debug(
                    "broker %s: dropping topic pusher %d on partition %d "
                    "(connection closed)", self.node_id, subscriber_key,
                    partition_id,
                )
            server.topic_pushers.pop(subscriber_key, None)

    # -- cluster self-assembly (reference bootstrap services) ---------------
    def _maybe_bootstrap(self) -> None:
        if self._closing:
            return
        self._maybe_create_default_topics()
        if self._bootstrap_started:
            return
        if 0 in self.partitions or self.topology.leader_address(0) is not None:
            self._bootstrap_started = True  # already bootstrapped or joined
            return
        alive = set(self.gossip.alive_members()) | {self.node_id}
        if len(alive) < max(1, self.cfg.cluster.bootstrap_expect):
            return
        # deterministic elector: the smallest node id drives the bootstrap
        if min(alive) != self.node_id:
            return
        # all chosen members must be reachable over the management plane
        members = sorted(alive)[: max(1, self.cfg.cluster.replication_factor)]
        if any(self._member_client_addr(n) is None for n in members):
            return
        self._bootstrap_started = True
        threading.Thread(
            target=self._bootstrap_system_partition, args=(members,),
            daemon=True, name="zb-bootstrap",
        ).start()

    def _bootstrap_system_partition(self, members) -> None:
        try:
            raft_addrs: Dict[str, list] = {}
            for node in members:
                addr = self._member_client_addr(node)
                rsp = msgpack.unpack(
                    self.client_transport.send_request(
                        addr,
                        msgpack.pack({"t": "create-partition", "partition": 0}),
                        timeout_ms=5000,
                    ).join(6)
                )
                if rsp.get("t") == "ok":
                    raft_addrs[node] = list(rsp.get("raft", ["", 0]))
            for node in raft_addrs:
                addr = self._member_client_addr(node)
                peers = {n: a for n, a in raft_addrs.items() if n != node}
                self.client_transport.send_request(
                    addr,
                    msgpack.pack(
                        {"t": "bootstrap-partition", "partition": 0, "members": peers}
                    ),
                    timeout_ms=5000,
                ).join(6)
        except Exception:  # noqa: BLE001 - the periodic check retries
            self._bootstrap_started = False

    def _maybe_create_default_topics(self) -> None:
        """Configured [[topics]] created once the system partition is led by
        this node (duplicate CREATEs are rejected — idempotent)."""
        server = self.partitions.get(0)
        if not self.cfg.topics or server is None or not server.is_leader:
            return
        if self._default_topics_created:
            return
        self._default_topics_created = True
        from zeebe_tpu.protocol.intents import TopicIntent
        from zeebe_tpu.protocol.metadata import RecordMetadata
        from zeebe_tpu.protocol.records import TopicRecord
        from zeebe_tpu.protocol.enums import RecordType as RT

        for topic in self.cfg.topics:
            observe_append(server.raft.append([
                Record(
                    metadata=RecordMetadata(
                        record_type=RT.COMMAND,
                        value_type=TopicRecord.VALUE_TYPE,
                        intent=int(TopicIntent.CREATE),
                    ),
                    value=TopicRecord(
                        name=topic.name,
                        partitions=topic.partitions,
                        replication_factor=topic.replication_factor,
                    ),
                )
            ]), "default-topic CREATE", 0)

    # -- topic orchestration (reference TopicCreationService + NodeSelector
    # + CreatePartitionRequest → ManagementApiRequestHandler) ---------------
    def start_topic_orchestration(self, creating_record: Record) -> None:
        """On the system-partition leader: bring the CREATING topic's
        partitions up on the least-loaded members, then confirm with a
        CREATE_COMPLETE command (the engine answers the waiting client)."""
        record = creating_record
        threading.Thread(
            target=self._orchestrate_topic, args=(record,), daemon=True,
            name=f"zb-topic-orchestrator-{record.value.name}",
        ).start()

    def _node_loads(self) -> Dict[str, int]:
        loads: Dict[str, int] = {self.node_id: 0}
        for node in list(self.topology.members):
            loads.setdefault(node, 0)
        with self.topology._lock:
            for _pid, entry in self.topology.partition_leaders.items():
                loads[entry[0]] = loads.get(entry[0], 0) + 1
        return loads

    def _member_client_addr(self, node: str) -> Optional[RemoteAddress]:
        if node == self.node_id:
            return self.client_address
        entry = self.topology.members.get(node)
        if not entry or not entry[0]:
            return None
        return RemoteAddress(entry[0], int(entry[1]))

    def _orchestrate_topic(self, record: Record) -> None:
        import time as _time

        value = record.value
        replication = max(1, int(value.replication_factor))
        deadline = _time.monotonic() + 60.0
        loads = self._node_loads()
        try:
            for pid in list(value.partition_ids):
                # NodeSelector: fewest-led-partitions first, stable order
                candidates = sorted(loads, key=lambda n: (loads[n], n))
                chosen = candidates[: min(replication, len(candidates))]
                raft_addrs: Dict[str, list] = {}
                for node in chosen:
                    addr = self._member_client_addr(node)
                    if addr is None:
                        continue
                    rsp = msgpack.unpack(
                        self.client_transport.send_request(
                            addr,
                            msgpack.pack({"t": "create-partition", "partition": pid}),
                            timeout_ms=5000,
                        ).join(6)
                    )
                    if rsp.get("t") == "ok":
                        raft_addrs[node] = list(rsp.get("raft", ["", 0]))
                for node in list(raft_addrs):
                    addr = self._member_client_addr(node)
                    peers = {n: a for n, a in raft_addrs.items() if n != node}
                    self.client_transport.send_request(
                        addr,
                        msgpack.pack(
                            {
                                "t": "bootstrap-partition",
                                "partition": pid,
                                "members": peers,
                            }
                        ),
                        timeout_ms=5000,
                    ).join(6)
                    loads[node] = loads.get(node, 0) + 1

            # leaders elected for every partition? then confirm
            def all_led():
                return all(
                    self.topology.leader_address(pid) is not None
                    for pid in value.partition_ids
                )

            while _time.monotonic() < deadline and not self._closing:
                if all_led():
                    break
                _time.sleep(0.05)
            if not all_led():
                return  # recovery re-triggers orchestration for CREATING topics
            server = self.partitions.get(0)
            if server is None or not server.is_leader:
                return
            from zeebe_tpu.protocol.intents import TopicIntent
            from zeebe_tpu.protocol.metadata import RecordMetadata
            from zeebe_tpu.protocol.records import TopicRecord
            from zeebe_tpu.protocol.enums import RecordType as RT

            observe_append(server.raft.append([
                Record(
                    key=record.key,
                    metadata=RecordMetadata(
                        record_type=RT.COMMAND,
                        value_type=TopicRecord.VALUE_TYPE,
                        intent=int(TopicIntent.CREATE_COMPLETE),
                        request_id=record.metadata.request_id,
                        request_stream_id=record.metadata.request_stream_id,
                    ),
                    value=TopicRecord(name=value.name),
                )
            ]), "topic CREATE_COMPLETE", 0)
        except Exception:  # noqa: BLE001 - orchestration retried on recovery
            import traceback

            traceback.print_exc()

    def _handle_create_partition(self, msg: dict):
        partition_id = int(msg.get("partition", 0))
        result = ActorFuture()
        self.open_partition(partition_id).on_complete(
            lambda f: result.complete(
                msgpack.pack(
                    {"t": "ok", "raft": [f._value.host, f._value.port]}
                    if f._exception is None
                    else {"t": "error", "code": "CREATE_FAILED"}
                )
            )
        )
        return result

    def _handle_bootstrap_partition(self, msg: dict):
        partition_id = int(msg.get("partition", 0))
        members = {
            str(node): RemoteAddress(a[0], int(a[1]))
            for node, a in dict(msg.get("members", {})).items()
        }
        self.bootstrap_partition(partition_id, members)
        return msgpack.pack({"t": "ok"})

    # -- workflow repository queries (reference WorkflowRepositoryService
    # list-workflows / get-workflow control messages) ------------------------
    def _handle_list_workflows(self, msg: dict) -> bytes:
        process_id = msg.get("process_id") or ""
        if process_id:
            workflows = list(self.repository.versions.get(process_id, []))
        else:
            workflows = list(self.repository.by_key.values())
        return msgpack.pack(
            {
                "t": "ok",
                "workflows": [
                    {"id": wf.id, "version": wf.version, "key": wf.key}
                    for wf in sorted(workflows, key=lambda w: w.key)
                ],
            }
        )

    def _handle_get_workflow(self, msg: dict) -> bytes:
        workflow_key = int(msg.get("workflow_key", -1))
        process_id = msg.get("process_id") or ""
        version = int(msg.get("version", -1))
        wf = None
        if workflow_key >= 0:
            wf = self.repository.by_key.get(workflow_key)
        elif process_id and version >= 0:
            wf = self.repository.by_id_and_version(process_id, version)
        elif process_id:
            wf = self.repository.latest(process_id)
        if wf is None:
            return msgpack.pack({"t": "error", "code": "NOT_FOUND"})
        return msgpack.pack(
            {
                "t": "ok",
                "id": wf.id,
                "version": wf.version,
                "key": wf.key,
                "resource": wf.source_resource,
                "resource_type": wf.source_type,
            }
        )

    # -- deployment distribution (reference FetchWorkflowRequest served by
    # the system partition's WorkflowRepositoryService; WorkflowCache on the
    # requesting side) ------------------------------------------------------
    def _handle_fetch_workflow(self, msg: dict) -> bytes:
        process_id = msg.get("process_id") or ""
        workflow_key = int(msg.get("workflow_key", -1))
        workflows = []
        if workflow_key >= 0:
            wf = self.repository.by_key.get(workflow_key)
            workflows = [wf] if wf else []
        elif process_id:
            workflows = list(self.repository.versions.get(process_id, []))
        return msgpack.pack(
            {
                "t": "fetch-workflow-rsp",
                "workflows": [
                    {
                        "id": wf.id,
                        "version": wf.version,
                        "key": wf.key,
                        "resource": wf.source_resource,
                        "resource_type": wf.source_type,
                    }
                    for wf in workflows
                ],
            }
        )

    def fetch_workflow(
        self, process_id: str, workflow_key: int, on_done: Callable[[], None]
    ) -> None:
        """Fetch a workflow from the system partition leader and register it
        locally; ``on_done`` fires (on the broker actor) regardless of
        outcome — the caller re-processes and lets the engine reject if the
        workflow truly does not exist."""
        addr = self.topology.leader_address(0)
        if addr is None:
            self.actor.run_delayed(100, on_done)
            return
        request = msgpack.pack(
            {
                "t": "fetch-workflow",
                "process_id": process_id,
                "workflow_key": workflow_key,
            }
        )
        future = self.client_transport.send_request(addr, request, timeout_ms=2000)

        def on_response(f: ActorFuture):
            def apply():
                if f._exception is None:
                    try:
                        self._register_fetched_workflows(msgpack.unpack(f._value))
                    except ValueError:
                        pass
                on_done()

            self.actor.run(apply)

        future.on_complete(on_response)

    def _register_fetched_workflows(self, msg: dict) -> None:
        from zeebe_tpu.models.bpmn.xml import read_model
        from zeebe_tpu.models.bpmn.yaml_front import read_yaml_workflow
        from zeebe_tpu.models.transform.transformer import transform_model

        for entry in msg.get("workflows", []):
            if int(entry.get("key", -1)) in self.repository.by_key:
                continue
            data = bytes(entry.get("resource", b""))
            if not data:
                continue
            try:
                if entry.get("resource_type") == "YAML_WORKFLOW":
                    model = read_yaml_workflow(data.decode("utf-8"))
                else:
                    model = read_model(data, strict=False)  # accepted at deploy
                for wf in transform_model(model):
                    if wf.id != entry.get("id"):
                        continue
                    wf.version = int(entry.get("version", 1))
                    wf.key = int(entry.get("key", -1))
                    wf.source_resource = data
                    wf.source_type = entry.get("resource_type", "BPMN_XML")
                    self.repository.merge([wf])
            except Exception:  # noqa: BLE001 - a bad resource only skips
                continue

    def _handle_topology_request(self) -> bytes:
        with self.topology._lock:
            entries = dict(self.topology.partition_leaders)
        leaders = {
            str(pid): {
                "node": entry[0],
                "addr": entry[1],
                "term": entry[3] if len(entry) > 3 else entry[2],
            }
            for pid, entry in entries.items()
        }
        return msgpack.pack({"t": "topology-rsp", "leaders": leaders})

    @staticmethod
    def _record_frame(record) -> bytes:
        """Wire frame for a response/push record, reusing the frame the
        log append already encoded for it (``LogStream.append`` caches the
        frame on request-relevant records) instead of paying a second
        full encode + crc per response; columns → frame happens ONCE per
        record."""
        cached = getattr(record, "_frame", None)
        if cached is not None and cached[0] == record.position:
            return cached[1]
        return codec.encode_record(record)

    @classmethod
    def _command_responder(cls, result: ActorFuture):
        def on_response(f: ActorFuture):
            if isinstance(f._exception, _AppendFailed):
                result.complete(
                    msgpack.pack({"t": "error", "code": "NOT_LEADER", "leader": ""})
                )
            elif f._exception is not None:
                result.complete(
                    msgpack.pack({"t": "error", "code": "INTERNAL", "message": str(f._exception)})
                )
            else:
                result.complete(
                    msgpack.pack({"t": "command-rsp", "frame": cls._record_frame(f._value)})
                )

        return on_response

    def _handle_command(self, msg: dict, result: ActorFuture) -> None:
        partition_id = int(msg.get("partition", 0))
        span = msg.pop("_trace", None)

        def finish_span(reason: str) -> None:
            # early lifecycle end (not leader / duplicate / malformed):
            # release the span from the live budget with the reason
            tracer = tracing.TRACER
            if span is not None and tracer is not None:
                tracer.finish(span, tracing.RESPONSE, verdict=reason)

        server = self.partitions.get(partition_id)
        if server is None or not server.is_leader:
            leader = self.topology.leader_node(partition_id)
            finish_span("NOT_LEADER")
            result.complete(
                msgpack.pack(
                    {"t": "error", "code": "NOT_LEADER", "leader": leader or ""}
                )
            )
            return
        # client retries re-send a command with the SAME cid after a lost
        # or slow response (cluster_client.send_command): answer duplicates
        # from the original append's response future instead of appending
        # twice — a retried CREATE must not create two instances. (Scope:
        # per-broker; a retry that lands on a NEW leader after failover is
        # at-least-once, as in the reference.)
        cid = str(msg.get("cid") or "")
        if cid:
            with self._request_lock:
                existing = self._cmd_dedup.get(cid)
            if existing is not None:
                finish_span("DUPLICATE")
                existing.on_complete(self._command_responder(result))
                return
        try:
            record, _ = codec.decode_record(bytes(msg.get("frame", b"")))
        except ValueError:
            finish_span("MALFORMED")
            result.complete(msgpack.pack({"t": "error", "code": "MALFORMED"}))
            return
        with self._request_lock:
            request_id = self._next_request_id
            self._next_request_id += 1
        record.metadata.request_id = request_id
        record.position = -1  # assigned on append
        record.timestamp = -1
        if span is not None:
            tracer = tracing.TRACER
            if tracer is not None:
                # from here the span is findable by request id (raft's
                # group commit binds the log position at fsync time)
                tracer.bind_request(span, request_id, partition_id)
                tracer.stamp(span, tracing.RAFT_QUEUE)

        response_future = ActorFuture()
        self._pending_responses[request_id] = response_future
        if cid:
            with self._request_lock:
                self._cmd_dedup[cid] = response_future
                while len(self._cmd_dedup) > 4096:
                    self._cmd_dedup.pop(next(iter(self._cmd_dedup)))

        response_future.on_complete(self._command_responder(result))

        append = server.raft.append([record])

        def on_append(f: ActorFuture):
            if f._exception is not None:
                self._pending_responses.pop(request_id, None)
                if cid:
                    with self._request_lock:
                        self._cmd_dedup.pop(cid, None)
                tracer = tracing.TRACER
                if tracer is not None and tracer.tracking_requests():
                    # the append failed before a position was bound: this
                    # is the span's terminal stage — nothing downstream
                    # can ever reach it (the client's retry arrives as a
                    # fresh sampled command), and an unfinishable span
                    # would pin every per-record stamp path hot
                    tracer.stamp_request(
                        request_id, "append_failed", final=True,
                        error=str(f._exception),
                    )
                # complete the SHARED future, not just this request's
                # result: retries deduped onto it must also learn
                # NOT_LEADER instead of hanging until their timeout
                response_future.complete_exceptionally(
                    _AppendFailed(str(f._exception))
                )

        append.on_complete(on_append)

    def send_client_response(self, response: Record, server) -> None:
        request_id = response.metadata.request_id
        if request_id < 0:
            return
        future = self._pending_responses.pop(request_id, None)
        if future is not None:
            tracer = tracing.TRACER
            if tracer is not None and tracer.tracking_requests():
                # the shared no-ack-plane rule (tracing.no_ack_plane):
                # no exporter plane on the responding partition, or every
                # exporter broke at open = no ack will ever finish the
                # span, so the response is its last stage
                tracer.stamp_request(
                    request_id, tracing.RESPONSE,
                    final=tracing.no_ack_plane(server),
                )
            future.complete(response)

    # -- job subscriptions over the client API ------------------------------
    def _handle_job_subscription(self, msg: dict, conn, result: ActorFuture) -> None:
        """reference: AddJobSubscriptionHandler /
        IncreaseJobSubscriptionCreditsHandler control messages; ACTIVATED
        records are pushed down the subscriber's own connection
        (SubscribedRecordWriter)."""
        action = msg.get("action")
        partition_id = int(msg.get("partition", 0))
        server = self.partitions.get(partition_id)
        if server is None or not server.is_leader or server.engine is None:
            result.complete(msgpack.pack({"t": "error", "code": "NOT_LEADER"}))
            return
        if action == "add":
            subscriber_key = int(msg["subscriber_key"])
            if conn is not None:
                self.on_push(
                    subscriber_key,
                    lambda pid, rec: conn.push(
                        msgpack.pack(
                            {
                                "t": "pushed-record",
                                "partition": pid,
                                "subscriber_key": subscriber_key,
                                "frame": self._record_frame(rec),
                            }
                        )
                    ),
                )
                # tear the subscription down when the worker's connection
                # dies, else activated jobs black-hole into dead credits
                # (reference: transport channel close listeners)
                conn.on_close(
                    lambda: self._drop_job_subscription(partition_id, subscriber_key)
                )
            backlog = server.engine.add_job_subscription(
                JobSubscription(
                    subscriber_key=subscriber_key,
                    job_type=msg["job_type"],
                    worker=msg.get("worker", "worker"),
                    timeout=int(msg.get("timeout", 300_000)),
                    credits=int(msg.get("credits", 32)),
                )
            )
            if backlog:
                observe_append(
                    server.raft.append(backlog),
                    "job-subscription backlog", partition_id,
                )
        elif action == "credits":
            server.engine.increase_job_credits(
                int(msg["subscriber_key"]), int(msg.get("credits", 1))
            )
            # returned credits must revisit the backlog (jobs that became
            # activatable while every subscription was dry) — host side
            # immediately; device side via the tick's PROBE_JOB_BACKLOG
            backlog = server.engine.backlog_activations()
            if backlog:
                observe_append(
                    server.raft.append(backlog),
                    "returned-credit backlog", partition_id,
                )
        elif action == "remove":
            self._drop_job_subscription(partition_id, int(msg["subscriber_key"]))
        result.complete(msgpack.pack({"t": "ok"}))

    def _drop_job_subscription(self, partition_id: int, subscriber_key: int) -> None:
        self._push_listeners.pop(subscriber_key, None)
        server = self.partitions.get(partition_id)
        if server is not None and server.engine is not None:
            server.engine.remove_job_subscription(subscriber_key)

    def on_push(self, subscriber_key: int, listener: Callable[[int, Record], None]) -> None:
        self._push_listeners[subscriber_key] = listener

    def push_to_subscriber(self, subscriber_key: int, partition_id: int, record: Record) -> None:
        listener = self._push_listeners.get(subscriber_key)
        if listener is not None:
            listener(partition_id, record)

    # -- cross-partition subscription commands ------------------------------
    def send_subscription_command(self, target_partition: int, record: Record) -> None:
        """Route to the target partition's leader over the subscription
        transport (reference SubscriptionCommandSender hash routing; the
        partition choice already happened in the engine). Remote sends are
        acked and retried until a leader accepts them (the reference's
        subscription command resend loop) — topology may lag an election."""
        server = self.partitions.get(target_partition)
        if server is not None and server.is_leader:
            # local fast path — but raft.append reports "not leader"
            # through the FUTURE, never by raising here. A stale
            # is_leader (step-down racing this send) used to lose the
            # command forever with the retry loop never started: a
            # cross-partition subscription OPEN vanishing means the
            # waiting instance never correlates
            future = server.raft.append([record])
            future.on_complete(lambda f: (
                self._retry_subscription_send(target_partition, record)
                if getattr(f, "_exception", None) is not None else None
            ))
            return
        self._retry_subscription_send(target_partition, record)

    def _retry_subscription_send(self, target_partition: int, record: Record) -> None:
        request = msgpack.pack(
            {
                "t": "subscription-cmd",
                "partition": target_partition,
                "frame": codec.encode_record(record),
            }
        )

        def retry_loop():
            import time as _time

            deadline = _time.monotonic() + 30.0
            while _time.monotonic() < deadline and not self._closing:
                # leadership may have landed here meanwhile; join the
                # append so a deposed leader's failure keeps retrying
                # instead of silently dropping the command
                local = self.partitions.get(target_partition)
                if local is not None and local.is_leader:
                    future = local.raft.append([record])
                    try:
                        future.join(3)
                        return
                    except TimeoutError:
                        # acked-means-committed: a slow quorum can hold
                        # the future past the join window while the
                        # record already sits in the leader's log —
                        # re-appending here would duplicate the command
                        # every 3s. Hand liveness to the future instead:
                        # a later failure (truncate/step-down) restarts
                        # the retry from its callback.
                        future.on_complete(lambda f: (
                            self._retry_subscription_send(
                                target_partition, record
                            )
                            if getattr(f, "_exception", None) is not None
                            and not self._closing
                            else None
                        ))
                        return
                    except Exception:  # noqa: BLE001 - deposed mid-append
                        pass
                addr = self.topology.leader_subscription_address(target_partition)
                if addr is not None:
                    try:
                        payload = self.client_transport.send_request(
                            addr, request, timeout_ms=2000
                        ).join(3)
                        if msgpack.unpack(payload).get("t") == "ok":
                            return
                    except Exception:  # noqa: BLE001 - retry through outages
                        pass
                _time.sleep(0.1)
            count_event(
                "subscription_send_expired",
                "Cross-partition subscription commands dropped after the "
                "retry deadline (no leader accepted them)",
            )
            logger.error(
                "cross-partition subscription command to partition %d "
                "dropped after 30s of retries", target_partition,
            )

        threading.Thread(target=retry_loop, daemon=True).start()

    def _on_subscription_request(self, payload: bytes, conn=None):
        """Acked subscription command (REQUEST frame): append on the leader,
        tell the sender to retry elsewhere otherwise."""
        try:
            msg = msgpack.unpack(payload)
        except Exception:  # noqa: BLE001
            return msgpack.pack({"t": "error", "code": "BAD_REQUEST"})
        if msg.get("t") != "subscription-cmd":
            return msgpack.pack({"t": "error", "code": "BAD_REQUEST"})
        result = ActorFuture()

        def do():
            partition_id = int(msg.get("partition", 0))
            server = self.partitions.get(partition_id)
            if server is None or not server.is_leader:
                result.complete(msgpack.pack({"t": "error", "code": "NOT_LEADER"}))
                return
            try:
                record, _ = codec.decode_record(bytes(msg.get("frame", b"")))
            except ValueError:
                result.complete(msgpack.pack({"t": "error", "code": "BAD_REQUEST"}))
                return
            record.position = -1
            record.timestamp = -1
            observe_append(
                server.raft.append([record]),
                "subscription-cmd record", partition_id,
            )
            result.complete(msgpack.pack({"t": "ok"}))

        self.actor.run(do)
        return result

    def _on_subscription_message(self, payload: bytes) -> None:
        try:
            msg = msgpack.unpack(payload)
        except Exception:  # noqa: BLE001
            return
        if msg.get("t") != "subscription-cmd":
            return

        def do():
            partition_id = int(msg.get("partition", 0))
            server = self.partitions.get(partition_id)
            if server is None or not server.is_leader:
                return
            try:
                record, _ = codec.decode_record(bytes(msg.get("frame", b"")))
            except ValueError:
                return
            record.position = -1
            record.timestamp = -1
            observe_append(
                server.raft.append([record]),
                "subscription message record", partition_id,
            )

        self.actor.run(do)

    # -- client command entry used by the in-process gateway ----------------
    def subscription_address(self) -> RemoteAddress:
        return self.subscription_server.address

    # -- periodic work -------------------------------------------------------
    def snapshot_all(self) -> None:
        """Checkpoint every led partition and WAIT for the commits (tests
        and admin calls expect the snapshot durable on return; the periodic
        tick uses _snapshot_all_on_actor directly and does not wait).
        Safe from any thread: the CAPTURE runs on the broker actor,
        serialized with record processing — a capture reads the same
        engine state processing mutates, and the device engine
        additionally DONATES its buffers to XLA each step (a concurrent
        read would hit deleted arrays). The commit (hash/compress/fsync)
        runs on worker threads off the serving path."""
        try:
            threads = self.actor.call(self._snapshot_all_on_actor).join(60)
        except TimeoutError:
            # a silently-skipped checkpoint turns into an unexplainable
            # missing-snapshot failure much later (round-4 flake hunt);
            # fail where the cause is
            raise TimeoutError(
                "snapshot_all: broker actor did not run the checkpoint "
                "within 60s (actor wedged or overloaded)"
            )
        for thread in threads:
            thread.join(60)
            if thread.is_alive():
                raise TimeoutError(
                    "snapshot_all: a snapshot commit did not finish within "
                    "60s (storage wedged?)"
                )

    def _snapshot_all_on_actor(self) -> List[threading.Thread]:
        """One capture per led partition, failures isolated per partition:
        a raising take on one partition must not starve the rest of their
        checkpoints (chaos break_fsync drives this path)."""
        threads: List[threading.Thread] = []
        for server in self.partitions.values():
            try:
                thread = server.snapshot()
            except Exception as e:  # noqa: BLE001 - per-partition isolation
                count_event(
                    "snapshot_take_failures",
                    "Snapshot takes that raised (capture or commit)",
                )
                logger.error(
                    "snapshot failed on partition %d: %r",
                    server.partition_id, e,
                )
                continue
            if thread is None:
                # a periodic-tick take may already be committing: hand its
                # thread to snapshot_all so the durable-on-return contract
                # holds (the in-flight take is at most one tick old)
                thread = server._snapshot_thread
            if thread is not None and thread.is_alive():
                threads.append(thread)
        return threads

    def _tick_engines(self) -> None:
        """Timer/TTL sweeps on leader partitions (reference periodic actor
        jobs: JobTimeOutStreamProcessor, MessageTimeToLiveChecker). The
        per-partition probe/sweep logic lives in ``PartitionServer.tick``
        (see its docstring for the async-probe rationale); in shared-wave
        mode the scheduler drives it through the registered feeds, so the
        sweep commands enter the same shared waves as client traffic."""
        self._check_span_commit_stalls()
        if self.wave_scheduler is not None:
            self.wave_scheduler.tick()
            return
        for server in self.partitions.values():
            server.tick()

    def _check_span_commit_stalls(self) -> None:
        """Commit-latency watchdog over the SAMPLED spans (the raft actor
        has its own, sampling-independent one): a traced command appended
        but uncommitted past the threshold is logged once with the
        relevant flight-recorder slice and counted process-globally."""
        tracer = tracing.TRACER
        if tracer is None or not tracer.by_position:
            return
        # claim only partitions this broker LEADS: the tracer is process-
        # global, and an in-process peer's tick must not report (and
        # mislabel) another leader's stall
        led = {
            pid for pid, server in self.partitions.items()
            if server.is_leader
        }
        if not led:
            return
        stalled = tracer.check_commit_stalls(led)
        if not stalled:
            return
        count_event(
            "serving_commit_stalls",
            "Sampled commands appended but uncommitted past the "
            "commit-latency watchdog threshold",
            delta=len(stalled),
        )
        for span in stalled:
            record_event(
                "stall", "sampled command commit stall",
                node=self.node_id, partition=span.partition,
                position=span.position, request_id=span.request_id,
            )
        # one log line (and ONE flight slice) per sweep — a wedged
        # partition can cross the threshold with a whole budget of spans
        # at once, and 256 copies of the same 25-line slice would bury
        # the forensics it exists to surface
        first = stalled[0]
        logger.warning(
            "broker %s: %d sampled command(s) (first: partition %d "
            "position %d) appended but uncommitted for >%dms; recent "
            "flight-recorder events:\n%s",
            self.node_id, len(stalled), first.partition, first.position,
            tracer.commit_stall_ms, FLIGHT.format_slice(last=25),
        )
