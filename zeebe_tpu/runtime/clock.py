"""Actor clock equivalents.

Reference parity: ``util/.../sched/clock/ActorClock.java`` and
``ControlledActorClock.java`` (tests pin and advance time deterministically).
"""

from __future__ import annotations

import time


class SystemClock:
    def __call__(self) -> int:
        return int(time.time() * 1000)

    def millis(self) -> int:
        return self()


class ControlledClock:
    """Deterministic clock for tests and replay (reference ControlledActorClock)."""

    def __init__(self, start_ms: int = 0):
        self.current = start_ms

    def __call__(self) -> int:
        return self.current

    def millis(self) -> int:
        return self.current

    def set(self, ms: int) -> None:
        self.current = ms

    def advance(self, ms: int) -> None:
        self.current += ms
