"""Engine selection: config → partition engine factory.

Reference parity: the reference has a single stream-processor engine,
installed unconditionally per leader partition
(broker-core/.../clustering/base/partitions/PartitionInstallService.java:106-291).
Here the broker chooses between the batched TPU device kernel (the
flagship) and the host oracle interpreter per the ``[engine]`` config
section; both serve the same record contract.
"""

from __future__ import annotations

from typing import Callable, Optional

from zeebe_tpu.runtime.config import BrokerCfg


def engine_factory_from_config(
    cfg: BrokerCfg,
) -> Optional[Callable]:
    """Build the ``engine_factory`` for :class:`ClusterBroker` /
    :class:`Broker` from ``cfg.engine``. Returns ``None`` for the host
    oracle (the brokers' built-in default)."""
    etype = cfg.engine.type.lower()
    if etype == "host":
        return None
    if etype == "tpu":
        capacity = int(cfg.engine.capacity)
        num_vars = int(cfg.engine.num_vars)
        sub_capacity = int(cfg.engine.sub_capacity)

        def factory(partition_id: int, broker):
            from zeebe_tpu.tpu import TpuPartitionEngine

            if getattr(cfg.engine, "pallas_selfcheck", True):
                # autotune FIRST so the selfcheck validates the dispatch
                # the partition will actually serve with (per-build
                # pallas/XLA winners; cache-hit after the first boot on a
                # given build), then the on-chip parity smoke: a broken
                # Mosaic lowering must refuse to serve, not corrupt
                # partition state (round-3 advisor). Memoized; no-op
                # off-TPU.
                from zeebe_tpu.tpu import autotune, pallas_ops

                autotune.ensure_autotuned()
                pallas_ops.selfcheck()
            # mesh placement: the broker's DevicePlan assigned this leader
            # partition a device at install time — the engine's state
            # commits there and its waves compute there, concurrently with
            # other partitions' waves on other devices
            device = None
            device_index = -1
            shard_devices = None
            device_indices = None
            state_shards = 1
            # sharded-state span first ([mesh] shardedPartitions > 1):
            # the partition's tables block-shard over the span instead of
            # committing to one device
            spanned = getattr(broker, "planned_span", None)
            if spanned is not None:
                shard_devs, shard_idx = spanned(partition_id)
                if shard_devs:
                    shard_devices = shard_devs
                    device_indices = shard_idx
                    device_index = shard_idx[0]
                    state_shards = len(shard_devs)
            if state_shards == 1:
                planned = getattr(broker, "planned_device", None)
                if planned is not None:
                    device, device_index = planned(partition_id)
            engine = TpuPartitionEngine(
                partition_id,
                broker.cfg.cluster.partitions,
                repository=broker.repository,
                clock=broker.clock,
                capacity=capacity,
                num_vars=num_vars,
                sub_capacity=sub_capacity,
                device=device,
                device_index=device_index,
                state_shards=state_shards,
                shard_devices=shard_devices,
                device_indices=device_indices,
                routing=getattr(cfg.mesh, "routing", "gathered"),
            )
            import jax as _jax

            if _jax.default_backend() == "tpu":
                # pay the kernel compiles at install time, not on the
                # first served batch (which blocks the broker actor and
                # times out every client request) — off-TPU compiles are
                # fast and tests deploy immediately, so skip there
                engine.warm()
            return engine

        return factory
    raise ValueError(
        f"unknown engine type {cfg.engine.type!r} (expected 'host' or 'tpu')"
    )
