"""Broker runtime: partitions, processing loop, config, clock.

Reference parity: ``broker-core/.../Broker.java`` bootstrap +
``clustering/base/partitions/PartitionInstallService`` + the
``StreamProcessorController`` processing loop.
"""

from zeebe_tpu.runtime.clock import ControlledClock, SystemClock
from zeebe_tpu.runtime.broker import Broker, Partition

__all__ = ["Broker", "Partition", "ControlledClock", "SystemClock"]
