"""Actor scheduler: the runtime's concurrency model.

Reference parity: ``util/src/main/java/io/zeebe/util/sched/`` — green-thread
cooperative scheduling (``ActorScheduler.java:34``), actors as single-writer
state machines whose jobs never run concurrently, the ``ActorControl`` API
(run / submit / run_delayed / run_at_fixed_rate / on_condition / futures,
``ActorControl.java:62-478``), a CPU-bound work-stealing thread group + an
IO-bound group (``WorkStealingGroup.java:22``), a pluggable clock
(``clock/ActorClock.java``) and a controlled scheduler for deterministic
tests (``testing/ControlledActorSchedulerRule``).

TPU-native re-design, not a port: the hot path of this framework is the
batched device kernel, so the scheduler's job is the *control plane* —
periodic snapshotting, timer/TTL sweeps, metrics flush, transport polling,
raft heartbeats. Python threads suffice for that (the GIL is irrelevant to
control-plane rates); the single-writer actor contract is what matters and
is preserved: an actor's jobs are serialized through its own mailbox, so
actor state needs no locks.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, List, Optional


class ActorFuture:
    """Completion future usable from actor callbacks.

    Reference: ``util/.../sched/future/CompletableActorFuture.java``.
    """

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._exception: Optional[BaseException] = None
        self._callbacks: List[Callable[["ActorFuture"], None]] = []
        self._lock = threading.Lock()

    def complete(self, value: Any = None) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._value = value
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb(self)

    def complete_exceptionally(self, exc: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._exception = exc
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for cb in callbacks:
            cb(self)

    def is_done(self) -> bool:
        return self._event.is_set()

    def join(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError("future not completed in time")
        if self._exception is not None:
            raise self._exception
        return self._value

    def on_complete(self, callback: Callable[["ActorFuture"], None]) -> None:
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(callback)
                return
        callback(self)


class _Timer:
    __slots__ = ("deadline", "seq", "job", "interval", "cancelled")

    def __init__(self, deadline: float, seq: int, job: "_Job", interval: Optional[float]):
        self.deadline = deadline
        self.seq = seq
        self.job = job
        self.interval = interval
        self.cancelled = False

    def __lt__(self, other):
        return (self.deadline, self.seq) < (other.deadline, other.seq)

    def cancel(self) -> None:
        self.cancelled = True


class _Job:
    __slots__ = ("actor", "fn")

    def __init__(self, actor: "Actor", fn: Callable[[], None]):
        self.actor = actor
        self.fn = fn


class _Condition:
    """Reference: ``ActorControl.onCondition`` — a named wakeup that
    schedules its job each time it is signalled."""

    __slots__ = ("name", "job", "scheduler")

    def __init__(self, name: str, job: _Job, scheduler: "ActorScheduler"):
        self.name = name
        self.job = job
        self.scheduler = scheduler

    def signal(self) -> None:
        self.scheduler._enqueue(self.job)


class ActorControl:
    """The API an actor uses to schedule its own work (single-writer:
    everything lands in this actor's serialized mailbox)."""

    def __init__(self, actor: "Actor", scheduler: "ActorScheduler"):
        self._actor = actor
        self._scheduler = scheduler

    def run(self, fn: Callable[[], None]) -> None:
        """Enqueue a job on this actor (reference actor.run/submit)."""
        self._scheduler._enqueue(_Job(self._actor, fn))

    submit = run

    def run_delayed(self, delay_ms: int, fn: Callable[[], None]) -> _Timer:
        return self._scheduler._schedule_timer(
            self._actor, delay_ms, fn, interval_ms=None
        )

    def run_at_fixed_rate(self, period_ms: int, fn: Callable[[], None]) -> _Timer:
        return self._scheduler._schedule_timer(
            self._actor, period_ms, fn, interval_ms=period_ms
        )

    def on_condition(self, name: str, fn: Callable[[], None]) -> _Condition:
        return _Condition(name, _Job(self._actor, fn), self._scheduler)

    def call(self, fn: Callable[[], Any]) -> ActorFuture:
        """Run ``fn`` on this actor; complete a future with its result
        (reference ActorControl.call — the cross-actor ask pattern)."""
        future = ActorFuture()

        def run():
            try:
                future.complete(fn())
            except BaseException as e:  # noqa: BLE001 - forwarded to future
                future.complete_exceptionally(e)

        self.run(run)
        return future

    def run_on_completion(self, future: ActorFuture, fn: Callable[[ActorFuture], None]) -> None:
        """Resume on this actor when ``future`` completes (the actor-safe
        continuation; reference actor.runOnCompletion)."""
        future.on_complete(lambda f: self.run(lambda: fn(f)))


class Actor:
    """Base class: subclass and override ``on_actor_started`` /
    ``on_actor_closing``. All callbacks run serialized (single-writer)."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or type(self).__name__
        self.actor: ActorControl = None  # injected at submit
        self._mailbox: deque = deque()
        self._running = False  # a worker is draining this actor's mailbox
        self._closed = False
        self._failure_count = 0  # jobs that raised (see ActorScheduler._drain)
        self._mailbox_lock = threading.Lock()

    def on_actor_started(self) -> None:  # noqa: B027 - optional hook
        pass

    def on_actor_closing(self) -> None:  # noqa: B027 - optional hook
        pass


class ActorScheduler:
    """Thread-group scheduler: ``cpu_threads`` workers drain actor mailboxes
    from a shared run queue (work sharing — contention profile of Python
    makes stealing pointless), ``io_threads`` drain io-submitted actors, and
    one timer thread expires delays/fixed-rates.

    Reference: ``ActorScheduler.newActorScheduler().build(); start()``
    (SystemContext.java:128-144 uses 2 cpu + 2 io by default).
    """

    def __init__(self, cpu_threads: int = 2, io_threads: int = 2, clock=None):
        self._clock = clock  # None → wall clock; callable → millis
        self._runq: deque = deque()
        self._io_runq: deque = deque()
        self._cv = threading.Condition()
        self._timers: List[_Timer] = []
        self._timer_seq = itertools.count()
        self._threads: List[threading.Thread] = []
        self._cpu_threads = cpu_threads
        self._io_threads = io_threads
        self._started = False
        self._stopping = False
        # failure escalation (reference ActorTask.java:38-48 — actor job
        # failures are counted and surfaced, never silently swallowed):
        # total count + a bounded ring of (actor_name, traceback) pairs,
        # plus listeners (broker health wires in here). Round-4 lesson: a
        # bare print turned a NameError in the broker tick into two
        # silent zero-perf rounds.
        self.actor_failures = 0
        self.last_failures: deque = deque(maxlen=32)
        self._failure_lock = threading.Lock()
        self._failure_listeners: List[Callable[[Actor, BaseException], None]] = []

    def on_actor_failure(
        self, listener: Callable[[Actor, BaseException], None]
    ) -> None:
        """Register a listener called (from the failing worker thread) on
        every actor-job exception."""
        self._failure_listeners.append(listener)

    def remove_actor_failure_listener(
        self, listener: Callable[[Actor, BaseException], None]
    ) -> None:
        try:
            self._failure_listeners.remove(listener)
        except ValueError:
            pass

    def _record_failure(self, actor: Actor, exc: BaseException) -> None:
        """Escalate one actor-job exception: traceback to stderr, counters
        + bounded failure ring (thread-safe — worker threads race here),
        then listener fan-out (a listener must never kill the worker)."""
        import traceback

        traceback.print_exc()
        with self._failure_lock:
            self.actor_failures += 1
            actor._failure_count += 1
            self.last_failures.append((actor.name, traceback.format_exc()))
        for listener in list(self._failure_listeners):
            try:
                listener(actor, exc)
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ActorScheduler":
        if self._started:
            return self
        self._started = True
        for i in range(self._cpu_threads):
            t = threading.Thread(
                target=self._worker, args=(self._runq,), name=f"zb-actor-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        for i in range(self._io_threads):
            t = threading.Thread(
                target=self._worker, args=(self._io_runq,), name=f"zb-io-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._timer_loop, name="zb-timer", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout)
        self._threads.clear()

    # -- actor submission --------------------------------------------------
    def submit_actor(self, actor: Actor, io_bound: bool = False) -> ActorFuture:
        """Install an actor; resolves when on_actor_started ran.

        Reference: ActorScheduler.submitActor (+ io-bound group selection).
        """
        actor.actor = ActorControl(actor, self)
        actor._io_bound = io_bound
        started = ActorFuture()

        def boot():
            actor.on_actor_started()
            started.complete(actor)

        self._enqueue(_Job(actor, boot))
        return started

    def close_actor(self, actor: Actor) -> ActorFuture:
        done = ActorFuture()

        def close():
            actor.on_actor_closing()
            actor._closed = True
            done.complete()

        self._enqueue(_Job(actor, close))
        return done

    # -- internals ---------------------------------------------------------
    def now_ms(self) -> int:
        if self._clock is not None:
            return self._clock()
        return int(time.monotonic() * 1000)

    def _enqueue(self, job: _Job) -> None:
        actor = job.actor
        with actor._mailbox_lock:
            if actor._closed:
                return
            actor._mailbox.append(job.fn)
            if actor._running:
                return  # the draining worker will pick it up
            actor._running = True
        queue = self._io_runq if getattr(actor, "_io_bound", False) else self._runq
        with self._cv:
            queue.append(actor)
            self._cv.notify()

    def _schedule_timer(
        self, actor: Actor, delay_ms: int, fn: Callable[[], None], interval_ms
    ) -> _Timer:
        timer = _Timer(
            self.now_ms() + delay_ms, next(self._timer_seq), _Job(actor, fn), interval_ms
        )
        with self._cv:
            heapq.heappush(self._timers, timer)
            self._cv.notify_all()
        return timer

    def _worker(self, queue: deque) -> None:
        while True:
            with self._cv:
                while not queue and not self._stopping:
                    self._cv.wait(0.1)
                if self._stopping:
                    return
                actor = queue.popleft()
            self._drain(actor)

    def _drain(self, actor: Actor, max_jobs: int = 64) -> None:
        """Run up to max_jobs queued jobs of one actor, then yield the thread
        (cooperative fairness — the reference's task-switching)."""
        for _ in range(max_jobs):
            with actor._mailbox_lock:
                if not actor._mailbox:
                    actor._running = False
                    return
                fn = actor._mailbox.popleft()
            try:
                fn()
            except Exception as exc:  # noqa: BLE001
                self._record_failure(actor, exc)
        # still work left: requeue for fairness
        queue = self._io_runq if getattr(actor, "_io_bound", False) else self._runq
        with self._cv:
            queue.append(actor)
            self._cv.notify()

    def _expire_due_timers(self, now: int) -> None:
        """Pop cancelled/due timers, enqueue their jobs, reschedule fixed
        rates. Caller holds no lock in the controlled scheduler; the
        threaded timer loop calls under self._cv."""
        while self._timers and (
            self._timers[0].cancelled or self._timers[0].deadline <= now
        ):
            timer = heapq.heappop(self._timers)
            if timer.cancelled:
                continue
            self._enqueue(timer.job)
            if timer.interval is not None:
                timer.deadline = now + timer.interval
                heapq.heappush(self._timers, timer)

    def _timer_loop(self) -> None:
        while True:
            with self._cv:
                if self._stopping:
                    return
                now = self.now_ms()
                self._expire_due_timers(now)
                # sleep until the next deadline (or a new timer / stop wakes
                # us); under a controlled clock poll at a coarse interval
                if self._clock is not None:
                    wait_s = 0.001
                elif self._timers:
                    wait_s = max((self._timers[0].deadline - now) / 1000.0, 0.0)
                else:
                    wait_s = 0.5
                self._cv.wait(wait_s)


class ControlledActorScheduler(ActorScheduler):
    """Deterministic scheduler for tests: no threads; work runs only when
    ``work_until_done()`` is called, and time advances only via the supplied
    controlled clock.

    Reference: ``util/.../sched/testing/ControlledActorSchedulerRule`` +
    ``ControlledActorClock`` (SURVEY.md §4 determinism tooling).
    """

    def __init__(self, clock=None):
        super().__init__(cpu_threads=0, io_threads=0, clock=clock)

    def start(self) -> "ControlledActorScheduler":
        self._started = True
        return self

    def work_until_done(self, max_jobs: int = 100_000) -> int:
        """Expire due timers and drain all mailboxes; returns jobs run. Job
        exceptions are reported (like the threaded drain) but never wedge
        the actor."""
        ran = 0
        while True:
            self._expire_due_timers(self.now_ms())
            actor = None
            for q in (self._runq, self._io_runq):
                if q:
                    actor = q.popleft()
                    break
            if actor is None:
                return ran
            while True:
                with actor._mailbox_lock:
                    if not actor._mailbox:
                        actor._running = False
                        break
                    fn = actor._mailbox.popleft()
                try:
                    fn()
                except Exception as exc:  # noqa: BLE001
                    self._record_failure(actor, exc)
                ran += 1
                if ran > max_jobs:
                    raise RuntimeError("controlled scheduler did not quiesce")
