"""Single-process broker: partitions + processing loop + routing.

Reference parity: the broker assembles per-partition log streams and stream
processors (``PartitionInstallService``), commands enter via the client API
handler (``ClientApiMessageHandler``: validate + write COMMAND with request
metadata), processors run the StreamProcessorController loop
(read committed → process → write follow-ups → side effects), and
cross-partition subscription commands travel over the subscription transport
(``SubscriptionApiCommandMessageHandler``).

Here the loop is explicit (`run_until_idle`) and single-threaded —
determinism is the point: the same committed log always replays to the same
state. The TPU engine plugs in as an alternative partition processor.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, Dict, List, Optional, Tuple

from zeebe_tpu.engine.interpreter import PartitionEngine, WorkflowRepository
from zeebe_tpu.log import LogStream, SegmentedLogStorage
from zeebe_tpu.protocol.columnar import as_log_batch
from zeebe_tpu.log.snapshot import SnapshotController, SnapshotMetadata, SnapshotStorage
from zeebe_tpu.protocol.enums import RecordType, ValueType
from zeebe_tpu.protocol.intents import SubscriberIntent, SubscriptionIntent
from zeebe_tpu.protocol.records import Record, stamp_source_positions
from zeebe_tpu.runtime.clock import SystemClock
from zeebe_tpu import tracing


class Partition:
    """A partition: log stream + stream processor + reader position."""

    def __init__(
        self,
        partition_id: int,
        log: LogStream,
        engine: PartitionEngine,
        snapshots: Optional[SnapshotController] = None,
    ):
        self.partition_id = partition_id
        self.log = log
        self.engine = engine
        self.snapshots = snapshots
        self.next_read_position = 0
        self.term = 0  # raft term once replicated; 0 in single-writer mode
        self.exporter_director = None  # set when exporters are configured

    def has_backlog(self) -> bool:
        return self.next_read_position <= self.log.commit_position


class TopicSubscriptionHandle:
    """Per-subscriber push stream (reference TopicSubscriptionPushProcessor):
    a read-only cursor over the partition's committed records with
    credit-bound delivery; acks persist progress as records in the log."""

    def __init__(self, broker, partition_id, name, handler, subscriber_key, cursor, credits):
        self.broker = broker
        self.partition_id = partition_id
        self.name = name
        self.handler = handler
        self.subscriber_key = subscriber_key
        self.cursor = cursor
        self.capacity = credits
        self._unacked: List[int] = []
        self.closed = False

    def pump(self) -> bool:
        """Push committed records up to the credit limit. Returns True if
        anything was delivered."""
        if self.closed:
            return False
        partition = self.broker.partitions[self.partition_id]
        pushed = False
        while len(self._unacked) < self.capacity:
            reader = partition.log.reader(self.cursor)
            batch = reader.read_committed()
            if not batch:
                break
            advanced = False
            for record in batch:
                if len(self._unacked) >= self.capacity:
                    break
                self.cursor = record.position + 1
                advanced = True
                # subscription/exporter-admin records are not re-delivered:
                # pushing them would make every ack generate further pushes
                if record.metadata.value_type in (
                    ValueType.SUBSCRIBER, ValueType.SUBSCRIPTION,
                    ValueType.EXPORTER,
                ):
                    continue
                self._unacked.append(record.position)
                self.handler(self.partition_id, record)
                pushed = True
            if not advanced:
                break
        return pushed

    def ack(self, position: int) -> None:
        """Acknowledge progress up to ``position`` (persisted in the log;
        restart/reopen resumes after it) and free credits."""
        from zeebe_tpu.protocol.records import TopicSubscriptionRecord

        self.broker.write_command(
            self.partition_id,
            TopicSubscriptionRecord(name=self.name, ack_position=position),
            SubscriptionIntent.ACKNOWLEDGE,
            key=self.subscriber_key,
            with_response=False,
        )
        self._unacked = [p for p in self._unacked if p > position]

    def close(self) -> None:
        self.closed = True
        if self in self.broker._topic_subscriptions:
            self.broker._topic_subscriptions.remove(self)


def _entry_position(entry) -> int:
    """Log position of a tail entry without materializing a lazy ref."""
    if type(entry) is tuple:
        return entry[0].col("position")[entry[1]]
    return entry.position


def _entry_record(entry):
    """The entry as a real ``Record`` (materializes lazy refs — only the
    record-listener tap pays this)."""
    if type(entry) is tuple:
        return entry[0].row(entry[1])
    return entry


class _BrokerFeed:
    """In-process partition → scheduler feed. Dispatch is synchronous
    (``engine.process_wave``) and applies PER RECORD in cursor order, so
    each partition's log bytes are independent of how the shared waves
    were packed — bit-identical to the per-partition drain."""

    def __init__(self, broker: "Broker", partition: Partition):
        self.broker = broker
        self.partition = partition
        self.partition_id = partition.partition_id

    @property
    def device_index(self) -> int:
        """Mesh device of this partition's engine (per-device wave
        metrics; -1 = unplaced/host engine)."""
        return getattr(self.partition.engine, "device_index", -1)

    @property
    def device_indices(self):
        """Span of a sharded-state engine (every plan index its wave
        computes on); empty for single-device engines."""
        return tuple(
            getattr(self.partition.engine, "device_indices", ()) or ()
        )

    @property
    def shard_fill(self):
        """Per-shard staged-row counts of the engine's last dispatched
        wave (sharded-state v2 fill accounting); empty otherwise."""
        return tuple(
            getattr(self.partition.engine, "last_shard_fill", ()) or ()
        )

    def backlog(self) -> int:
        p = self.partition
        return max(0, p.log.commit_position - p.next_read_position + 1)

    def take(self, limit: int):
        p = self.partition
        view = p.log.committed_view(p.next_read_position, limit)
        if not len(view):
            return []
        positions = view.positions()
        p.next_read_position = positions[-1] + 1
        tracer = tracing.TRACER
        if tracer is not None and tracer.by_position:
            tracer.stamp_positions(
                self.partition_id, positions, tracing.FEED_TAKE
            )
        return view

    def dispatch(self, records):
        import time as _time

        t0 = _time.perf_counter()
        p = self.partition
        results = p.engine.process_wave(records)
        entries = (
            records.entries() if hasattr(records, "entries") else records
        )
        for entry, result in zip(entries, results):
            self.broker._apply_result(p, entry, result)
        host_s, device_s = getattr(p.engine, "last_wave_seconds", (None, 0.0))
        if host_s is None:
            host_s, device_s = _time.perf_counter() - t0, 0.0
        return None, host_s, device_s

    def collect(self, pending):  # synchronous dispatch: nothing pending
        return 0.0, 0.0

    def rewind(self, position: int) -> None:
        if position >= 0:
            p = self.partition
            p.next_read_position = min(p.next_read_position, position)

    def tick(self) -> None:  # Broker.tick drives sweeps explicitly
        pass


class Broker:
    """In-process broker (reference: EmbeddedBrokerRule-style single JVM)."""

    def __init__(
        self,
        num_partitions: int = 1,
        data_dir: Optional[str] = None,
        clock: Optional[Callable[[], int]] = None,
        engine_factory=None,
        exporters=None,
    ):
        """``exporters``: optional list of ``ExporterCfg`` entries and/or
        ``(id, Exporter)`` pairs; each partition gets its own director
        (cfg entries build a fresh instance per partition, instance pairs
        are shared — fine for the default single partition)."""
        self.clock = clock or SystemClock()
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="zeebe-tpu-")
        self.repository = WorkflowRepository()
        self.partitions: List[Partition] = []
        import random

        # request ids stay sequential from 0: they are LOG-VISIBLE
        # metadata, and the wave/mesh parity suites pin two Brokers'
        # logs byte-identical. The process-global tracer, however,
        # indexes live spans by request id — several in-process Brokers
        # would collide in by_request and stamp or finish each other's
        # spans — so tracer keys get a per-incarnation random namespace
        # added on top (the log bytes never see it)
        self._next_request_id = 0
        self._trace_request_ns = random.getrandbits(47) << 20
        self._responses: Dict[int, Record] = {}
        self._push_listeners: Dict[int, Callable[[Record], None]] = {}
        self._record_listeners: List[Callable[[int, Record], None]] = []
        self._topic_subscriptions: List[TopicSubscriptionHandle] = []
        self._rr_partition = 0
        self._exporter_specs = list(exporters or [])
        # mesh frame exchange (scheduler/placement.MeshExchange): when the
        # engine factory placed partitions on devices, cross-partition
        # sends between device-resident partitions ride the all_to_all
        # exchange. The single-writer broker flushes IMMEDIATELY per send,
        # so the destination log is byte-identical to the direct append
        # (tests pin it). None = direct append (the default).
        self.mesh_exchange = None
        # shared-wave drain (zeebe_tpu/scheduler): the SAME scheduler the
        # cluster broker runs, so tier-1 covers its packing/dispatch path;
        # False restores the per-partition baseline the A/B compares to
        self.use_scheduler = True
        self._scheduler = None
        # record-lifecycle tracing: reuse (or install) the process-wide
        # span tracer — stamp sites read the tracing.TRACER global, and
        # tests drive sampling via tracing.install()
        tracing.ensure_tracer()
        from zeebe_tpu.tracing.recorder import record_event

        # a boot marker anchors every flight-recorder dump: restarts are
        # the first thing a post-mortem looks for
        record_event(
            "broker", "in-process broker started",
            partitions=num_partitions, data_dir=self.data_dir,
        )

        factory = engine_factory or (
            lambda pid: PartitionEngine(
                partition_id=pid,
                num_partitions=num_partitions,
                repository=self.repository,
                clock=self.clock,
            )
        )
        for pid in range(num_partitions):
            pdir = os.path.join(self.data_dir, f"partition-{pid}")
            storage = SegmentedLogStorage(pdir)
            log = LogStream(storage, partition_id=pid, clock=self.clock)
            snapshots = SnapshotController(
                SnapshotStorage(os.path.join(pdir, "snapshots"))
            )
            self.partitions.append(Partition(pid, log, factory(pid), snapshots))
        self._recover_partitions()
        self._open_exporters()

    # -- recovery: snapshot + replay (reference StreamProcessorController
    # recovery :156-211 then reprocessing :213-279) -------------------------
    def _recover_partitions(self) -> None:  # noqa: D401
        """Restore each partition's newest valid snapshot, then replay the
        committed records after it to rebuild state — without re-executing
        side effects (no appends, responses, sends, or pushes).

        Partitions replay in id order: deployments commit on their partition
        before instance commands causally follow on others (the reference's
        system-partition-first ordering)."""
        boundaries = {}
        for partition in self.partitions:
            # position-based re-reads (incident resolution, reference
            # TypedStreamReader) serve from the LOG behind the engine's
            # hot cache window — no spill copies, no cache pre-fill
            cache = getattr(partition.engine, "records_by_position", None)
            log_backed = hasattr(cache, "set_log_lookup")
            if log_backed:
                cache.set_log_lookup(partition.log.record_at)
            state, meta = partition.snapshots.recover(partition.log.next_position - 1)
            if state is not None:
                partition.engine.restore_state(state)
                partition.next_read_position = meta.last_processed_position + 1
            # single pass over the log to find the replay boundary
            last_source = -1
            for record in partition.log.reader(0):
                if not log_backed:
                    partition.engine.records_by_position[record.position] = record
                if record.source_record_position > last_source:
                    last_source = record.source_record_position
            boundaries[partition.partition_id] = last_source
        for partition in self.partitions:
            self._replay(partition, boundaries[partition.partition_id])

    def _open_exporters(self) -> None:
        """One director per partition, resumed at the engine state's
        recovered acked positions (reference ExporterDirectorService:
        installed next to the stream processor). Synchronous mode: the
        ``run_until_idle`` loop pumps directors to quiescence."""
        from zeebe_tpu.exporter.director import (
            fold_tail_acks,
            remove_stale_positions,
        )

        if not self._exporter_specs:
            # even with NO exporters configured the recovered positions of
            # previously configured ones must be swept (REMOVE), or the
            # last-removed exporter's stale entry pins the compaction
            # floor forever
            for partition in self.partitions:
                stale = remove_stale_positions(
                    fold_tail_acks(
                        partition.engine.exporter_positions,
                        partition.log,
                        partition.next_read_position,
                    ),
                    (),
                )
                if stale:
                    partition.log.append(stale)
            return
        from zeebe_tpu.exporter import ExporterDirector, build_exporter

        ids = [
            spec[0] if isinstance(spec, tuple) else spec.id
            for spec in self._exporter_specs
        ]
        if len(set(ids)) != len(ids):
            # two exporters on one id share one replicated position entry:
            # the faster one's ack overwrites the slower one's progress
            # and a restart silently skips the difference
            raise ValueError(f"duplicate exporter ids in {ids}")
        if len(self.partitions) > 1 and any(
            isinstance(spec, tuple) for spec in self._exporter_specs
        ):
            # a shared instance would interleave partitions into one sink
            # (and the JSONL dedup tail would silently DROP the lower
            # partition's records); cfg entries build one instance per
            # partition and are the only safe multi-partition shape
            raise ValueError(
                "exporter instance pairs cannot be shared across "
                "multiple partitions — pass ExporterCfg entries instead"
            )
        for partition in self.partitions:
            pairs = []
            for spec in self._exporter_specs:
                if isinstance(spec, tuple):
                    pairs.append(spec)
                else:
                    pairs.append(build_exporter(spec))
            director = ExporterDirector(
                partition.partition_id,
                partition.log,
                pairs,
                append_fn=lambda recs, p=partition: p.log.append(recs),
                clock=self.clock,
            )
            director.open(fold_tail_acks(
                partition.engine.exporter_positions,
                partition.log,
                partition.next_read_position,
            ))
            partition.exporter_director = director

    def _pump_exporters(self) -> bool:
        progress = False
        for partition in self.partitions:
            director = getattr(partition, "exporter_director", None)
            if director is not None:
                progress = director.pump() or progress
        return progress

    def _replay(self, partition: Partition, last_source: int) -> None:
        # Reprocess only up to the last source event position — the highest
        # position whose follow-ups are already in the log. Records after it
        # were appended but never processed (crash between append and
        # process); they are processed normally, WITH side effects, by the
        # regular loop (reference StreamProcessorController:189-279:
        # lastSourceEventPosition bounds reprocessing).
        reader = partition.log.reader(partition.next_read_position)
        for record in reader.read_committed():
            if record.position > last_source:
                break
            partition.engine.process(record)  # state updates only
            partition.next_read_position = record.position + 1

    def snapshot(self) -> None:
        """Checkpoint every partition (reference: periodic
        ``actor.runAtFixedRate(snapshotPeriod, createSnapshot)``; here the
        runtime decides when — tests and the broker's timer loop call it)."""
        for partition in self.partitions:
            metadata = SnapshotMetadata(
                last_processed_position=partition.next_read_position - 1,
                last_written_position=partition.log.next_position - 1,
                term=partition.term,
            )
            # dirty-delta path: clean families reuse the previous take's
            # manifest entries (no re-encode/re-hash; on the device engine
            # no device→host readback either)
            partition.snapshots.take_engine(partition.engine, metadata)
            # compaction: the snapshot covers everything below its
            # last-processed position — drop those records (bounded by the
            # engine's floor: open incidents still re-read their failure
            # events by position). Reference: segments below the snapshot
            # are deleted; the log stops pinning every record in RAM.
            floor = min(
                metadata.last_processed_position + 1,
                partition.engine.compaction_floor(),
            )
            partition.log.compact(floor)

    # -- client API (reference ClientApiMessageHandler) --------------------
    def write_command(
        self,
        partition_id: int,
        value,
        intent: int,
        key: int = -1,
        with_response: bool = True,
    ) -> Optional[int]:
        """Write a COMMAND record to a partition's log; returns request id."""
        from zeebe_tpu.protocol.metadata import RecordMetadata

        request_id = None
        md = RecordMetadata(
            record_type=RecordType.COMMAND,
            value_type=value.VALUE_TYPE,
            intent=int(intent),
        )
        if with_response:
            request_id = self._next_request_id
            self._next_request_id += 1
            md.request_id = request_id
            md.request_stream_id = 0
        record = Record(key=key, metadata=md, value=value)
        tracer = tracing.TRACER
        span = tracer.maybe_sample(partition_id) if tracer is not None else None
        partition = self.partitions[partition_id]
        if span is not None and request_id is not None:
            # bind by request id BEFORE the append: a concurrent drain
            # thread can apply the record the instant it lands, and the
            # RESPONSE stamp looks the span up by request id
            tracer.bind_request(
                span, self._trace_request_ns + request_id, partition_id
            )
        partition.log.append([record])
        if span is not None:
            # single-writer broker: the append IS the commit (no raft
            # queue/fsync hops); the span is position-keyed from here
            tracer.bind_position(
                span, partition_id, record.position, committed=True
            )
            if (
                not span.finished
                and partition.next_read_position > record.position
            ):
                # a drain on another thread applied the record between
                # the append and the bind: the position-keyed stamps
                # (APPLY, finish_positions) already missed this span.
                # With no ack plane nothing later can finish it; with a
                # working plane it survives ONLY if some exporter has
                # not yet dispatched past the position (the coming
                # dispatch stamps it and the ack then finishes it) —
                # otherwise close it instead of leaking it in the live
                # budget with every stamp path hot.
                director = partition.exporter_director
                if (
                    director is None
                    or not director.can_ack()
                    or director.dispatch_passed(record.position)
                ):
                    tracer.finish_positions(
                        partition_id, (record.position,)
                    )
        return request_id

    def next_partition(self) -> int:
        """Round-robin partition selection (reference client routing)."""
        pid = self._rr_partition
        self._rr_partition = (self._rr_partition + 1) % len(self.partitions)
        return pid

    def partition_for_correlation_key(self, correlation_key: str) -> int:
        return self.partitions[0].engine.partition_for_correlation_key(correlation_key)

    def take_response(self, request_id: int) -> Optional[Record]:
        return self._responses.pop(request_id, None)

    def on_push(
        self, subscriber_key: int, listener: Callable[[int, Record], None]
    ) -> None:
        """Register a push listener; called with (partition_id, record)."""
        self._push_listeners[subscriber_key] = listener

    def on_record(self, listener: Callable[[int, Record], None]) -> None:
        """In-process record tap (tests/debug; the durable, credit-controlled
        variant is ``open_topic_subscription``)."""
        self._record_listeners.append(listener)

    # -- topic subscriptions (reference TopicSubscriptionManagementProcessor
    # + per-subscriber TopicSubscriptionPushProcessor:36) -------------------
    def open_topic_subscription(
        self,
        name: str,
        handler: Callable[[int, Record], None],
        partition_id: int = 0,
        start_position: Optional[int] = None,
        credits: int = 32,
        force_start: bool = False,
    ) -> "TopicSubscriptionHandle":
        """Open a durable push subscription over a partition's record stream.
        Resumes from the last ACKNOWLEDGEd position persisted in the log
        unless ``force_start``; otherwise starts at ``start_position`` (or
        0). Push pace is credit-bound; ``handle.ack(position)`` persists
        progress and replenishes credits."""
        from zeebe_tpu.protocol.records import TopicSubscriberRecord

        request_id = self.write_command(
            partition_id,
            TopicSubscriberRecord(
                name=name,
                start_position=-1 if start_position is None else start_position,
                buffer_size=credits,
                force_start=force_start,
            ),
            SubscriberIntent.SUBSCRIBE,
        )
        self.run_until_idle()
        response = self.take_response(request_id)
        engine = self.partitions[partition_id].engine
        acked = engine.topic_sub_acks.get(name)
        if acked is not None and not force_start:
            cursor = acked + 1  # resume after the last acknowledged record
        elif start_position is not None:
            cursor = start_position
        else:
            cursor = 0
        handle = TopicSubscriptionHandle(
            broker=self,
            partition_id=partition_id,
            name=name,
            handler=handler,
            subscriber_key=response.key if response is not None else -1,
            cursor=cursor,
            credits=credits,
        )
        self._topic_subscriptions.append(handle)
        self._pump_topic_subscriptions()
        return handle

    def _pump_topic_subscriptions(self) -> bool:
        pushed = False
        for handle in list(self._topic_subscriptions):
            pushed = handle.pump() or pushed
        return pushed

    # -- processing loop ----------------------------------------------------
    # committed records drain in WAVES: one engine dispatch per wave (the
    # device engine's SIMD unit — per-record process() calls round-trip
    # the device once per record), but results apply PER RECORD in log
    # order, so the appended log is byte-identical to record-at-a-time
    # processing (tests/test_serving_wave.py pins this). Set to 1 to force
    # the record-at-a-time baseline.
    wave_size = 256

    def _wave_scheduler(self):
        """The broker's shared-wave scheduler, feeds registered once and
        sizing resynced per drain (tests retune ``wave_size`` after
        construction)."""
        from zeebe_tpu.scheduler import WaveScheduler

        size = max(1, self.wave_size)
        scheduler = self._scheduler
        if scheduler is None:
            scheduler = WaveScheduler(wave_size=size)
            for partition in self.partitions:
                scheduler.register(_BrokerFeed(self, partition))
            self._scheduler = scheduler
        if scheduler.wave_size != size:
            scheduler.wave_size = size
            scheduler.quantum = max(1, size // 8)
            scheduler.backpressure_limit = 4 * size
        return scheduler

    def run_until_idle(self, max_iterations: int = 100_000) -> int:
        """Process all partitions until no backlog remains. Returns the number
        of records processed (the StreamProcessorController hot loop,
        StreamProcessorController.java:296-399, run to quiescence).

        Default mode drains through the shared-wave scheduler — one wave
        may pack several partitions' committed tails (continuous
        batching); per-partition apply order is cursor order either way,
        so each partition's log is bit-identical across modes
        (``use_scheduler = False`` forces the per-partition baseline)."""
        if not self.use_scheduler:
            return self._run_until_idle_unscheduled(max_iterations)
        scheduler = self._wave_scheduler()
        processed = 0
        progress = True
        while progress:
            progress = False
            drained = scheduler.drain(
                max_records=max_iterations + 1 - processed
            )
            processed += drained
            if processed > max_iterations:
                raise RuntimeError("broker did not reach quiescence")
            if drained:
                progress = True
            # deliver to topic subscriptions; their handlers may write acks
            # or commands, which the next pass processes
            if self._pump_topic_subscriptions():
                progress = True
            # exporters tail the freshly committed records; their position
            # acks are records too and process on the next pass
            if self._pump_exporters():
                progress = True
        return processed

    def _run_until_idle_unscheduled(self, max_iterations: int) -> int:
        """Per-partition baseline drain (the bench A/B reference): each
        partition's backlog drains to empty in its own waves before the
        next partition runs."""
        from zeebe_tpu.runtime.metrics import observe_wave

        processed = 0
        progress = True
        wave_cap = max(1, self.wave_size)
        while progress:
            progress = False
            for partition in self.partitions:
                while partition.has_backlog():
                    reader = partition.log.reader(partition.next_read_position)
                    records = reader.read_committed()
                    if not records:
                        break
                    for start in range(0, len(records), wave_cap):
                        wave = records[start : start + wave_cap]
                        results = partition.engine.process_wave(wave)
                        for record, result in zip(wave, results):
                            self._apply_result(partition, record, result)
                        processed += len(wave)
                        host_s, device_s = getattr(
                            partition.engine, "last_wave_seconds", (0.0, 0.0)
                        )
                        observe_wave(len(wave), wave_cap, host_s, device_s)
                        if processed > max_iterations:
                            raise RuntimeError("broker did not reach quiescence")
                    progress = True
            if self._pump_topic_subscriptions():
                progress = True
            if self._pump_exporters():
                progress = True
        return processed

    def _apply_result(self, partition: Partition, record, result) -> None:
        """Apply one processed record's outputs — sends, follow-up appends,
        responses, pushes — exactly as the per-record loop did (the engine
        already processed the whole wave; application stays record-major
        so the log bytes don't depend on the wave size). ``record`` may be
        a real ``Record`` or a lazy ``(batch, idx)`` tail entry; only the
        record-listener tap materializes it."""
        position = _entry_position(record)
        # monotone: the scheduler feed already advanced the cursor at
        # take(); the baseline path advances here
        if position + 1 > partition.next_read_position:
            partition.next_read_position = position + 1
        tracer = tracing.TRACER
        # "no ack will ever arrive" probe (scans exporter handles):
        # computed lazily, at most once per record, only on traced paths
        no_ack_plane = None
        if tracer is not None and tracer.by_position:
            tracer.stamp_positions(
                partition.partition_id, (position,), tracing.APPLY
            )
        for target_pid, send in result.sends:
            # reference: subscription transport → command on the target log.
            # Sends go BEFORE the local follow-up append: once the follow-ups
            # are durable this record is inside the replay boundary and its
            # side effects never re-run, so a crash in between must lose the
            # (reprocessable) follow-ups, not the send. Duplicate sends after
            # a crash are fine — subscription open/correlate are idempotent
            # (dead activity ⇒ rejection; CLOSE removes all matches).
            self._route_send(partition, target_pid, send)
        if result.written:
            stamp_source_positions(result.written, position)
            partition.log.append(as_log_batch(result.written))
            cache = partition.engine.records_by_position
            for written in result.written:
                if type(written) is tuple:
                    # lazy columnar follow-up: the log-backed cache serves
                    # position re-reads without materializing it here
                    continue
                cache[written.position] = written
        for response in result.responses:
            if response.metadata.request_id >= 0:
                self._responses[response.metadata.request_id] = response
                if tracer is not None and tracer.tracking_requests():
                    # without an exporter plane — or with one whose every
                    # exporter broke at open — no ack will ever finish
                    # the span: the response is its last stage
                    if no_ack_plane is None:
                        no_ack_plane = tracing.no_ack_plane(partition)
                    tracer.stamp_request(
                        self._trace_request_ns + response.metadata.request_id,
                        tracing.RESPONSE, final=no_ack_plane,
                    )
        for subscriber_key, push in result.pushes:
            listener = self._push_listeners.get(subscriber_key)
            if listener is not None:
                listener(partition.partition_id, push)
        if tracer is not None and tracer.by_position:
            if no_ack_plane is None:
                no_ack_plane = tracing.no_ack_plane(partition)
            if no_ack_plane:
                # no exporter plane (or one that can never ack again):
                # this apply is the last stage a span at this position can
                # reach (response-less internal commands never hit the
                # stamp_request(final=True) path above)
                tracer.finish_positions(partition.partition_id, (position,))
        for listener in self._record_listeners:
            listener(partition.partition_id, _entry_record(record))

    def _route_send(self, partition: Partition, target_pid: int, send) -> None:
        """Cross-partition send: over the mesh all_to_all frame exchange
        when both partitions are device-resident and an exchange is
        installed, direct append otherwise. Immediate flush keeps the
        single-writer broker deterministic: the arrival appends at exactly
        the point the direct append would have."""
        exchange = self.mesh_exchange
        if exchange is not None:
            src = getattr(partition.engine, "device_index", -1)
            dst = getattr(
                self.partitions[target_pid].engine, "device_index", -1
            )
            if src >= 0 and dst >= 0 and src != dst:
                from zeebe_tpu.protocol import codec

                if exchange.queue(
                    src, dst, target_pid, codec.encode_record(send)
                ):
                    exchange.flush(self._deliver_mesh_frame)
                    return
        self.partitions[target_pid].log.append([send])

    def _deliver_mesh_frame(self, partition_id: int, frame: bytes) -> None:
        from zeebe_tpu.protocol import codec

        record, _ = codec.decode_record(bytes(frame))
        record.position = -1  # assigned at append, like transport arrivals
        record.timestamp = -1
        self.partitions[partition_id].log.append([record])

    # -- time-driven side processors ---------------------------------------
    def tick(self) -> None:
        """Fire due timers / job timeouts / message TTLs (reference: periodic
        actor jobs — JobTimeOutStreamProcessor, MessageTimeToLiveChecker)."""
        for partition in self.partitions:
            for command in partition.engine.check_job_deadlines():
                partition.log.append([command])
            for command in partition.engine.check_timer_deadlines():
                partition.log.append([command])
            for command in partition.engine.check_message_ttls():
                partition.log.append([command])
            # jobs stranded by credit droughts (see backlog_activations).
            # The DEVICE job backlog is gated behind the same cheap fused
            # probe the cluster broker uses (PROBE_JOB_BACKLOG): the
            # unconditional device_backlog_activations() here pulled the
            # whole job table device→host every tick (~150 ms on a
            # tunneled chip) even when nothing was assignable. Unlike the
            # cluster broker's launch-and-poll pattern, the probe here is
            # read SYNCHRONOUSLY (one fused scalar): this embedded broker
            # is the oracle-parity surface — a one-tick-deferred probe
            # would assign backlog a tick later than the host oracle and
            # break the DualRig log comparisons tick-for-tick
            backlog = partition.engine.backlog_activations()
            probe = getattr(partition.engine, "deadlines_due_probe", None)
            if hasattr(partition.engine, "device_backlog_activations"):
                from zeebe_tpu.tpu.engine import PROBE_JOB_BACKLOG

                mask = int(probe()) if probe is not None else PROBE_JOB_BACKLOG
                if mask & PROBE_JOB_BACKLOG:
                    backlog = backlog + (
                        partition.engine.device_backlog_activations()
                    )
            for command in backlog:
                partition.log.append([command])

    def records(self, partition_id: int = 0) -> List[Record]:
        """All committed records of a partition (test/debug; reference
        LogStreamPrinter / RecordStream asserts)."""
        return list(self.partitions[partition_id].log.reader(0))

    def close(self) -> None:
        from zeebe_tpu.tracing.recorder import record_event

        record_event("broker", "in-process broker closed",
                     data_dir=self.data_dir)
        for partition in self.partitions:
            if partition.exporter_director is not None:
                partition.exporter_director.close()
            partition.log.storage.close()
