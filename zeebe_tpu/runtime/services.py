"""Service container: async dependency-injection / lifecycle kernel.

Reference parity: ``service-container/`` — named services with declared
dependencies and injectors (``ServiceBuilder.dependency/group/install``),
start ordering resolved from the dependency graph
(``ServiceDependencyResolver``), service groups with join/leave listeners
(how the reference broker reacts to leader partitions appearing:
``PartitionInstallService`` installs into LEADER_PARTITION_GROUP_NAME and
components subscribe), composite installs, and async stop cascading to
dependents. The whole broker is assembled from services
(``SystemContext.initSystemContext``).

Single-writer: the container itself is an Actor — all mutation runs on its
mailbox, so no locks around the registry.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Set

from zeebe_tpu.runtime.actors import Actor, ActorFuture, ActorScheduler


class Service:
    """Optional base: services may be plain values; lifecycle hooks are
    duck-typed (``start(ctx)`` / ``stop(ctx)``)."""

    def start(self, ctx: "ServiceStartContext") -> None:  # noqa: B027
        pass

    def stop(self, ctx: "ServiceStopContext") -> None:  # noqa: B027
        pass


@dataclasses.dataclass
class ServiceStartContext:
    name: str
    container: "ServiceContainer"
    # injected dependency values by service name
    dependencies: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def get(self, dep_name: str) -> Any:
        return self.dependencies[dep_name]


@dataclasses.dataclass
class ServiceStopContext:
    name: str
    container: "ServiceContainer"


@dataclasses.dataclass
class _Registration:
    name: str
    service: Any
    dependencies: List[str]
    injectors: Dict[str, Callable[[Any], None]]
    groups: List[str]
    started: bool = False
    stopping: bool = False
    start_future: ActorFuture = dataclasses.field(default_factory=ActorFuture)
    stop_future: Optional[ActorFuture] = None


class ServiceBuilder:
    """Fluent install builder (reference ``ServiceBuilder``)."""

    def __init__(self, container: "ServiceContainer", name: str, service: Any):
        self._container = container
        self._name = name
        self._service = service
        self._dependencies: List[str] = []
        self._injectors: Dict[str, Callable[[Any], None]] = {}
        self._groups: List[str] = []

    def dependency(
        self, name: str, injector: Optional[Callable[[Any], None]] = None
    ) -> "ServiceBuilder":
        self._dependencies.append(name)
        if injector is not None:
            self._injectors[name] = injector
        return self

    def group(self, group_name: str) -> "ServiceBuilder":
        self._groups.append(group_name)
        return self

    def install(self) -> ActorFuture:
        reg = _Registration(
            name=self._name,
            service=self._service,
            dependencies=self._dependencies,
            injectors=self._injectors,
            groups=self._groups,
        )
        return self._container._install(reg)


class CompositeServiceBuilder:
    """Install a set of services atomically-ish: one future completing when
    all are started (reference ``CompositeServiceBuilder``)."""

    def __init__(self, container: "ServiceContainer"):
        self._container = container
        self._builders: List[ServiceBuilder] = []

    def create_service(self, name: str, service: Any) -> ServiceBuilder:
        b = self._container.create_service(name, service)
        self._builders.append(b)
        return b

    def install(self) -> ActorFuture:
        futures = [b.install() for b in self._builders]
        done = ActorFuture()
        remaining = [len(futures)]
        if not futures:
            done.complete([])
            return done

        def on_one(f: ActorFuture):
            if f._exception is not None:
                done.complete_exceptionally(f._exception)  # first failure wins
                return
            remaining[0] -= 1
            if remaining[0] == 0:
                done.complete([fut._value for fut in futures])

        for f in futures:
            f.on_complete(on_one)
        return done


class ServiceContainer(Actor):
    """The registry + dependency resolver."""

    def __init__(self, scheduler: ActorScheduler):
        super().__init__("service-container")
        self._scheduler = scheduler
        self._registry: Dict[str, _Registration] = {}
        self._group_members: Dict[str, Set[str]] = {}
        self._group_listeners: Dict[str, List] = {}
        scheduler.submit_actor(self)  # zblint: disable=unobserved-actor-future (boot submit; start failures land in the scheduler failure ring)

    # -- public API --------------------------------------------------------
    def create_service(self, name: str, service: Any) -> ServiceBuilder:
        return ServiceBuilder(self, name, service)

    def composite(self) -> CompositeServiceBuilder:
        return CompositeServiceBuilder(self)

    def get(self, name: str) -> Any:
        reg = self._registry.get(name)
        return reg.service if reg and reg.started else None

    def has_service(self, name: str) -> bool:
        reg = self._registry.get(name)
        return bool(reg and reg.started)

    def remove_service(self, name: str) -> ActorFuture:
        """Stop a service and, transitively, everything depending on it
        (reference: dependent services stop before their dependency)."""
        done = ActorFuture()
        self.actor.run(lambda: self._do_remove(name, done))
        return done

    def on_group_change(
        self,
        group_name: str,
        on_join: Optional[Callable[[str, Any], None]] = None,
        on_leave: Optional[Callable[[str, Any], None]] = None,
    ) -> None:
        """Group listeners (reference ServiceGroupReference): ``on_join``
        fires for existing members too."""

        def add():
            self._group_listeners.setdefault(group_name, []).append((on_join, on_leave))
            if on_join:
                for member in sorted(self._group_members.get(group_name, ())):
                    reg = self._registry.get(member)
                    if reg and reg.started:
                        on_join(member, reg.service)

        self.actor.run(add)

    def group_members(self, group_name: str) -> List[str]:
        return sorted(self._group_members.get(group_name, ()))

    # -- container-actor internals ----------------------------------------
    def _install(self, reg: _Registration) -> ActorFuture:
        def do_install():
            if reg.name in self._registry:
                reg.start_future.complete_exceptionally(
                    ValueError(f"service {reg.name!r} already installed")
                )
                return
            cycle = self._find_cycle(reg)
            if cycle is not None:
                reg.start_future.complete_exceptionally(
                    ValueError(
                        f"circular service dependency: {' -> '.join(cycle)}"
                    )
                )
                return
            self._registry[reg.name] = reg
            self._try_start_ready()

        self.actor.run(do_install)
        return reg.start_future

    def _find_cycle(self, new_reg: _Registration):
        """Detect a dependency cycle that installing ``new_reg`` would close
        (reference ServiceDependencyResolver rejects circular dependencies
        instead of hanging the install)."""
        graph = {name: r.dependencies for name, r in self._registry.items()}
        graph[new_reg.name] = new_reg.dependencies
        path: List[str] = []
        on_path = set()
        visited = set()

        def visit(name: str):
            if name in on_path:
                return path[path.index(name):] + [name]
            if name in visited or name not in graph:
                return None
            visited.add(name)
            on_path.add(name)
            path.append(name)
            for dep in graph[name]:
                found = visit(dep)
                if found:
                    return found
            path.pop()
            on_path.discard(name)
            return None

        return visit(new_reg.name)

    def _try_start_ready(self) -> None:
        progressed = True
        while progressed:
            progressed = False
            for reg in list(self._registry.values()):
                if reg.started or reg.stopping:
                    continue
                deps = [self._registry.get(d) for d in reg.dependencies]
                if all(d is not None and d.started for d in deps):
                    self._start_one(reg)
                    progressed = True

    def _start_one(self, reg: _Registration) -> None:
        ctx = ServiceStartContext(
            name=reg.name,
            container=self,
            dependencies={d: self._registry[d].service for d in reg.dependencies},
        )
        for dep_name, injector in reg.injectors.items():
            injector(self._registry[dep_name].service)
        start = getattr(reg.service, "start", None)
        if callable(start):
            try:
                start(ctx)
            except Exception as e:  # noqa: BLE001
                reg.start_future.complete_exceptionally(e)
                del self._registry[reg.name]
                return
        reg.started = True
        for group in reg.groups:
            self._group_members.setdefault(group, set()).add(reg.name)
            for on_join, _ in self._group_listeners.get(group, ()):
                if on_join:
                    on_join(reg.name, reg.service)
        reg.start_future.complete(reg.service)

    def _dependents_of(self, name: str) -> List[str]:
        return [
            r.name
            for r in self._registry.values()
            if name in r.dependencies and r.started and not r.stopping
        ]

    def _do_remove(self, name: str, done: ActorFuture) -> None:
        reg = self._registry.get(name)
        if reg is None:
            done.complete()
            return
        if reg.stopping:
            # an in-flight removal owns the stop: park this caller on it
            reg.stop_future.on_complete(lambda _f: done.complete())
            return
        if not reg.started:
            # never started: no stop() to run; unblock anyone awaiting install
            self._registry.pop(name, None)
            reg.start_future.complete_exceptionally(
                ValueError(f"service {name!r} removed before start")
            )
            done.complete()
            return
        reg.stopping = True
        reg.stop_future = done
        dependents = self._dependents_of(name)
        remaining = [len(dependents)]

        def stop_self():
            for group in reg.groups:
                members = self._group_members.get(group, set())
                members.discard(reg.name)
                for _, on_leave in self._group_listeners.get(group, ()):
                    if on_leave:
                        on_leave(reg.name, reg.service)
            stop = getattr(reg.service, "stop", None)
            if callable(stop):
                try:
                    stop(ServiceStopContext(name=reg.name, container=self))
                except Exception:  # noqa: BLE001
                    import traceback

                    traceback.print_exc()
            self._registry.pop(reg.name, None)
            done.complete()

        if not dependents:
            stop_self()
            return

        def on_dependent_stopped(_f):
            remaining[0] -= 1
            if remaining[0] == 0:
                self.actor.run(stop_self)

        for dep in dependents:
            child_done = ActorFuture()
            self._do_remove(dep, child_done)
            child_done.on_complete(on_dependent_stopped)

    def close(self) -> ActorFuture:
        """Stop every service, leaves-first (reference
        ServiceContainer.closeAsync)."""
        done = ActorFuture()

        def do_close():
            roots = [
                r.name
                for r in self._registry.values()
                if r.started and not r.stopping
            ]
            remaining = [len(roots)]
            if not remaining[0]:
                done.complete()
                return

            def on_one(_f):
                remaining[0] -= 1
                if remaining[0] == 0:
                    done.complete()

            for name in roots:
                child = ActorFuture()
                self._do_remove(name, child)
                child.on_complete(on_one)

        self.actor.run(do_close)
        return done
