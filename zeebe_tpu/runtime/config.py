"""Broker configuration: TOML file → typed config tree + env overrides.

Reference parity: ``broker-core/.../system/configuration/`` —
``TomlConfigurationReader`` parses ``zeebe.cfg.toml`` into the ``BrokerCfg``
bean tree (network with port offset, data, cluster, threads, metrics,
gossip, raft, bootstrap topics), and ``Environment`` applies env-var
overrides (e.g. ``ZEEBE_PORT_OFFSET`` in ``NetworkCfg``). The canonical
commented default file lives at ``dist/zeebe.cfg.toml`` (reference
``dist/src/main/config/zeebe.cfg.toml``).
"""

from __future__ import annotations

import dataclasses
import os

try:
    import tomllib
except ImportError:  # Python < 3.11: tomli is the API-compatible backport
    import tomli as tomllib
from typing import Any, Dict, List, Optional

# default ports mirror the reference layout (client 26501, management 26502,
# replication 26503, subscription 26504; gateway 26500)
DEFAULT_GATEWAY_PORT = 26500
DEFAULT_CLIENT_PORT = 26501
DEFAULT_MANAGEMENT_PORT = 26502
DEFAULT_REPLICATION_PORT = 26503
DEFAULT_SUBSCRIPTION_PORT = 26504


@dataclasses.dataclass
class NetworkCfg:
    host: str = "127.0.0.1"
    port_offset: int = 0
    gateway_port: int = DEFAULT_GATEWAY_PORT
    client_port: int = DEFAULT_CLIENT_PORT
    management_port: int = DEFAULT_MANAGEMENT_PORT
    replication_port: int = DEFAULT_REPLICATION_PORT
    subscription_port: int = DEFAULT_SUBSCRIPTION_PORT

    def apply_offset(self) -> None:
        # reference: portOffset shifts every socket binding by offset * 10
        shift = self.port_offset * 10
        self.gateway_port += shift
        self.client_port += shift
        self.management_port += shift
        self.replication_port += shift
        self.subscription_port += shift


@dataclasses.dataclass
class DataCfg:
    directory: str = "data"
    segment_size_bytes: int = 64 * 1024 * 1024
    snapshot_period_ms: int = 15 * 60 * 1000
    snapshot_replication_period_ms: int = 5 * 60 * 1000
    # serve the partition logs through the C++ storage backend
    # (native/log_storage.cc — same on-disk format as the Python one);
    # requires the native toolchain, fails loudly when missing
    native_storage: bool = False


@dataclasses.dataclass
class ClusterCfg:
    node_id: str = "node-0"
    initial_contact_points: List[str] = dataclasses.field(default_factory=list)
    bootstrap_expect: int = 1
    replication_factor: int = 1
    partitions: int = 1


@dataclasses.dataclass
class ThreadsCfg:
    cpu_thread_count: int = 2
    io_thread_count: int = 2


@dataclasses.dataclass
class MetricsCfg:
    enabled: bool = True
    file: str = "metrics/zeebe.prom"
    flush_period_ms: int = 5_000
    # HTTP /metrics endpoint for prometheus scraping (0 disables; the
    # file writer keeps running either way — the reference exposes the
    # file via node exporter, here the broker serves it directly)
    port: int = 9600


@dataclasses.dataclass
class EngineCfg:
    """Which stream-processing engine serves the partitions this node
    leads: ``host`` = the Python oracle interpreter, ``tpu`` = the batched
    device kernel (``zeebe_tpu.tpu.TpuPartitionEngine``). The reference
    has exactly one engine, installed unconditionally per partition
    (broker-core/.../PartitionInstallService.java:106-291); here the
    device engine is the flagship and the host oracle the fallback."""

    type: str = "host"  # "host" | "tpu"
    capacity: int = 1 << 12  # device table capacity (instances/jobs rows)
    num_vars: int = 16  # payload variable columns on device
    sub_capacity: int = 16  # sub-process nesting table rows
    # on-chip pallas-vs-XLA parity smoke before the first TPU engine
    # serves (refuses to serve on divergence); no-op off-TPU
    pallas_selfcheck: bool = True


@dataclasses.dataclass
class SchedulerCfg:
    """Cross-partition continuous-batching wave scheduler
    (``zeebe_tpu/scheduler/``): committed records from every leader
    partition on this broker pack into SHARED device waves. ``enabled =
    false`` restores the per-partition drain (the A/B baseline the bench
    compares against)."""

    enabled: bool = True
    wave_size: int = 512  # shared-wave record capacity (= drain chunk)
    # deficit-round-robin quantum: records of credit per feed per packing
    # round (0 = wave_size // 8)
    quantum: int = 0
    # per-partition cap on dispatched-but-unapplied records; a partition
    # at the cap is skipped until its apply side catches up (0 = 4 waves)
    backpressure_limit: int = 0


@dataclasses.dataclass
class MeshCfg:
    """Mesh-sharded serving plane (``scheduler/placement.DevicePlan``):
    leader partitions are placed across the visible accelerator devices
    (round-robin, rebalanced on leadership change), so the wave
    scheduler's drain dispatches different partitions' wave segments to
    DIFFERENT devices within one scheduling round. ``enabled = false``
    pins every engine to the default device — the single-device A/B
    baseline ``bench.py --mesh`` compares against. Only the device engine
    (``[engine] type = "tpu"``) is placed; the host oracle has no device
    state."""

    enabled: bool = True
    # cap on devices used (0 = every visible device)
    devices: int = 0
    # route cross-partition message-correlation command frames over the
    # mesh's all_to_all exchange instead of the host transport hop when
    # both partitions are device-resident on this broker
    exchange: bool = True
    exchange_slots: int = 32  # frames per (src, dst) device pair per round
    exchange_frame_bytes: int = 1024  # slot width; larger frames fall back
    # mesh-SHARDED partition state: each leader partition's row tables
    # block-shard over a span of this many devices (engine
    # ``state_shards``) — the wave's step gathers the tables over ICI,
    # computes on the whole span at once, and keeps local row blocks.
    # 0/1 = single-device placement (the default); replays are
    # bit-identical either way (tests/test_sharded_state.py pins it)
    sharded_partitions: int = 0
    # sharded-state ROUTING mode (v2): "gathered" = every wave gathers
    # the sharded tables (v1 — compute does not divide by the span);
    # "resident" = residency-routed staging — single-owner waves stage
    # into the owner shard's batch lane and step only its local rows (no
    # per-wave table gather; unknown-residency/overflow waves fall back
    # to a gathered step). Logs are bit-identical in every mode.
    routing: str = "gathered"


@dataclasses.dataclass
class AdmissionCfg:
    """Gateway admission control (shed-before-collapse): commands beyond
    the per-connection in-flight bound — or arriving while the broker
    backlog sits above the queue-depth watermark — are rejected with a
    retryable RESOURCE_EXHAUSTED instead of queueing until timeout."""

    enabled: bool = True
    max_inflight_per_connection: int = 1024
    queue_depth_high: int = 8192
    retry_after_ms: int = 50


@dataclasses.dataclass
class TracingCfg:
    """Record-lifecycle tracing (``zeebe_tpu/tracing/``): sampled
    commands are stamped at every serving-plane hop (gateway receive →
    … → exporter ack) and per-wave timelines are kept for Perfetto
    export (``tools/trace_report.py``). Sampling is deterministic per
    (seed, partition, arrival index) so chaos replays trace the same
    commands. ``enabled = false`` removes the span tracer entirely —
    the hot paths fall back to a single global read. The flight
    recorder (bounded event ring, dump-on-invariant-failure) is always
    on regardless of this section."""

    enabled: bool = True
    sample_rate: float = 0.01  # sampled fraction of commands per partition
    seed: int = 0
    per_partition_budget: int = 256  # live spans per partition (cap)
    commit_stall_ms: int = 5_000  # commit-latency watchdog threshold
    slow_wave_ms: int = 5_000  # slow-wave watchdog threshold


@dataclasses.dataclass
class GossipCfg:
    probe_interval_ms: int = 250
    probe_timeout_ms: int = 500
    probe_indirect_nodes: int = 2
    suspicion_multiplier: int = 5
    sync_interval_ms: int = 10_000


@dataclasses.dataclass
class RaftCfg:
    heartbeat_interval_ms: int = 250
    election_timeout_ms: int = 1_000


@dataclasses.dataclass
class TopicCfg:
    name: str = "default-topic"
    partitions: int = 1
    replication_factor: int = 1


@dataclasses.dataclass
class ExporterCfg:
    """One ``[[exporters]]`` entry (reference: the exporters section of
    zeebe.cfg.toml — id + className + per-exporter args). ``type`` is a
    built-in name (``jsonl``, ``metrics``, ``memory``) or a
    ``package.module:Class`` path; ``args`` passes through to
    ``Exporter.configure`` verbatim."""

    id: str = ""
    type: str = ""
    args: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class BrokerCfg:
    network: NetworkCfg = dataclasses.field(default_factory=NetworkCfg)
    data: DataCfg = dataclasses.field(default_factory=DataCfg)
    cluster: ClusterCfg = dataclasses.field(default_factory=ClusterCfg)
    threads: ThreadsCfg = dataclasses.field(default_factory=ThreadsCfg)
    metrics: MetricsCfg = dataclasses.field(default_factory=MetricsCfg)
    gossip: GossipCfg = dataclasses.field(default_factory=GossipCfg)
    raft: RaftCfg = dataclasses.field(default_factory=RaftCfg)
    engine: EngineCfg = dataclasses.field(default_factory=EngineCfg)
    scheduler: SchedulerCfg = dataclasses.field(default_factory=SchedulerCfg)
    mesh: MeshCfg = dataclasses.field(default_factory=MeshCfg)
    admission: AdmissionCfg = dataclasses.field(default_factory=AdmissionCfg)
    tracing: TracingCfg = dataclasses.field(default_factory=TracingCfg)
    topics: List[TopicCfg] = dataclasses.field(default_factory=list)
    exporters: List[ExporterCfg] = dataclasses.field(default_factory=list)


_SECTION_KEYS = {
    "network": NetworkCfg,
    "data": DataCfg,
    "cluster": ClusterCfg,
    "threads": ThreadsCfg,
    "metrics": MetricsCfg,
    "gossip": GossipCfg,
    "raft": RaftCfg,
    "engine": EngineCfg,
    "scheduler": SchedulerCfg,
    "mesh": MeshCfg,
    "admission": AdmissionCfg,
    "tracing": TracingCfg,
}

# env overrides (reference Environment: ZEEBE_* wins over the file)
_ENV_OVERRIDES = {
    "ZEEBE_HOST": ("network", "host", str),
    "ZEEBE_PORT_OFFSET": ("network", "port_offset", int),
    "ZEEBE_NODE_ID": ("cluster", "node_id", str),
    "ZEEBE_PARTITIONS": ("cluster", "partitions", int),
    "ZEEBE_REPLICATION_FACTOR": ("cluster", "replication_factor", int),
    "ZEEBE_BOOTSTRAP_EXPECT": ("cluster", "bootstrap_expect", int),
    "ZEEBE_CONTACT_POINTS": (
        "cluster",
        "initial_contact_points",
        lambda v: [p.strip() for p in v.split(",") if p.strip()],
    ),
    # singular alias: both spellings appear in reference deployments
    "ZEEBE_CONTACT_POINT": (
        "cluster",
        "initial_contact_points",
        lambda v: [p.strip() for p in v.split(",") if p.strip()],
    ),
    "ZEEBE_DATA_DIR": ("data", "directory", str),
    "ZEEBE_NATIVE_STORAGE": (
        "data",
        "native_storage",
        lambda v: v.strip().lower() in ("1", "true", "yes"),
    ),
    "ZEEBE_ENGINE_TYPE": ("engine", "type", str),
    "ZEEBE_METRICS_PORT": ("metrics", "port", int),
    "ZEEBE_SCHEDULER_ENABLED": (
        "scheduler",
        "enabled",
        lambda v: v.strip().lower() in ("1", "true", "yes"),
    ),
    "ZEEBE_ADMISSION_ENABLED": (
        "admission",
        "enabled",
        lambda v: v.strip().lower() in ("1", "true", "yes"),
    ),
    "ZEEBE_MESH_ENABLED": (
        "mesh",
        "enabled",
        lambda v: v.strip().lower() in ("1", "true", "yes"),
    ),
    "ZEEBE_MESH_DEVICES": ("mesh", "devices", int),
    "ZEEBE_MESH_SHARDED_PARTITIONS": ("mesh", "sharded_partitions", int),
    "ZEEBE_MESH_ROUTING": ("mesh", "routing", str),
    "ZEEBE_TRACING_ENABLED": (
        "tracing",
        "enabled",
        lambda v: v.strip().lower() in ("1", "true", "yes"),
    ),
    "ZEEBE_TRACING_SAMPLE_RATE": ("tracing", "sample_rate", float),
}


def _apply_section(cfg_obj: Any, table: Dict[str, Any], path: str) -> None:
    fields = {f.name: f for f in dataclasses.fields(cfg_obj)}
    for key, value in table.items():
        # accept camelCase (reference TOML style) and snake_case
        snake = "".join(
            "_" + c.lower() if c.isupper() else c for c in key
        ).lstrip("_")
        if snake not in fields:
            raise ValueError(f"unknown config key [{path}] {key!r}")
        setattr(cfg_obj, snake, value)


def load_config(
    path: Optional[str] = None,
    toml_text: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
) -> BrokerCfg:
    """Parse config (file path or literal text), then apply env overrides.
    Missing sections keep defaults (the reference ships a fully commented
    default file; every knob is optional) — but an explicitly named file
    that does not exist is an error: silently running on all-defaults is
    how a container ignores its own config."""
    cfg = BrokerCfg()
    data: Dict[str, Any] = {}
    if toml_text is not None:
        data = tomllib.loads(toml_text)
    elif path is not None:
        if not os.path.exists(path):
            raise FileNotFoundError(f"config file not found: {path}")
        with open(path, "rb") as f:
            data = tomllib.load(f)

    for section, table in data.items():
        if section == "topics":
            for entry in table:
                topic = TopicCfg()
                _apply_section(topic, entry, "topics")
                cfg.topics.append(topic)
            continue
        if section == "exporters":
            for entry in table:
                exporter = ExporterCfg()
                _apply_section(exporter, entry, "exporters")
                if not exporter.id or not exporter.type:
                    raise ValueError(
                        "[[exporters]] entries need both 'id' and 'type'"
                    )
                if any(e.id == exporter.id for e in cfg.exporters):
                    # two exporters sharing an id would share one
                    # replicated position entry — the faster one's ack
                    # overwrites the slower one's real progress and a
                    # restart silently skips the difference
                    raise ValueError(
                        f"duplicate exporter id {exporter.id!r}"
                    )
                cfg.exporters.append(exporter)
            continue
        target_cls = _SECTION_KEYS.get(section)
        if target_cls is None:
            raise ValueError(f"unknown config section [{section}]")
        _apply_section(getattr(cfg, section), table, section)

    environment = env if env is not None else os.environ
    for var, (section, attr, conv) in _ENV_OVERRIDES.items():
        if var in environment:
            setattr(getattr(cfg, section), attr, conv(environment[var]))

    cfg.network.apply_offset()
    # the metrics endpoint is a socket binding too: shift it with the rest
    # so several brokers can share one host (reference portOffset contract)
    if cfg.metrics.port:
        cfg.metrics.port += cfg.network.port_offset * 10
    return cfg
